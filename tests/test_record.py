"""Event recorder unit tests (ref: pkg/client/record/event.go +
events_cache.go): compression bumps count on identical events, and the
async wrapper posts in the background without stalling the caller."""

import threading
import time

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver.master import Master
from kubernetes_tpu.client.client import Client, InProcessTransport
from kubernetes_tpu.client.record import AsyncEventRecorder, EventRecorder


def mk_pod(name="p1"):
    return api.Pod(metadata=api.ObjectMeta(
        name=name, namespace="default", uid=f"uid-{name}"))


def setup():
    m = Master()
    client = Client(InProcessTransport(m))
    rec = EventRecorder(client, api.EventSource(component="test"))
    return client, rec


def test_eventf_posts_and_compresses():
    client, rec = setup()
    pod = mk_pod()
    rec.eventf(pod, "Scheduled", "placed on %s", "node-1")
    rec.eventf(pod, "Scheduled", "placed on %s", "node-1")
    evs = client.events("default").list().items
    assert len(evs) == 1
    assert evs[0].reason == "Scheduled"
    assert evs[0].count == 2          # compression, not a second object
    rec.eventf(pod, "Started", "container up")
    assert len(client.events("default").list().items) == 2


def test_async_recorder_posts_in_background():
    client, rec = setup()
    arec = AsyncEventRecorder(rec)
    try:
        for i in range(5):
            arec.eventf(mk_pod(f"p{i}"), "Scheduled", "ok")
        assert arec.flush(timeout=5.0)
        assert len(client.events("default").list().items) == 5
    finally:
        arec.stop()


def test_async_recorder_never_blocks_caller_on_slow_posts():
    client, rec = setup()
    gate = threading.Event()
    orig = rec.eventf

    def slow_eventf(*a, **kw):
        gate.wait(5.0)
        return orig(*a, **kw)
    rec.eventf = slow_eventf
    arec = AsyncEventRecorder(rec)
    try:
        t0 = time.perf_counter()
        for i in range(10):
            arec.eventf(mk_pod(f"s{i}"), "Scheduled", "ok")
        assert time.perf_counter() - t0 < 0.5    # enqueue only
        gate.set()
        assert arec.flush(timeout=10.0)
        assert len(client.events("default").list().items) == 10
    finally:
        gate.set()
        arec.stop()


def test_async_recorder_flush_covers_in_flight_item():
    client, rec = setup()
    release = threading.Event()
    posted = []
    orig = rec.eventf

    def gated(*a, **kw):
        release.wait(5.0)
        out = orig(*a, **kw)
        posted.append(out)
        return out
    rec.eventf = gated
    arec = AsyncEventRecorder(rec)
    try:
        arec.eventf(mk_pod("only"), "Scheduled", "ok")
        time.sleep(0.1)   # worker has popped it; queue is empty, post gated
        assert not arec.flush(timeout=0.3)   # must NOT claim done
        release.set()
        assert arec.flush(timeout=5.0)
        assert len(posted) == 1
    finally:
        release.set()
        arec.stop()


def test_async_recorder_drops_oldest_under_storm():
    client, rec = setup()
    gate = threading.Event()
    orig = rec.eventf
    rec.eventf = lambda *a, **kw: (gate.wait(10.0), orig(*a, **kw))[1]
    arec = AsyncEventRecorder(rec, max_queue=8)
    try:
        for i in range(100):                  # storm >> queue bound
            arec.eventf(mk_pod(f"x{i}"), "Scheduled", "ok")
        gate.set()
        assert arec.flush(timeout=10.0)
        n = len(client.events("default").list().items)
        assert n <= 10                        # bounded: old events shed
    finally:
        gate.set()
        arec.stop()


def test_async_recorder_stop_is_idempotent_and_rejects_after():
    client, rec = setup()
    arec = AsyncEventRecorder(rec)
    arec.stop()
    arec.stop()
    arec.eventf(mk_pod(), "Scheduled", "ok")   # no-op, no crash


def test_recorder_cache_is_lru_bounded():
    """The compression cache must not grow one entry per unique message
    forever (50k-pod churn embeds a distinct pod name in every message):
    bounded LRU, eviction costs only compression."""
    client, rec = setup()
    rec._max_cache = 8
    for i in range(50):
        rec.eventf(mk_pod(f"p{i}"), "Scheduled", "assigned %s", f"p{i}")
    assert len(rec._cache) == 8
    # the newest keys survived; re-posting one bumps count (still cached)
    rec.eventf(mk_pod("p49"), "Scheduled", "assigned %s", "p49")
    evs = {e.involved_object.name: e
           for e in client.events("default").list().items}
    assert evs["p49"].count == 2
    # an evicted key posts a fresh object instead of bumping (count 1 on
    # the new event), and the cache stays at the bound
    rec.eventf(mk_pod("p0"), "Scheduled", "assigned %s", "p0")
    assert len(rec._cache) == 8
    p0_events = [e for e in client.events("default").list().items
                 if e.involved_object.name == "p0"]
    assert [e.count for e in p0_events] == [1, 1]


def test_recorder_cache_lru_touch_on_hit():
    """A hot key re-used between inserts is the LAST evicted (true LRU,
    not FIFO): the scheduler's one steady compressed event survives a
    storm of one-off messages."""
    client, rec = setup()
    rec._max_cache = 4
    hot = mk_pod("hot")
    rec.eventf(hot, "Scheduled", "steady")
    for i in range(10):
        rec.eventf(mk_pod(f"cold{i}"), "Scheduled", "one-off %d", i)
        rec.eventf(hot, "Scheduled", "steady")     # touch: moves to MRU
    evs = [e for e in client.events("default").list().items
           if e.involved_object.name == "hot"]
    assert len(evs) == 1 and evs[0].count == 11


def test_async_recorder_posted_and_dropped_counters():
    """The dropped count is a registered metric family now, not a bare
    attribute: queue_full shedding and rate_limited rejections land in
    event_recorder_dropped_total{reason}, successes in
    event_recorder_posted_total — visible to /metrics, flightrec, and
    the churn record's disclosure."""
    from kubernetes_tpu.util import metrics
    mx = metrics.event_recorder_metrics()
    posted0 = mx.posted.value()
    qfull0 = mx.dropped.value("queue_full")
    rl0 = mx.dropped.value("rate_limited")

    client, rec = setup()
    gate = threading.Event()
    orig = rec.eventf
    rec.eventf = lambda *a, **kw: (gate.wait(10.0), orig(*a, **kw))[1]
    arec = AsyncEventRecorder(rec, max_queue=8)
    try:
        for i in range(30):                  # storm >> queue bound
            arec.eventf(mk_pod(f"m{i}"), "Scheduled", "ok")
        gate.set()
        assert arec.flush(timeout=10.0)
        posted = mx.posted.value() - posted0
        shed = mx.dropped.value("queue_full") - qfull0
        assert posted >= 1 and shed >= 1
        assert posted + shed >= 30 - 1       # worker may hold one in flight
    finally:
        gate.set()
        arec.stop()

    client, rec = setup()
    arec = AsyncEventRecorder(rec, qps=0.0001, burst=1)
    try:
        arec.eventf(mk_pod("a"), "Scheduled", "ok")
        arec.eventf(mk_pod("b"), "Scheduled", "ok")   # token bucket empty
        assert arec.flush(timeout=5.0)
        assert mx.dropped.value("rate_limited") - rl0 == 1
        assert arec.dropped == 1             # legacy attribute still kept
    finally:
        arec.stop()


def test_async_recorder_event_qps_token_bucket():
    """Client-side event rate limit (the successor codebases' --event-qps):
    a burst beyond the bucket is dropped without blocking the caller, and
    tokens refill over time."""
    client, rec = setup()
    arec = AsyncEventRecorder(rec, qps=10.0, burst=5)
    try:
        for i in range(50):
            arec.eventf(mk_pod(f"q{i}"), "Scheduled", "ok")
        assert arec.flush(timeout=10.0)
        posted = len(client.events("default").list().items)
        assert posted <= 6          # burst of 5 (+1 refill at most)
        assert arec.dropped >= 44
        time.sleep(0.35)            # ~3 tokens refill at 10 qps
        arec.eventf(mk_pod("late"), "Scheduled", "ok")
        assert arec.flush(timeout=10.0)
        assert len(client.events("default").list().items) > posted
    finally:
        arec.stop()
