"""End-to-end authn/authz matrix through the live HTTP stack.

The shape of the reference's test/integration/auth_test.go: a table of
(credential, verb, path, body) -> expected status, driven through a real
APIServer with a union authenticator (token file with groups + basic
auth) in front and an ABAC policy file behind, covering every registry,
every subresource, watch, and the unauthenticated/bad-credential rows.

Personas (one ABAC line each, ref: pkg/auth/authorizer/abac):
  alice   superuser (bare user line matches everything)
  bob     read-only everywhere ("readonly": true)
  carol   full access, but only in namespace "project1"
  dave    pods only, any namespace, any verb
  erin    events read-only (resource+readonly combine)
  ctrl    member of group "controllers" -> group line grants all
  mallory authenticated, matches NO line -> everything 403
  (none)  no credentials -> 401 everywhere
"""

import http.client
import json

import pytest

from kubernetes_tpu import auth as authpkg
from kubernetes_tpu.apiserver.http import APIServer
from kubernetes_tpu.apiserver.master import Master, MasterConfig
from kubernetes_tpu.auth.abac import ABACAuthorizer

TOKENS = "\n".join([
    "tok-alice,alice,u1",
    "tok-bob,bob,u2",
    "tok-carol,carol,u3",
    "tok-dave,dave,u4",
    "tok-erin,erin,u5",
    'tok-ctrl,ctrl,u6,"controllers,system"',
    "tok-mallory,mallory,u7",
])

POLICY = "\n".join([
    "# superuser",
    '{"user": "alice"}',
    '{"user": "bob", "readonly": true}',
    '{"user": "carol", "namespace": "project1"}',
    '{"user": "dave", "resource": "pods"}',
    '{"user": "erin", "resource": "events", "readonly": true}',
    '{"group": "controllers"}',
])


def pod(name, ns="default", host=""):
    spec = {"containers": [{"name": "c", "image": "img"}]}
    if host:
        spec["host"] = host
    return json.dumps({"kind": "Pod", "apiVersion": "v1",
                       "metadata": {"name": name, "namespace": ns},
                       "spec": spec})


def obj(kind, name, ns=None, **extra):
    meta = {"name": name}
    if ns:
        meta["namespace"] = ns
    return json.dumps({"kind": kind, "apiVersion": "v1",
                       "metadata": meta, **extra})


# The matrix. Paths are v1; METHOD "" means GET. Expected codes:
# 401 unauthenticated, 403 denied by policy, 2xx allowed (404 also proves
# an ALLOW: authz passed, object merely absent — same convention as the
# reference's matrix, which distinguishes "deny" only by 403).
NS = "/api/v1/namespaces"
ROWS = [
    # --- no credentials / bad credentials -> 401 regardless of path
    (None, "GET", f"{NS}/default/pods", None, 401),
    (None, "POST", f"{NS}/default/pods", pod("x"), 401),
    ("bad-token", "GET", f"{NS}/default/pods", None, 401),
    ("bad-basic", "GET", f"{NS}/default/pods", None, 401),

    # --- alice: superuser everywhere, every registry
    ("tok-alice", "POST", f"{NS}/default/pods", pod("a1"), 201),
    ("tok-alice", "GET", f"{NS}/default/pods", None, 200),
    ("tok-alice", "GET", f"{NS}/default/pods/a1", None, 200),
    ("tok-alice", "POST", f"{NS}/default/services",
     obj("Service", "svc-a", "default", spec={"port": 80}), 201),
    ("tok-alice", "POST", f"{NS}/default/replicationcontrollers",
     obj("ReplicationController", "rc-a", "default",
         spec={"replicas": 0, "selector": {"app": "x"}}), 201),
    ("tok-alice", "POST", f"{NS}/default/endpoints",
     obj("Endpoints", "ep-a", "default"), 201),
    ("tok-alice", "POST", "/api/v1/nodes",
     obj("Node", "node-a"), 201),
    ("tok-alice", "GET", "/api/v1/nodes", None, 200),
    ("tok-alice", "POST", "/api/v1/namespaces",
     obj("Namespace", "project1"), 201),
    ("tok-alice", "POST", f"{NS}/default/secrets",
     obj("Secret", "sec-a", "default"), 201),
    ("tok-alice", "POST", f"{NS}/default/limitranges",
     obj("LimitRange", "lr-a", "default"), 201),
    ("tok-alice", "POST", f"{NS}/default/resourcequotas",
     obj("ResourceQuota", "rq-a", "default"), 201),
    ("tok-alice", "POST", f"{NS}/default/events",
     obj("Event", "ev-a", "default", reason="Tested"), 201),
    # subresources: binding, pods/status, resourcequotas/status
    ("tok-alice", "POST", f"{NS}/default/pods/a1/binding",
     json.dumps({"kind": "Binding", "apiVersion": "v1",
                 "metadata": {"name": "a1", "namespace": "default"},
                 "podName": "a1", "host": "node-a"}), 201),
    ("tok-alice", "PUT", f"{NS}/default/pods/a1/status",
     pod("a1", host="node-a"), 200),
    ("tok-alice", "GET", "/api/v1/watch/pods?namespace=default", None, 200),
    ("tok-alice", "DELETE", f"{NS}/default/pods/a1", None, 200),

    # --- bob: read-only everywhere
    ("tok-bob", "GET", f"{NS}/default/pods", None, 200),
    ("tok-bob", "GET", "/api/v1/nodes", None, 200),
    ("tok-bob", "GET", f"{NS}/default/services", None, 200),
    ("tok-bob", "GET", f"{NS}/default/secrets", None, 200),
    ("tok-bob", "GET", "/api/v1/watch/pods?namespace=default", None, 200),
    ("tok-bob", "GET", f"{NS}/project1/pods", None, 200),
    ("tok-bob", "POST", f"{NS}/default/pods", pod("b1"), 403),
    ("tok-bob", "PUT", f"{NS}/default/pods/a1", pod("a1"), 403),
    ("tok-bob", "DELETE", f"{NS}/default/pods/a1", None, 403),
    ("tok-bob", "POST", "/api/v1/nodes", obj("Node", "node-b"), 403),
    ("tok-bob", "POST", f"{NS}/default/pods/a1/binding",
     json.dumps({"kind": "Binding", "apiVersion": "v1",
                 "metadata": {"name": "a1", "namespace": "default"},
                 "podName": "a1", "host": "node-a"}), 403),
    ("tok-bob", "DELETE", "/api/v1/namespaces/project1", None, 403),

    # --- carol: anything, but only inside namespace project1
    ("tok-carol", "POST", f"{NS}/project1/pods", pod("c1", "project1"), 201),
    ("tok-carol", "GET", f"{NS}/project1/pods", None, 200),
    ("tok-carol", "GET", f"{NS}/project1/pods/c1", None, 200),
    ("tok-carol", "POST", f"{NS}/project1/services",
     obj("Service", "svc-c", "project1", spec={"port": 81}), 201),
    ("tok-carol", "DELETE", f"{NS}/project1/pods/c1", None, 200),
    ("tok-carol", "GET", f"{NS}/default/pods", None, 403),
    ("tok-carol", "POST", f"{NS}/default/pods", pod("c2"), 403),
    ("tok-carol", "GET", "/api/v1/nodes", None, 403),  # cluster-scoped: ns ""
    ("tok-carol", "POST", "/api/v1/namespaces",
     obj("Namespace", "project2"), 403),

    # --- dave: pods in any namespace, any verb; nothing else
    ("tok-dave", "POST", f"{NS}/default/pods", pod("d1"), 201),
    ("tok-dave", "POST", f"{NS}/project1/pods", pod("d2", "project1"), 201),
    ("tok-dave", "GET", f"{NS}/default/pods/d1", None, 200),
    ("tok-dave", "DELETE", f"{NS}/default/pods/d1", None, 200),
    ("tok-dave", "GET", f"{NS}/default/services", None, 403),
    ("tok-dave", "GET", "/api/v1/nodes", None, 403),
    ("tok-dave", "POST", f"{NS}/default/events",
     obj("Event", "ev-d", "default"), 403),
    ("tok-dave", "GET", f"{NS}/default/resourcequotas", None, 403),

    # --- erin: events read-only — resource AND readonly must both match
    ("tok-erin", "GET", f"{NS}/default/events", None, 200),
    ("tok-erin", "POST", f"{NS}/default/events",
     obj("Event", "ev-e", "default"), 403),
    ("tok-erin", "GET", f"{NS}/default/pods", None, 403),

    # --- ctrl: allowed via group membership line
    ("tok-ctrl", "POST", f"{NS}/default/pods", pod("g1"), 201),
    ("tok-ctrl", "DELETE", f"{NS}/default/pods/g1", None, 200),
    ("tok-ctrl", "GET", "/api/v1/nodes", None, 200),
    ("tok-ctrl", "POST", "/api/v1/nodes", obj("Node", "node-g"), 201),

    # --- basic auth hits the same matrix (bob via password file)
    ("basic-bob", "GET", f"{NS}/default/pods", None, 200),
    ("basic-bob", "POST", f"{NS}/default/pods", pod("bb"), 403),

    # --- mallory: authenticates fine, matches no policy line
    ("tok-mallory", "GET", f"{NS}/default/pods", None, 403),
    ("tok-mallory", "POST", f"{NS}/default/pods", pod("m1"), 403),
    ("tok-mallory", "GET", "/api/v1/nodes", None, 403),
    ("tok-mallory", "DELETE", f"{NS}/default/pods/a1", None, 403),
]


@pytest.fixture(scope="module")
def server():
    authenticator = authpkg.UnionAuthenticator(
        authpkg.load_token_file(TOKENS),
        authpkg.BasicAuthAuthenticator(
            authpkg.load_password_file("pw-bob,bob,u2")),
    )
    master = Master(MasterConfig(authorizer=ABACAuthorizer.from_text(POLICY)))
    srv = APIServer(master, authenticator=authenticator).start()
    yield srv
    srv.stop()


def _headers(cred):
    import base64
    if cred is None:
        return {}
    if cred == "bad-token":
        return {"Authorization": "Bearer nope"}
    if cred == "bad-basic":
        raw = base64.b64encode(b"bob:wrong").decode()
        return {"Authorization": f"Basic {raw}"}
    if cred == "basic-bob":
        raw = base64.b64encode(b"bob:pw-bob").decode()
        return {"Authorization": f"Basic {raw}"}
    return {"Authorization": f"Bearer {cred}"}


@pytest.mark.parametrize("cred,method,path,body,want",
                         ROWS, ids=[f"{i:02d}-{r[0]}-{r[1]}-{r[2].split('?')[0].rsplit('/', 1)[-1]}"
                                    for i, r in enumerate(ROWS)])
def test_matrix(server, cred, method, path, body, want):
    conn = http.client.HTTPConnection("127.0.0.1", server.port)
    headers = _headers(cred)
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        got = resp.status
        if "watch" not in path:
            resp.read()
    finally:
        conn.close()
    # 404 after an authz pass still demonstrates ALLOW; only compare the
    # deny/unauth codes exactly and treat 2xx/404/409 as "allowed"
    if want in (401, 403):
        assert got == want, f"{cred} {method} {path}: got {got}, want {want}"
    else:
        assert got in (want, 404, 409), \
            f"{cred} {method} {path}: got {got}, want allow ({want})"
