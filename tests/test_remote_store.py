"""Remote store: the kube-store server + RemoteStore client.

The topology parity piece (ref: DESIGN.md:17-40 — etcd is its own
process; every apiserver shares it): RemoteStore must behave exactly
like MemStore through the same contract, including watch resume
semantics, CAS conflicts as typed errors, and the batched wave-commit
ops. The final test drives a full apiserver + a second worker sharing
one listen port (SO_REUSEPORT) against one store process, the
multi-worker deployment hack/churn_mp.py --apiservers N uses.
"""

import threading
import time

import pytest

from kubernetes_tpu.storage.memstore import (
    ErrCASConflict,
    ErrIndexOutdated,
    ErrKeyExists,
    ErrKeyNotFound,
    MemStore,
)
from kubernetes_tpu.storage.remote import RemoteStore, StoreServer


@pytest.fixture()
def remote():
    srv = StoreServer(MemStore()).start()
    try:
        yield RemoteStore(srv.address)
    finally:
        srv.stop()


def test_crud_and_errors(remote):
    kv = remote.create("/r/a", "1")
    assert (kv.key, kv.value, kv.modified_index) == ("/r/a", "1", 2)
    with pytest.raises(ErrKeyExists):
        remote.create("/r/a", "x")
    kv2 = remote.compare_and_swap("/r/a", "2", kv.modified_index)
    assert kv2.modified_index == 3 and kv2.created_index == 2
    with pytest.raises(ErrCASConflict):
        remote.compare_and_swap("/r/a", "x", 2)
    with pytest.raises(ErrKeyNotFound):
        remote.get("/r/missing")
    with pytest.raises(ErrKeyNotFound):
        remote.delete("/r/missing")
    kvs, index = remote.list("/r")
    assert [k.value for k in kvs] == ["2"] and index == 3
    assert remote.index == 3
    assert remote.delete("/r/a").value == "2"


def test_get_many_and_cas_many(remote):
    a = remote.create("/m/a", "1")
    b = remote.create("/m/b", "1")
    got = remote.get_many(["/m/a", "/m/zz", "/m/b"])
    assert got[0].value == "1" and got[1] is None and got[2].value == "1"
    out = remote.compare_and_swap_many([
        ("/m/a", "2", a.modified_index),
        ("/m/b", "2", 999),          # stale -> conflict
        ("/m/c", "2", 1),            # absent -> not found
    ])
    assert out[0].modified_index == 4
    assert isinstance(out[1], ErrCASConflict)
    assert isinstance(out[2], ErrKeyNotFound)


def test_watch_stream_and_resume(remote):
    w = remote.watch("/w", from_index=0)
    remote.create("/w/a", "1")
    remote.set("/w/a", "2")
    it = iter(w)
    e1, e2 = next(it), next(it)
    assert e1.object.action == "create" and e1.object.kv.value == "1"
    assert e2.object.action == "set" and e2.object.prev_kv.value == "1"
    w.stop()
    # resume from index replays history after that index
    w2 = remote.watch("/w", from_index=e1.object.index)
    e = next(iter(w2))
    assert e.object.index == e1.object.index + 1 and e.object.kv.value == "2"
    w2.stop()


def test_watch_outdated_index_raises(remote):
    for i in range(MemStore.HISTORY_WINDOW + 10):
        remote.set("/h/k", str(i))
    with pytest.raises(ErrIndexOutdated):
        remote.watch("/h", from_index=1)


def test_client_watch_stop_releases_server_watcher(remote):
    w = remote.watch("/s", from_index=0)
    remote.create("/s/a", "1")
    assert next(iter(w)).object.kv.value == "1"
    w.stop()
    time.sleep(0.2)
    # a stopped remote watcher must not leak server-side: new writes
    # still succeed and a fresh watch sees them
    remote.create("/s/b", "1")
    w2 = remote.watch("/s", from_index=0)
    remote.create("/s/c", "1")
    assert next(iter(w2)).object.key == "/s/c"
    w2.stop()


def test_concurrent_clients_share_indices(remote):
    # two client objects (distinct connections) interleave writes; the
    # store's global index stays monotonic across them
    other = RemoteStore(f"127.0.0.1:{remote._addr[1]}")
    seen = []
    lock = threading.Lock()

    def writer(store, tag):
        for i in range(50):
            kv = store.set(f"/c/{tag}-{i}", "x")
            with lock:
                seen.append(kv.modified_index)

    t1 = threading.Thread(target=writer, args=(remote, "a"))
    t2 = threading.Thread(target=writer, args=(other, "b"))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert len(seen) == 100 and len(set(seen)) == 100
    assert max(seen) == remote.index


def test_apiserver_workers_share_store_via_reuseport():
    """Two apiserver workers on ONE port (SO_REUSEPORT), one kube-store:
    an object created through the shared port is visible no matter which
    worker serves the read, and resourceVersions are globally ordered."""
    import http.client
    import json

    from kubernetes_tpu.apiserver.http import APIServer
    from kubernetes_tpu.apiserver.master import Master, MasterConfig

    store_srv = StoreServer(MemStore()).start()
    workers = []
    try:
        w0 = APIServer(Master(MasterConfig(
            store=RemoteStore(store_srv.address))),
            port=0, reuse_port=True).start()
        workers.append(w0)
        port = w0.port
        w1 = APIServer(Master(MasterConfig(
            store=RemoteStore(store_srv.address))),
            port=port, reuse_port=True).start()
        workers.append(w1)

        def do(method, path, body=None):
            conn = http.client.HTTPConnection("127.0.0.1", port)
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            out = json.loads(resp.read())
            conn.close()
            return resp.status, out

        rvs = set()
        for i in range(8):  # fresh connection each time -> both workers
            code, out = do("POST", "/api/v1/namespaces/default/pods",
                           json.dumps({
                               "kind": "Pod", "apiVersion": "v1",
                               "metadata": {"name": f"shared-{i}",
                                            "namespace": "default"},
                               "spec": {"containers": [
                                   {"name": "c", "image": "i"}]}}))
            assert code == 201, out
            rvs.add(out["metadata"]["resourceVersion"])
        assert len(rvs) == 8  # globally unique revisions across workers
        code, out = do("GET", "/api/v1/namespaces/default/pods")
        assert code == 200 and len(out["items"]) == 8
    finally:
        for w in workers:
            w.stop()
        store_srv.stop()


def test_watch_survives_idle_longer_than_call_timeout():
    """The stream socket must carry NO timeout: a quiet prefix can sit
    idle far longer than the pooled-call socket timeout, and a timed-out
    recv would silently close every downstream watcher (regression:
    watch streams died after call_timeout of quiet). Pinned for real by
    shrinking the injectable timeout below the idle period."""
    srv = StoreServer(MemStore()).start()
    try:
        rs = RemoteStore(srv.address, call_timeout_s=0.5)
        w = rs.watch("/idle", from_index=0)
        time.sleep(1.6)               # > 3x the call timeout, zero events
        rs.create("/idle/k", "1")     # stream must still be alive
        ev = next(iter(w))
        assert ev.object.kv.value == "1"
        w.stop()
    finally:
        srv.stop()


def test_storeserver_sigkill_restart_clients_and_data_recover(tmp_path):
    """kube-store crash-restart: SIGKILL the store process (no shutdown
    hooks), restart it on the same port + --data-dir, and the world
    resumes — data and resourceVersions intact (WAL+snapshot), pooled
    client connections reconnect transparently on their next call, and a
    severed watch stream ends cleanly (the Reflector re-list contract)
    instead of hanging. The etcd-restart scenario for the remote
    topology (ref: the reference's components ride out etcd restarts by
    list-then-watch resume, pkg/client/cache/reflector.go:83)."""
    import os
    import socket as socket_mod
    import subprocess
    import sys

    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    data_dir = str(tmp_path / "store-data")

    def free_port():
        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    port = free_port()

    def spawn():
        p = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.cmd.storeserver",
             "--port", str(port), "--data-dir", data_dir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        assert "listening" in p.stdout.readline()
        return p

    proc = spawn()
    try:
        rs = RemoteStore(f"127.0.0.1:{port}")
        kv1 = rs.create("/reg/pods/default/a", '{"spec": 1}')
        kv2 = rs.set("/reg/pods/default/b", '{"spec": 2}')
        w = rs.watch("/reg", from_index=0)

        proc.kill()              # SIGKILL: no shutdown hooks run
        proc.wait(timeout=10)
        # the severed stream must END (close), not hang the consumer
        ended = [False]

        def drain():
            for _ in w:
                pass
            ended[0] = True

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        t.join(timeout=10)
        assert ended[0], "watch did not close on store death"

        proc = spawn()           # restart on the same port + data dir
        # pooled connection is dead; the next call reconnects and reads
        # the WAL-recovered state with resourceVersions preserved
        got = rs.get("/reg/pods/default/a")
        assert got.value == '{"spec": 1}'
        assert got.modified_index == kv1.modified_index
        kvs, index = rs.list("/reg")
        assert {k.value for k in kvs} == {'{"spec": 1}', '{"spec": 2}'}
        assert index >= kv2.modified_index
        # new writes continue the monotonic index past the pre-crash one
        kv3 = rs.set("/reg/pods/default/c", '{"spec": 3}')
        assert kv3.modified_index > kv2.modified_index
        # and a fresh watch resumes from a pre-crash revision
        w2 = rs.watch("/reg", from_index=kv2.modified_index)
        assert next(iter(w2)).object.kv.value == '{"spec": 3}'
        w2.stop()
    finally:
        proc.kill()
