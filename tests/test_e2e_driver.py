"""The e2e suite driver (hack/e2e.py) against a live standalone cluster.

Mirrors the reference's hack/e2e.go entry: boot a real cluster, run the
suites over real HTTP, require every suite green. This is the one test
that exercises the whole stack the way an operator would — kubeconfig,
kubectl subprocesses, HTTP watch streams — rather than through in-process
seams.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.timeout(240)
def test_e2e_driver_all_suites_pass(tmp_path):
    env = dict(os.environ,
               PYTHONPATH=REPO + (os.pathsep + os.environ["PYTHONPATH"]
                                  if os.environ.get("PYTHONPATH") else ""),
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "hack", "e2e.py"),
         "--up", "--port", "18611"],
        capture_output=True, text=True, env=env, timeout=220,
        cwd=str(tmp_path))
    assert out.returncode == 0, f"stdout:\n{out.stdout}\nstderr:\n{out.stderr}"
    assert "ALL SUITES PASSED" in out.stdout
