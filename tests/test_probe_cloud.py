"""ProbeCloud — the discovery-command cloud provider.

Second real provider through the same seam as InventoryCloud, from the
live-query angle (ref: the reference's GCE/vagrant/ovirt providers poll
an external system, pkg/cloudprovider/cloud.go:26-80). The probe here
is a real subprocess printing JSON; the tests cover TTL-cached refresh,
degradation to the stale snapshot on probe failure, the never-readable
error, and the Clusters facet the inventory provider doesn't implement.
"""

import json
import sys

import pytest

from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.cloudprovider import get_provider
from kubernetes_tpu.cloudprovider.probe import ProbeCloud, ProbeError

INVENTORY = {
    "zone": {"failure_domain": "cell-a", "region": "dc1"},
    "instances": [
        {"name": "w1", "addresses": ["10.1.0.1"], "cpu": "4",
         "memory": "8Gi"},
        {"name": "w2", "addresses": ["10.1.0.2"]},
    ],
    "clusters": {"names": ["alpha", "beta"],
                 "masters": {"alpha": "10.1.0.100", "beta": "10.1.0.200"}},
}


def probe_cmd_printing(data) -> list:
    return [sys.executable, "-c",
            f"import sys; sys.stdout.write({json.dumps(json.dumps(data))!s})"]


def probe_cmd_from_file(path) -> list:
    # each run re-reads the file — lets tests change what discovery finds
    return [sys.executable, "-c",
            f"import sys; sys.stdout.write(open({str(path)!r}).read())"]


def test_probe_discovers_instances_zones_clusters():
    cloud = ProbeCloud(probe_cmd_printing(INVENTORY))
    inst = cloud.instances()
    assert inst.list_instances() == ["w1", "w2"]
    assert inst.list_instances("w1") == ["w1"]
    assert inst.node_addresses("w1") == ["10.1.0.1"]
    spec = inst.get_node_resources("w1")
    assert spec.capacity["cpu"] == Quantity("4")
    assert inst.get_node_resources("w2") is None
    z = cloud.zones().get_zone()
    assert (z.failure_domain, z.region) == ("cell-a", "dc1")
    c = cloud.clusters()
    assert c.list_clusters() == ["alpha", "beta"]
    assert c.master("alpha") == "10.1.0.100"
    with pytest.raises(KeyError):
        c.master("nope")


def test_probe_ttl_refresh_picks_up_changes(tmp_path):
    src = tmp_path / "inv.json"
    src.write_text(json.dumps(INVENTORY))
    t = [0.0]
    cloud = ProbeCloud(probe_cmd_from_file(src), ttl_s=10.0,
                       clock=lambda: t[0])
    assert cloud.instances().list_instances() == ["w1", "w2"]

    changed = dict(INVENTORY, instances=[{"name": "w3"}])
    src.write_text(json.dumps(changed))
    # inside the TTL: cached snapshot still served (no re-probe)
    t[0] = 5.0
    assert cloud.instances().list_instances() == ["w1", "w2"]
    # past the TTL: discovery re-runs and sees the new world
    t[0] = 11.0
    assert cloud.instances().list_instances() == ["w3"]


def test_probe_failure_degrades_to_stale_not_empty(tmp_path):
    src = tmp_path / "inv.json"
    src.write_text(json.dumps(INVENTORY))
    t = [0.0]
    cloud = ProbeCloud(probe_cmd_from_file(src), ttl_s=1.0,
                       clock=lambda: t[0])
    assert cloud.instances().list_instances() == ["w1", "w2"]

    src.write_text("{ torn json")          # discovery backend flaps
    t[0] = 2.0
    assert cloud.instances().list_instances() == ["w1", "w2"]  # stale, not []

    src.unlink()                           # command itself now fails
    t[0] = 4.0
    assert cloud.instances().list_instances() == ["w1", "w2"]

    src.write_text(json.dumps(INVENTORY))  # backend recovers
    t[0] = 6.0
    assert cloud.instances().node_addresses("w2") == ["10.1.0.2"]


def test_probe_never_readable_raises():
    cloud = ProbeCloud([sys.executable, "-c", "import sys; sys.exit(3)"])
    with pytest.raises(ProbeError):
        cloud.instances()


def test_probe_registered_in_provider_registry(tmp_path, monkeypatch):
    src = tmp_path / "inv.json"
    src.write_text(json.dumps(INVENTORY))
    # the registry factory reads KTPU_CLOUD_PROBE_CMD (shlex-split)
    monkeypatch.setenv(
        "KTPU_CLOUD_PROBE_CMD",
        f'{sys.executable} -c "import sys; '
        f"sys.stdout.write(open('{src}').read())\"")
    cloud = get_provider("probe")
    assert cloud.instances().list_instances() == ["w1", "w2"]


def test_probe_malformed_schema_degrades_to_stale(tmp_path):
    """Exit-0 probe printing structurally-broken JSON (instance without
    name, zone as a string) must degrade to the stale snapshot, not
    crash the sync tick (regression)."""
    src = tmp_path / "inv.json"
    src.write_text(json.dumps(INVENTORY))
    t = [0.0]
    cloud = ProbeCloud(probe_cmd_from_file(src), ttl_s=1.0,
                       clock=lambda: t[0])
    assert cloud.instances().list_instances() == ["w1", "w2"]
    src.write_text(json.dumps({"zone": "not-a-dict",
                               "instances": [{"host": "no-name-key"}]}))
    t[0] = 2.0
    assert cloud.instances().list_instances() == ["w1", "w2"]
    assert cloud.clusters().list_clusters() == ["alpha", "beta"]
