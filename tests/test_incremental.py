"""IncrementalEncoder vs full encoder — decision equivalence under churn.

The incremental encoder's arrays differ from the full encoder's (sticky
vocabulary order, pow-2 padding, resident group rows), but the DECISIONS the
solver derives from them must be identical for every wave, and both must
match the serial oracle. Fuzzed over multi-wave churn traces with pod
creates/deletes, binds, node-label dependence, services, gangs, and extended
resources.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.models import gang
from kubernetes_tpu.models.batch_solver import (
    decisions_to_names,
    snapshot_to_inputs,
    solve,
)
from kubernetes_tpu.models.incremental import IncrementalEncoder
from kubernetes_tpu.models.oracle import solve_serial
from kubernetes_tpu.models.policy import BatchPolicy
from kubernetes_tpu.models.snapshot import encode_snapshot


def mk_node(name, cpu_m=2000, mem=4 << 30, labels=None, extra=None):
    cap = {"cpu": Quantity(f"{cpu_m}m"), "memory": Quantity(mem)}
    for k, v in (extra or {}).items():
        cap[k] = Quantity(v)
    return api.Node(metadata=api.ObjectMeta(name=name, labels=labels or {}),
                    spec=api.NodeSpec(capacity=cap))


_uid = [0]


def mk_pod(name, ns="default", cpu_m=0, mem=0, host="", labels=None,
           node_selector=None, host_ports=(), pds=(), extra=None, group=None):
    limits = {}
    if cpu_m:
        limits["cpu"] = Quantity(f"{cpu_m}m")
    if mem:
        limits["memory"] = Quantity(mem)
    for k, v in (extra or {}).items():
        limits[k] = Quantity(v)
    ann = {}
    if group:
        ann[gang.GANG_NAME_ANNOTATION] = group
    _uid[0] += 1
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns,
                                uid=f"uid-{_uid[0]}", labels=labels or {},
                                annotations=ann),
        spec=api.PodSpec(
            host=host, node_selector=node_selector or {},
            containers=[api.Container(
                name="c", image="i",
                ports=[api.ContainerPort(container_port=80 + i, host_port=p)
                       for i, p in enumerate(host_ports)],
                resources=api.ResourceRequirements(limits=limits))],
            volumes=[api.Volume(name=f"v{i}", source=api.VolumeSource(
                gce_persistent_disk=api.GCEPersistentDiskVolumeSource(
                    pd_name=pd))) for i, pd in enumerate(pds)]),
        status=api.PodStatus(host=host))


def assert_wave_equivalent(enc, nodes, existing, pending, services=()):
    """Incremental decisions == full-encode decisions == serial oracle."""
    inc = enc.encode(nodes, existing, pending, services)
    chosen_inc, _ = solve(inc)
    got = decisions_to_names(inc, chosen_inc)
    full = encode_snapshot(nodes, existing, pending, services,
                           policy=enc.policy)
    chosen_full, _ = solve(full)
    want = decisions_to_names(full, chosen_full)
    assert got == want, f"incremental={got}\nfull       ={want}"
    serial = solve_serial(nodes, existing, pending, services, gangs=True)
    assert want == serial, f"batch={want}\nserial={serial}"
    return got


def test_single_wave_matches_full():
    enc = IncrementalEncoder()
    nodes = [mk_node(f"n{i}") for i in range(4)]
    pending = [mk_pod(f"p{i}", cpu_m=100, mem=64 << 20) for i in range(6)]
    assert_wave_equivalent(enc, nodes, [], pending)


def test_pod_axis_padding_is_inert():
    """Wave sizes 1..9 share pow-2 buckets; padding rows never place."""
    enc = IncrementalEncoder()
    nodes = [mk_node(f"n{i}") for i in range(3)]
    existing = []
    for wave in range(1, 10):
        pending = [mk_pod(f"w{wave}p{i}", cpu_m=50) for i in range(wave)]
        got = assert_wave_equivalent(enc, nodes, existing, pending)
        assert len(got) == wave
        for p, h in zip(pending, got):
            if h:
                p.status.host = h
                existing.append(p)


def test_incremental_tracks_binds_and_deletes():
    enc = IncrementalEncoder()
    nodes = [mk_node("a", cpu_m=1000, mem=1 << 30),
             mk_node("b", cpu_m=1000, mem=1 << 30)]
    existing = []
    # wave 1: fill node capacity
    p1 = [mk_pod(f"p{i}", cpu_m=400, mem=128 << 20) for i in range(4)]
    got = assert_wave_equivalent(enc, nodes, existing, p1)
    for p, h in zip(p1, got):
        p.status.host = h
        existing.append(p)
    # wave 2: cluster full (2x1000m - 4x400m = 200m free per node)
    p2 = [mk_pod("q0", cpu_m=400, mem=128 << 20),
          mk_pod("q1", cpu_m=400, mem=128 << 20)]
    got = assert_wave_equivalent(enc, nodes, existing, p2)
    assert got == [None, None]
    # delete two pods (one per node under LR spreading), capacity frees up
    del existing[0:2]
    p3 = [mk_pod("r0", cpu_m=400, mem=128 << 20),
          mk_pod("r1", cpu_m=400, mem=128 << 20)]
    got = assert_wave_equivalent(enc, nodes, existing, p3)
    assert None not in got


def test_node_change_triggers_consistent_rebuild():
    enc = IncrementalEncoder()
    nodes = [mk_node("a"), mk_node("b")]
    pending = [mk_pod("p0", cpu_m=100)]
    assert_wave_equivalent(enc, nodes, [], pending)
    nodes = nodes + [mk_node("c", labels={"zone": "z2"})]
    pending = [mk_pod("p1", cpu_m=100, node_selector={"zone": "z2"})]
    got = assert_wave_equivalent(enc, nodes, [], pending)
    assert got == ["c"]


def test_label_policy_planes_supported():
    pol = BatchPolicy(label_presence=((("blessed",), True),),
                      label_prefs=(("fast", True, 2),),
                      anti_affinity=(("zone", 1),))
    enc = IncrementalEncoder(pol)
    nodes = [mk_node("a", labels={"blessed": "1", "zone": "z1"}),
             mk_node("b", labels={"blessed": "1", "fast": "1", "zone": "z2"}),
             mk_node("c", labels={"zone": "z1"})]  # not blessed -> filtered
    svc = api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                      spec=api.ServiceSpec(port=80, selector={"app": "x"}))
    pending = [mk_pod(f"p{i}", labels={"app": "x"}) for i in range(4)]

    inc = enc.encode(nodes, [], pending, [svc])
    chosen_inc, _ = solve(inc)
    got = decisions_to_names(inc, chosen_inc)
    full = encode_snapshot(nodes, [], pending, [svc], policy=pol)
    chosen_full, _ = solve(full)
    assert got == decisions_to_names(full, chosen_full)
    assert "c" not in got


def test_existing_pod_counts_in_every_matching_group():
    """An existing pod whose labels satisfy several services' selectors is
    a spreading peer of ALL of them (full encoder's member_exist matrix),
    not just of its own first service — regression for the incremental
    single-group counting bug."""
    enc = IncrementalEncoder()
    nodes = [mk_node("n0", cpu_m=4000, mem=8 << 30),
             mk_node("n1", cpu_m=4000, mem=8 << 30)]
    services = [
        api.Service(metadata=api.ObjectMeta(name="s0", namespace="default"),
                    spec=api.ServiceSpec(port=80, selector={"a": "1"})),
        api.Service(metadata=api.ObjectMeta(name="s1", namespace="default"),
                    spec=api.ServiceSpec(port=80, selector={"b": "2"})),
    ]
    # bound pod matches BOTH selectors; loader pod biases n1's resources
    both = mk_pod("both", labels={"a": "1", "b": "2"}, host="n0")
    loader = mk_pod("load", cpu_m=2000, mem=2 << 30, host="n1")
    existing = [both, loader]
    # warm the encoder's resident planes before the decisive wave
    assert_wave_equivalent(enc, nodes, existing, [mk_pod("warm")], services)
    # pending pod matches only s1 — 'both' must count as its n0 peer
    pending = [mk_pod("p", labels={"b": "2"})]
    assert_wave_equivalent(enc, nodes, existing, pending, services)


def test_affinity_policy_rejected():
    with pytest.raises(ValueError):
        IncrementalEncoder(BatchPolicy(affinity_labels=("rack",)))


def test_compiled_shape_count_bounded_under_churn():
    """Steady-state churn must re-use compiled programs: track the set of
    distinct solver input shape signatures across 30 waves of varying size
    and content; the pow-2 buckets keep it small."""
    enc = IncrementalEncoder()
    rng = random.Random(5)
    nodes = [mk_node(f"n{i}") for i in range(16)]
    existing = []
    shapes = set()
    for wave in range(30):
        size = rng.randint(3, 9)  # spans the 4-, 8- and 16-pod buckets
        pending = [mk_pod(f"w{wave}p{i}", cpu_m=rng.choice([50, 100]),
                          mem=64 << 20,
                          host_ports=(rng.choice([8080, 9090]),)
                          if rng.random() < 0.3 else ())
                   for i in range(size)]
        snap = enc.encode(nodes, existing, pending)
        inp = snapshot_to_inputs(snap)
        shapes.add(tuple((a.shape, str(a.dtype)) for a in inp))
        chosen, _ = solve(snap)
        for p, h in zip(pending, decisions_to_names(snap, chosen)):
            if h:
                p.status.host = h
                existing.append(p)
        while len(existing) > 40:    # deletes churn the planes too
            existing.pop(rng.randrange(len(existing)))
    # one shape per touched pow-2 pod bucket (4/8/16); nothing per-wave
    assert len(shapes) <= 3, f"{len(shapes)} distinct compiled shapes"


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_churn_equivalence(seed):
    rng = random.Random(3000 + seed)
    zones = ["z1", "z2"]
    nodes = [mk_node(f"n{i}", cpu_m=rng.choice([1000, 2000]),
                     mem=rng.choice([2 << 30, 4 << 30]),
                     labels={"zone": rng.choice(zones)} if rng.random() < 0.6
                     else {},
                     extra={"nvidia.com/gpu": 2} if rng.random() < 0.3
                     else None)
             for i in range(rng.randint(3, 10))]
    # overlapping selectors: one pod can satisfy several services
    sels = [{"app": "a0"}, {"app": "a1"}, {"tier": "web"},
            {"app": "a0", "tier": "web"}]
    services = [api.Service(
        metadata=api.ObjectMeta(name=f"svc{k}", namespace="default"),
        spec=api.ServiceSpec(port=80, selector=sels[k]))
        for k in range(rng.randint(0, 4))]
    enc = IncrementalEncoder()
    existing = []
    for wave in range(rng.randint(2, 5)):
        pending = []
        for i in range(rng.randint(1, 12)):
            kw = dict(cpu_m=rng.choice([0, 100, 400]),
                      mem=rng.choice([0, 64 << 20, 256 << 20]))
            if rng.random() < 0.4:
                kw["labels"] = {"app": f"a{rng.randint(0, 2)}"}
                if rng.random() < 0.5:
                    kw["labels"]["tier"] = "web"
            if rng.random() < 0.25:
                kw["host_ports"] = (rng.choice([8080, 9090, 7070]),)
            if rng.random() < 0.2:
                kw["node_selector"] = {"zone": rng.choice(zones)}
            if rng.random() < 0.15:
                kw["pds"] = (rng.choice(["pd1", "pd2"]),)
            if rng.random() < 0.2:
                kw["extra"] = {"nvidia.com/gpu": 1}
            if rng.random() < 0.25:
                kw["group"] = f"g{wave}x{rng.randint(0, 1)}"
            pending.append(mk_pod(f"w{wave}p{i}", **kw))
        pending = gang.order_wave(pending)
        got = assert_wave_equivalent(enc, nodes, existing, pending, services)
        for p, h in zip(pending, got):
            if h:
                p.status.host = h
                existing.append(p)
        for _ in range(rng.randint(0, 4)):
            if existing:
                existing.pop(rng.randrange(len(existing)))


# -- O(changed) delta path ---------------------------------------------------

@pytest.mark.parametrize("seed", range(4))
def test_fuzz_delta_equivalence(seed):
    """encode_delta fed from churn deltas must match a fresh full encode
    (and therefore the serial oracle) wave after wave — adds, host
    changes, removals, service groups, pinned hosts, gangs."""
    rng = random.Random(7000 + seed)
    nodes = [mk_node(f"n{i}", cpu_m=rng.choice([1000, 2000]),
                     labels={"zone": rng.choice(["z1", "z2"])})
             for i in range(rng.randint(3, 8))]
    services = [api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(port=80, selector={"app": "web"}))]
    enc = IncrementalEncoder()
    existing = []
    # first wave establishes planes through the full path
    snap = enc.encode(nodes, existing, [], services)
    for wave in range(4):
        pending = [mk_pod(f"w{wave}p{i}",
                          cpu_m=rng.choice([0, 100, 400]),
                          labels={"app": "web"} if rng.random() < 0.5
                          else {})
                   for i in range(rng.randint(1, 10))]
        upserted, removed = [], []
        # simulate binds from previous waves arriving as deltas
        for p in list(existing):
            if rng.random() < 0.15:
                existing.remove(p)
                removed.append(p)
        snap = enc.encode_delta(nodes, upserted, removed, pending, services)
        assert snap is not None
        fresh = IncrementalEncoder().encode(nodes, existing, pending,
                                            services)
        chosen_d, _ = solve(snap)
        chosen_f, _ = solve(fresh)
        assert decisions_to_names(snap, chosen_d) == \
            decisions_to_names(fresh, chosen_f)
        # commit this wave's decisions as delta upserts for the next
        names = decisions_to_names(snap, chosen_d)
        ups = []
        for p, h in zip(pending, names):
            if h:
                p.status.host = h
                existing.append(p)
                ups.append(p)
        snap2 = enc.encode_delta(nodes, ups, [], [], services)
        assert snap2 is not None


def test_delta_bails_to_full_on_overflow_and_node_change():
    enc = IncrementalEncoder()
    nodes = [mk_node("n1", cpu_m=500)]
    enc.encode(nodes, [], [], [])
    # capacity overflow: two 400m pods on a 500m node
    over = []
    for i in range(2):
        p = mk_pod(f"e{i}", cpu_m=400)
        p.status.host = "n1"
        over.append(p)
    assert enc.encode_delta(nodes, over, [], [], []) is None
    # full path still encodes (order-exact greedy walk)
    snap = enc.encode(nodes, over, [], [])
    assert snap is not None
    # node-set change: delta refuses
    enc2 = IncrementalEncoder()
    enc2.encode(nodes, [], [], [])
    assert enc2.encode_delta([mk_node("n2")], [], [], [], []) is None


# -- resident zone-count planes (ServiceAntiAffinity) ------------------------

def _zone_fixture(n_nodes=32, n_existing=64):
    pol = BatchPolicy(anti_affinity=(("zone", 2),))
    enc = IncrementalEncoder(pol)
    nodes = [mk_node(f"n{i}", labels={"zone": f"z{i % 4}"} if i % 5 else {})
             for i in range(n_nodes)]
    svc = api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                      spec=api.ServiceSpec(port=80, selector={"app": "x"}))
    existing = [mk_pod(f"e{i}", labels={"app": "x"} if i % 2 else {},
                       host=f"n{i % n_nodes}") for i in range(n_existing)]
    return pol, enc, nodes, [svc], existing


def test_zone_count_planes_stay_exact_under_delta_churn():
    """The resident [A, G, V] zone-count planes must equal the from-scratch
    derivation (batch_solver.derive_zone_counts) after every delta, and
    the delta-path decisions must match a fresh encoder's and the full
    encoder's under an anti-affinity policy."""
    from kubernetes_tpu.models.batch_solver import derive_zone_counts

    pol, enc, nodes, services, existing = _zone_fixture()
    enc.encode(nodes, existing, [mk_pod("warm", labels={"app": "x"})],
               services)
    rng = random.Random(11)
    for wave in range(4):
        pending = [mk_pod(f"w{wave}p{j}",
                          labels={"app": "x"} if rng.random() < 0.7 else {})
                   for j in range(rng.randint(2, 6))]
        upserted, removed = [], []
        for p in list(existing):
            if rng.random() < 0.1:
                existing.remove(p)
                removed.append(p)
        snap = enc.encode_delta(nodes, upserted, removed, pending, services)
        assert snap is not None
        want = derive_zone_counts(snap.node_zone, snap.group_counts,
                                  snap.zone_counts0.shape[2])
        assert np.array_equal(snap.zone_counts0, want)
        fresh = IncrementalEncoder(pol).encode(nodes, existing, pending,
                                               services)
        full = encode_snapshot(nodes, existing, pending, services,
                               policy=pol)
        chosen_d, _ = solve(snap)
        chosen_fr, _ = solve(fresh)
        chosen_fu, _ = solve(full)
        assert decisions_to_names(snap, chosen_d) == \
            decisions_to_names(fresh, chosen_fr) == \
            decisions_to_names(full, chosen_fu)
        for p, h in zip(pending, decisions_to_names(snap, chosen_d)):
            if h:
                p.status.host = h
                existing.append(p)
                enc.encode_delta(nodes, [p], [], [], services)


def test_zone_plane_maintenance_is_o_changed():
    """Counter-based O(changed) guard (tier-1 safe: no timing): one pod
    bind + one delete must touch the zone planes a constant number of
    times — A dims x matching groups per pod — independent of cluster
    size, and must not trigger a node-plane rebuild."""
    pol, enc, nodes, services, existing = _zone_fixture()
    enc.encode(nodes, existing, [mk_pod("warm", labels={"app": "x"})],
               services)
    rebuilds = enc.op_counts["node_rebuilds"]
    zw0 = enc.op_counts["zone_writes"]
    newpod = mk_pod("np", labels={"app": "x"})
    newpod.status.host = "n3"
    gone = existing[1]  # labeled {"app": "x"}, on a zone-labeled node
    snap = enc.encode_delta(nodes, [newpod], [gone],
                            [mk_pod("pend", labels={"app": "x"})], services)
    assert snap is not None
    assert enc.op_counts["node_rebuilds"] == rebuilds
    # A=1 anti-affinity dim, 1 matching group, 2 changed pods -> <= 2
    # single-element writes; the resident planes were NOT rebuilt from
    # the 64-pod existing list
    assert enc.op_counts["zone_writes"] - zw0 <= 2


def test_store_changelog_and_modeler_delta():
    from kubernetes_tpu.client.cache import FIFO, Store
    from kubernetes_tpu.scheduler.driver import SimpleModeler

    s = Store()
    t0 = s.token()
    a, b = mk_pod("a"), mk_pod("b")
    s.add(a); s.add(b); s.delete(a)
    events, t1 = s.delta_since(t0)
    assert [op for op, _ in events] == ["set", "set", "delete"]
    assert s.delta_since(t1) == ([], t1)
    # kube-slipstream: a relist DIFFS against the cache instead of
    # invalidating every token — identical contents log nothing, a
    # vanished object logs a delete, and consumers replay through
    s.replace([b])
    assert s.delta_since(t1) == ([], t1)
    s.replace([])
    events, t2 = s.delta_since(t1)
    assert [(op, o.metadata.name) for op, o in events] == [("delete", "b")]
    # only a diff wider than the retained window breaks tokens
    s.add(b)
    t3 = s.token()
    try:
        Store._LOG_MAX = 1
        s.replace([mk_pod("c"), mk_pod("d")])
    finally:
        Store._LOG_MAX = 1 << 14
    assert s.delta_since(t3) is None

    m = SimpleModeler(FIFO(), Store())
    tok = m.token()
    p = mk_pod("p1"); p.status.host = "n1"
    m.assume_pod(p)
    ups, rms, tok = m.delta(tok)
    assert [x.metadata.name for x in ups] == ["p1"] and rms == []
    # the reflector catches the bind: assumed -> scheduled is a
    # migration, never a removal
    m.scheduled.add(p)
    ups, rms, tok = m.delta(tok)   # prune fires inside delta
    assert rms == [] and [x.metadata.name for x in ups] == ["p1"]
    # true deletion: gone from both stores
    m.scheduled.delete(p)
    ups, rms, tok = m.delta(tok)
    assert ups == [] and [x.metadata.name for x in rms] == ["p1"]
    # delete + recreate of the same NAME with a new uid inside one
    # window: the old uid must surface as removed (else its resources
    # leak in the encoder) and the new one as upserted
    old = mk_pod("p2"); old.metadata.uid = "uid-old"
    m.scheduled.add(old)
    ups, rms, tok = m.delta(tok)
    m.scheduled.delete(old)
    new = mk_pod("p2"); new.metadata.uid = "uid-new"
    m.scheduled.add(new)
    ups, rms, tok = m.delta(tok)
    assert [x.metadata.uid for x in ups] == ["uid-new"]
    assert [x.metadata.uid for x in rms] == ["uid-old"]
