"""Scheme/codec/validation/fields tests.

Mirrors the reference's serialization round-trip fuzzing
(ref: pkg/api/serialization_test.go) and validation tables
(ref: pkg/api/validation/validation_test.go).
"""

import random

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import validation
from kubernetes_tpu.api.fields import parse_field_selector
from kubernetes_tpu.api.latest import scheme
from kubernetes_tpu.api.meta import accessor, default_rest_mapper
from kubernetes_tpu.api.quantity import Quantity


def _fuzz_pod(rng: random.Random) -> api.Pod:
    return api.Pod(
        metadata=api.ObjectMeta(
            name=f"pod-{rng.randrange(1000)}",
            namespace=rng.choice(["default", "kube-system", "test"]),
            uid=str(rng.randrange(10**9)),
            resource_version=str(rng.randrange(100)),
            labels={f"k{i}": f"v{rng.randrange(5)}" for i in range(rng.randrange(3))},
            annotations={"note": "x"} if rng.random() < 0.5 else {},
        ),
        spec=api.PodSpec(
            containers=[
                api.Container(
                    name=f"c{i}",
                    image=f"img:{rng.randrange(9)}",
                    ports=[
                        api.ContainerPort(container_port=8000 + i, host_port=rng.choice([0, 9000 + i]))
                    ],
                    resources=api.ResourceRequirements(
                        limits={
                            "cpu": Quantity(f"{rng.randrange(1, 4000)}m"),
                            "memory": Quantity(f"{rng.randrange(1, 4096)}Mi"),
                        }
                    ),
                )
                for i in range(1 + rng.randrange(2))
            ],
            restart_policy=rng.choice([api.RestartPolicyAlways, api.RestartPolicyNever]),
            node_selector={"disk": "ssd"} if rng.random() < 0.3 else {},
            host=rng.choice(["", "node-1"]),
        ),
        status=api.PodStatus(phase=rng.choice(["", api.PodPending, api.PodRunning])),
    )


def test_round_trip_fuzz_all_versions():
    rng = random.Random(42)
    for _ in range(50):
        pod = _fuzz_pod(rng)
        for version in scheme.versions():
            data = scheme.encode(pod, version)
            back = scheme.decode(data)
            assert back == pod, f"round-trip failed for version {version}"


def test_round_trip_other_kinds():
    objs = [
        api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                    spec=api.ServiceSpec(port=80, selector={"a": "b"}, portal_ip="10.0.0.1")),
        api.ReplicationController(
            metadata=api.ObjectMeta(name="rc", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=3, selector={"a": "b"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"a": "b"}),
                    spec=api.PodSpec(containers=[api.Container(name="c", image="i")]),
                ),
            ),
        ),
        api.Node(metadata=api.ObjectMeta(name="n1"),
                 spec=api.NodeSpec(capacity={"cpu": Quantity("4"), "memory": Quantity("8Gi")})),
        api.Namespace(metadata=api.ObjectMeta(name="space")),
        api.Event(metadata=api.ObjectMeta(name="e", namespace="default"),
                  involved_object=api.ObjectReference(kind="Pod", name="p", namespace="default"),
                  reason="scheduled", count=2),
        api.Binding(metadata=api.ObjectMeta(name="p", namespace="default"),
                    pod_name="p", host="node-1"),
        api.Status(status=api.StatusFailure, reason=api.ReasonNotFound, code=404),
        api.Endpoints(metadata=api.ObjectMeta(name="s", namespace="default"),
                      endpoints=[api.Endpoint(ip="10.1.2.3", port=8080)]),
    ]
    for obj in objs:
        for version in scheme.versions():
            assert scheme.decode(scheme.encode(obj, version)) == obj


def test_v1beta1_flattens_metadata():
    import json

    pod = api.Pod(metadata=api.ObjectMeta(name="x", namespace="default"))
    wire = json.loads(scheme.encode(pod, "v1beta1"))
    assert wire["id"] == "x"
    assert "metadata" not in wire
    v1 = json.loads(scheme.encode(pod, "v1"))
    assert v1["metadata"]["name"] == "x"


def test_convert_wire_between_versions():
    pod = api.Pod(metadata=api.ObjectMeta(name="x", namespace="default"))
    import json
    beta = json.loads(scheme.encode(pod, "v1beta1"))
    v1 = scheme.convert_wire(beta, "v1beta1", "v1")
    assert v1["metadata"]["name"] == "x"
    assert v1["apiVersion"] == "v1"


def test_list_round_trip():
    pl = api.PodList(items=[_fuzz_pod(random.Random(7)) for _ in range(3)])
    for version in scheme.versions():
        assert scheme.decode(scheme.encode(pl, version)) == pl


def test_accessor():
    pod = api.Pod(metadata=api.ObjectMeta(name="x", namespace="ns", resource_version="5"))
    assert accessor.name(pod) == "x"
    assert accessor.namespace(pod) == "ns"
    assert accessor.resource_version(pod) == "5"
    accessor.set_resource_version(pod, "6")
    assert pod.metadata.resource_version == "6"
    assert accessor.kind(pod) == "Pod"


def test_rest_mapper():
    m = default_rest_mapper()
    assert m.kind_for("pods") == "Pod"
    assert m.kind_for("po") == "Pod"
    assert m.resource_for("Service") == "services"
    assert m.is_namespaced("pods") is True
    assert m.is_namespaced("nodes") is False
    assert m.type_for("rc") is api.ReplicationController


# -- validation tables ------------------------------------------------------

def _valid_pod():
    return api.Pod(
        metadata=api.ObjectMeta(name="abc", namespace="default"),
        spec=api.PodSpec(containers=[api.Container(name="ctr", image="image")]),
    )


def test_validate_pod_success():
    assert validation.validate_pod(_valid_pod()) == []


@pytest.mark.parametrize(
    "mutate",
    [
        lambda p: setattr(p.metadata, "name", ""),
        lambda p: setattr(p.metadata, "name", "Not_Valid!"),
        lambda p: setattr(p.metadata, "namespace", ""),
        lambda p: setattr(p.spec, "containers", []),
        lambda p: setattr(p.spec.containers[0], "name", ""),
        lambda p: setattr(p.spec.containers[0], "image", ""),
        lambda p: setattr(p.spec, "restart_policy", "Sometimes"),
        lambda p: p.spec.containers[0].ports.append(api.ContainerPort(container_port=0)),
        lambda p: p.spec.containers[0].volume_mounts.append(
            api.VolumeMount(name="nope", mount_path="/x")),
    ],
)
def test_validate_pod_failures(mutate):
    pod = _valid_pod()
    mutate(pod)
    assert validation.validate_pod(pod) != []


def test_validate_host_port_conflict():
    pod = _valid_pod()
    pod.spec.containers = [
        api.Container(name="a", image="i",
                      ports=[api.ContainerPort(container_port=80, host_port=8080)]),
        api.Container(name="b", image="i",
                      ports=[api.ContainerPort(container_port=81, host_port=8080)]),
    ]
    errs = validation.validate_pod(pod)
    assert any(e.type == "duplicate value" for e in errs)


def test_validate_rc():
    rc = api.ReplicationController(
        metadata=api.ObjectMeta(name="rc", namespace="default"),
        spec=api.ReplicationControllerSpec(
            replicas=2, selector={"a": "b"},
            template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels={"a": "b"}),
                spec=api.PodSpec(containers=[api.Container(name="c", image="i")]),
            ),
        ),
    )
    assert validation.validate_replication_controller(rc) == []
    rc.spec.template.metadata.labels = {"a": "MISMATCH"}
    assert validation.validate_replication_controller(rc) != []
    rc.spec.template.metadata.labels = {"a": "b"}
    rc.spec.replicas = -1
    assert validation.validate_replication_controller(rc) != []


def test_validate_service():
    svc = api.Service(metadata=api.ObjectMeta(name="abc", namespace="default"),
                      spec=api.ServiceSpec(port=80))
    assert validation.validate_service(svc) == []
    svc.spec.port = 0
    assert validation.validate_service(svc) != []


def test_validate_pod_update_immutable_spec():
    old = _valid_pod()
    new = _valid_pod()
    new.spec.containers[0].image = "image:v2"
    assert validation.validate_pod_update(new, old) == []  # image change OK
    new2 = _valid_pod()
    new2.spec.containers[0].command = ["changed"]
    assert validation.validate_pod_update(new2, old) != []


# -- field selectors --------------------------------------------------------

def test_field_selector():
    sel = parse_field_selector("spec.host=")
    assert sel.matches({"spec.host": ""})
    assert not sel.matches({"spec.host": "node-1"})
    sel2 = parse_field_selector("status.phase!=Running,spec.host=n1")
    assert sel2.matches({"status.phase": "Pending", "spec.host": "n1"})
    assert not sel2.matches({"status.phase": "Running", "spec.host": "n1"})
    assert parse_field_selector("").matches({"anything": "x"})
