"""Reflector/FIFO/Store cache tests (ref: pkg/client/cache/*_test.go)."""

import os
import threading
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.latest import scheme
from kubernetes_tpu.client.cache import (
    FIFO,
    ListWatch,
    Poller,
    Reflector,
    Store,
    StorePodLister,
    StoreServiceLister,
)
from kubernetes_tpu.api.labels import parse_selector
from kubernetes_tpu.storage.helper import StoreHelper
from kubernetes_tpu.storage.memstore import MemStore


def _pod(name, ns="default", labels=None, host=""):
    return api.Pod(metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels or {}),
                   spec=api.PodSpec(host=host))


def test_store_basics():
    s = Store()
    s.add(_pod("a"))
    s.add(_pod("b"))
    assert len(s) == 2
    assert s.get_by_key("default/a").metadata.name == "a"
    s.delete(_pod("a"))
    assert s.get_by_key("default/a") is None
    s.replace([_pod("x")])
    assert s.list_keys() == ["default/x"]


def test_fifo_coalesces_updates():
    f = FIFO()
    p1 = _pod("a")
    f.add(p1)
    p1b = _pod("a")
    p1b.spec.host = "updated"
    f.add(p1b)  # same key: coalesce, keep position
    f.add(_pod("b"))
    first = f.pop()
    assert first.metadata.name == "a" and first.spec.host == "updated"
    assert f.pop().metadata.name == "b"


def test_fifo_pop_blocks_until_add():
    f = FIFO()
    got = []

    def consumer():
        got.append(f.pop())

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    assert not got
    f.add(_pod("late"))
    t.join(timeout=1)
    assert got and got[0].metadata.name == "late"


def test_fifo_pop_timeout():
    f = FIFO()
    with pytest.raises(TimeoutError):
        f.pop(timeout=0.05)


def test_fifo_delete_skipped_by_pop():
    f = FIFO()
    f.add(_pod("a"))
    f.add(_pod("b"))
    f.delete(_pod("a"))
    assert f.pop().metadata.name == "b"


def _cluster_source():
    """A StoreHelper-backed pods ListWatch, as the real client will provide."""
    h = StoreHelper(MemStore(), scheme)

    def list_fn():
        return h.extract_to_list("/pods", api.PodList)

    def watch_fn(rv):
        return h.watch("/pods", resource_version=rv)

    return h, ListWatch(list_fn, watch_fn)


def _wait_for(pred, timeout=2.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_reflector_mirrors_store():
    h, lw = _cluster_source()
    h.create_obj("/pods/default/pre", _pod("pre"))
    store = Store()
    r = Reflector(lw, store, name="pods").run()
    try:
        assert _wait_for(lambda: store.get_by_key("default/pre") is not None)
        h.create_obj("/pods/default/live", _pod("live"))
        assert _wait_for(lambda: store.get_by_key("default/live") is not None)
        live = store.get_by_key("default/live")
        live2 = scheme.deep_copy(live)
        live2.spec.host = "n1"
        h.set_obj("/pods/default/live", live2)
        assert _wait_for(
            lambda: (store.get_by_key("default/live") or _pod("x")).spec.host == "n1")
        h.delete_obj("/pods/default/pre")
        assert _wait_for(lambda: store.get_by_key("default/pre") is None)
        assert r.last_sync_resource_version != ""
    finally:
        r.stop()


def test_reflector_into_fifo_feeds_consumer():
    """The scheduler's pattern: unassigned pods reflected into a FIFO
    (ref: factory.go:126)."""
    h, lw = _cluster_source()
    fifo = FIFO()
    r = Reflector(lw, fifo, name="unassigned").run()
    try:
        h.create_obj("/pods/default/w1", _pod("w1"))
        # --race mode preempts between nearly every bytecode: delivery is
        # still guaranteed (the reflector watches from the list rv, so
        # there is no lost-event window) but latency balloons; the
        # assertion is about delivery, not speed
        got = fifo.pop(timeout=10 if os.environ.get("KTPU_RACE") else 2)
        assert got.metadata.name == "w1"
    finally:
        r.stop()


def test_reflector_survives_watch_closure():
    h, lw = _cluster_source()
    store = Store()
    real_watch = lw.watch_fn
    watches = []

    def tracking_watch(rv):
        w = real_watch(rv)
        watches.append(w)
        return w

    lw.watch_fn = tracking_watch
    r = Reflector(lw, store, name="pods").run()
    try:
        h.create_obj("/pods/default/a", _pod("a"))
        assert _wait_for(lambda: store.get_by_key("default/a") is not None)
        watches[-1].close()  # server closes stream: reflector must relist+rewatch
        h.create_obj("/pods/default/b", _pod("b"))
        assert _wait_for(lambda: store.get_by_key("default/b") is not None)
    finally:
        r.stop()


def test_poller_replaces():
    calls = []

    def list_fn():
        calls.append(1)
        return api.PodList(items=[_pod(f"p{len(calls)}")],
                           metadata=api.ListMeta(resource_version="1"))

    store = Store()
    p = Poller(list_fn, period=0.02, store=store)
    p.run()
    try:
        assert _wait_for(lambda: len(calls) >= 3)
        assert len(store) == 1
    finally:
        p.stop()


def test_pod_and_service_listers():
    pods = Store()
    pods.add(_pod("a", labels={"app": "web"}))
    pods.add(_pod("b", labels={"app": "db"}))
    lister = StorePodLister(pods)
    assert {p.metadata.name for p in lister.list()} == {"a", "b"}
    assert [p.metadata.name for p in lister.list(parse_selector("app=web"))] == ["a"]

    services = Store()
    services.add(api.Service(metadata=api.ObjectMeta(name="web", namespace="default"),
                             spec=api.ServiceSpec(port=80, selector={"app": "web"})))
    services.add(api.Service(metadata=api.ObjectMeta(name="all", namespace="other"),
                             spec=api.ServiceSpec(port=80, selector={"app": "web"})))
    slister = StoreServiceLister(services)
    got = slister.get_pod_services(_pod("a", labels={"app": "web"}))
    assert [s.metadata.name for s in got] == ["web"]  # namespace-scoped


def test_reflector_stop_join_freezes_store():
    """The post-join freeze contract: once stop()+join() returns True the
    run loop has exited, so no event written to the source afterwards can
    ever land in the store (what the stale-wave tests rely on to freeze a
    scheduler's view deterministically)."""
    h, lw = _cluster_source()
    store = Store()
    r = Reflector(lw, store, name="pods").run()
    try:
        h.create_obj("/pods/default/a", _pod("a"))
        assert _wait_for(lambda: store.get_by_key("default/a") is not None)
    finally:
        r.stop()
    assert r.join(5.0), "reflector thread did not exit"
    # join(True) means the thread is DEAD — a write after it can never be
    # applied, no grace sleep needed
    h.create_obj("/pods/default/late", _pod("late"))
    assert store.get_by_key("default/late") is None
    assert store.get_by_key("default/a") is not None
    # join is idempotent and True on a never-started reflector too
    assert r.join(0.1)
    assert Reflector(lw, Store(), name="never-run").join(0.1)
