"""kube-horizon cross-worker side channel (apiserver/share.py).

The contract under test (docs/design/apiserver-hotpath.md §cross-worker):

- the fairshed ledger is the EXACT feed — creates on worker A and binds
  on worker B sum to the same global backlog from every attachment, so
  the backlog governor fires at the same threshold on every worker of
  an SO_REUSEPORT fleet, and the measured Retry-After hints agree;
- the frame ring is the loss-TOLERANT feed — records a keeping-up
  reader imports are byte-identical to what the committing worker
  published (including across the wrap pad); a lapped reader loses
  records to ``ring_drops`` but never imports torn bytes;
- the live APIServer path: worker A's write-path seed publishes into
  its ring, worker B's drain imports the exact wire JSON into its own
  cache (the sibling never pays the encode).
"""

import json

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import fairshed
from kubernetes_tpu.apiserver.http import APIServer
from kubernetes_tpu.apiserver.master import Master, MasterConfig
from kubernetes_tpu.apiserver.share import ShareSegment, SharedLedger
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.http import HTTPTransport


def mkseg(tmp_path, nworkers=2, ring_bytes=8192):
    """Create a segment and attach one ShareSegment per worker, the way
    the harness parent creates and each worker process attaches."""
    path = str(tmp_path / "share.seg")
    segs = [ShareSegment.create(path, nworkers, ring_bytes=ring_bytes,
                                worker_index=0)]
    segs += [ShareSegment(path, worker_index=i) for i in range(1, nworkers)]
    return segs


# ---------------------------------------------------------------------------
# segment plumbing
# ---------------------------------------------------------------------------

def test_segment_rejects_foreign_files_and_bad_index(tmp_path):
    bogus = tmp_path / "not-a-segment"
    bogus.write_bytes(b"\0" * 4096)
    with pytest.raises(ValueError, match="not a kube-share segment"):
        ShareSegment(str(bogus))
    a, _b = mkseg(tmp_path)
    with pytest.raises(ValueError, match="out of range"):
        ShareSegment(a.path, worker_index=2)


def test_ledger_counters_are_exact_across_attachments(tmp_path):
    a, b = mkseg(tmp_path)
    la, lb = SharedLedger(a), SharedLedger(b)
    for _ in range(7):
        la.note_created()
    lb.note_bound(3)
    # both attachments read the same global truth
    assert la.backlog() == lb.backlog() == 4
    # availability-safe delete clamp: deletes only count against a
    # positive backlog (deleting a BOUND pod opens no phantom headroom)
    for _ in range(10):
        lb.note_deleted()
    assert la.backlog() == lb.backlog() == 0
    lb.note_bound(100)
    assert la.backlog() == lb.backlog() == 0  # never negative


# ---------------------------------------------------------------------------
# the governor at N workers — the lifted --overload restriction
# ---------------------------------------------------------------------------

def test_governor_fires_at_same_backlog_on_every_worker(tmp_path):
    a, b = mkseg(tmp_path)
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    fs_a = fairshed.FairShed(backlog_limit=5, clock=clock,
                             ledger=SharedLedger(a, clock=clock))
    fs_b = fairshed.FairShed(backlog_limit=5, clock=clock,
                             ledger=SharedLedger(b, clock=clock))
    # a ledger-less worker in the same fleet is the broken pre-horizon
    # topology: it sees only its local share of the creates
    fs_blind = fairshed.FairShed(backlog_limit=5, clock=clock)
    for _ in range(5):
        fs_a.note_pod_created()
        fs_a.admit(fairshed.WORKLOAD).release()
    # worker B served ZERO creates, yet its governor fires at the same
    # global threshold the single-worker contract promises
    assert fs_a.backlog == fs_b.backlog == 5
    with pytest.raises(fairshed.Shed):
        fs_b.admit(fairshed.WORKLOAD, pod_create=True)
    with pytest.raises(fairshed.Shed):
        fs_a.admit(fairshed.WORKLOAD, pod_create=True)
    # the blind worker admits — exactly the governor bypass that forced
    # --overload to require --apiservers 1 before the ledger existed
    fs_blind.admit(fairshed.WORKLOAD, pod_create=True).release()
    # binds observed by B reopen headroom for A's next create
    fs_b.note_pods_bound(2)
    assert fs_a.backlog == 3
    fs_a.admit(fairshed.WORKLOAD, pod_create=True).release()


def test_retry_after_hints_agree_across_workers(tmp_path):
    a, b = mkseg(tmp_path)
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    la = SharedLedger(a, clock=clock)
    lb = SharedLedger(b, clock=clock)
    fs_a = fairshed.FairShed(backlog_limit=4, clock=clock, ledger=la)
    fs_b = fairshed.FairShed(backlog_limit=4, clock=clock, ledger=lb)
    # anchor both rate windows, then let A bind 50 pods over 5 seconds:
    # the GLOBAL bind rate (10/s) is measurable from either worker
    la.bind_rate(), lb.bind_rate()
    now[0] += 5.0
    la.note_bound(50)
    assert la.bind_rate() == pytest.approx(10.0)
    assert lb.bind_rate() == pytest.approx(10.0)
    for _ in range(60):
        fs_a.note_pod_created()
    assert fs_a.backlog == fs_b.backlog == 10  # 60 created - 50 bound
    hints = []
    for fs in (fs_a, fs_b):
        with pytest.raises(fairshed.Shed) as ei:
            fs.admit(fairshed.WORKLOAD, pod_create=True)
        assert ei.value.reason == "backlog"
        hints.append(ei.value.retry_after_s)
    # same global backlog / same global rate -> the same measured hint,
    # regardless of which worker the kernel routed the create to
    assert hints[0] == hints[1] > 0.0


# ---------------------------------------------------------------------------
# frame ring: exact bytes for a keeping-up reader, counted loss for a
# lapped one
# ---------------------------------------------------------------------------

def test_frame_records_import_bit_identical(tmp_path):
    a, b = mkseg(tmp_path)
    pub = [(f"rv-{i}", "v1", json.dumps({"kind": "Pod", "i": i,
                                         "pad": "é" * 20}))
           for i in range(10)]
    for rv, ver, wire in pub:
        assert a.publish_frame(rv, ver, wire)
    assert b.drain_frames() == pub       # exact tuples, publish order
    assert b.drain_frames() == []        # cursor advanced, nothing new
    assert a.drain_frames() == []        # own block is never self-drained
    assert b.ring_drops == 0


def test_frame_ring_wraps_without_loss_for_keeping_up_reader(tmp_path):
    a, b = mkseg(tmp_path, ring_bytes=4096)
    wire = json.dumps({"pad": "x" * 300})
    got = []
    for i in range(50):  # ~18 KB through a 4 KB ring
        assert a.publish_frame(f"rv-{i}", "v1", wire)
        got.extend(b.drain_frames())
    assert got == [(f"rv-{i}", "v1", wire) for i in range(50)]
    assert b.ring_drops == 0


def test_lapped_reader_drops_are_counted_never_torn(tmp_path):
    a, b = mkseg(tmp_path, ring_bytes=4096)
    pub = {}
    for i in range(60):  # laps the ring several times, reader asleep
        rv, wire = f"rv-{i:03d}", json.dumps({"i": i, "pad": "y" * 200})
        assert a.publish_frame(rv, "v1", wire)
        pub[rv] = wire
    got = b.drain_frames()
    assert b.ring_drops >= 1
    # whatever survives is byte-exact — a lap loses records, it never
    # fabricates or tears one
    for rv, ver, wire in got:
        assert ver == "v1" and pub[rv] == wire


def test_oversize_record_is_refused_not_published(tmp_path):
    a, b = mkseg(tmp_path, ring_bytes=4096)
    assert not a.publish_frame("rv-big", "v1", "z" * 3000)
    assert a.worker_counters(0)["published"] == 0
    assert b.drain_frames() == []
    # read-only attachments (harness probes) can never publish
    probe = ShareSegment(a.path, worker_index=-1)
    assert not probe.publish_frame("rv", "v1", "{}")


# ---------------------------------------------------------------------------
# the live path: worker A's write seeds worker B's cache
# ---------------------------------------------------------------------------

def test_apiserver_sibling_imports_seeded_encoding(tmp_path):
    seg_a, seg_b = mkseg(tmp_path, ring_bytes=1 << 20)
    srv_a = APIServer(Master(MasterConfig()), share=seg_a).start()
    srv_b = APIServer(Master(MasterConfig()), share=seg_b).start()
    try:
        client = Client(HTTPTransport(srv_a.base_url))
        pod = client.pods().create(api.Pod(
            metadata=api.ObjectMeta(name="seeded", namespace="default"),
            spec=api.PodSpec(containers=[api.Container(name="c",
                                                       image="img")])))
        assert srv_a.metric_seed_published.value() >= 1
        # worker B never served the write; its drain imports the exact
        # bytes worker A cached at commit time
        srv_b._drain_share_seeds()
        assert srv_b.metric_seed_imported.value() >= 1
        rv = pod.metadata.resource_version
        keys = [k for k in srv_b._wire_cache if k[0] == rv]
        assert keys, f"rv {rv} not imported"
        for key in keys:
            assert srv_b._wire_cache[key] == srv_a._wire_cache[key]
        # a second drain is a no-op, not a re-import
        imported = srv_b.metric_seed_imported.value()
        srv_b._drain_share_seeds()
        assert srv_b.metric_seed_imported.value() == imported
    finally:
        srv_a.stop()
        srv_b.stop()
        seg_a.close()
        seg_b.close()
