"""hack/perfgate.py — the regression gate over the record trajectory.

Tier-1 coverage: the committed r08-r10 records must gate green against
their own best priors (the trajectory the repo actually shipped), a
synthetic 10% sustained-rate regression must gate red, advisory keys
must warn without failing, and shape isolation must keep fan-out /
lag-storm records out of the clean series' baselines."""

import copy
import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_perfgate():
    spec = importlib.util.spec_from_file_location(
        "perfgate", os.path.join(_REPO, "hack", "perfgate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def pg():
    return _load_perfgate()


@pytest.fixture(scope="module")
def r10(pg):
    path = os.path.join(_REPO, "CHURN_MP_r10_fullshape.json")
    with open(path) as fh:
        return json.load(fh)


class TestCommittedTrajectory:
    def test_committed_records_gate_green(self, pg):
        """Every committed r8+ record vs its best prior: the shipped
        trajectory must satisfy the gate the future rounds will face."""
        results = pg.check_committed(_REPO)
        assert results, "no committed records gated"
        red = [r for r in results if r["verdict"] == "red"]
        assert red == [], red

    def test_fullshape_rounds_found_baselines(self, pg):
        by_rec = {r.get("record"): r for r in pg.check_committed(_REPO)}
        for rnd in (8, 9, 10):
            rec = by_rec.get(f"CHURN_MP_r{rnd:02d}_fullshape.json")
            assert rec is not None
            assert rec.get("baseline"), rec  # a real prior was compared
            assert rec["verdict"] == "green"

    def test_fanout_record_isolated_from_clean_shape(self, pg):
        res = pg.gate(os.path.join(_REPO, "CHURN_MP_r08_fanout.json"),
                      repo=_REPO)
        # observer-watcher topology has no committed prior of its own
        # shape; it must NOT have gated against the clean full-shape runs
        assert res.get("no_baseline") is True
        assert res["verdict"] == "green"


class TestVerdicts:
    def test_synthetic_10pct_sustained_regression_is_red(self, pg, r10):
        fresh = copy.deepcopy(r10)
        fresh["sustained_pods_per_s"] = round(
            r10["sustained_pods_per_s"] * 0.90, 1)
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "red"
        assert any("sustained" in f for f in res["failures"])
        assert res["keys"]["sustained_pods_per_s"]["status"] == "regressed"

    def test_within_2pct_is_green(self, pg, r10):
        fresh = copy.deepcopy(r10)
        fresh["sustained_pods_per_s"] = round(
            r10["sustained_pods_per_s"] * 0.98, 1)
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "green"
        assert res["keys"]["sustained_pods_per_s"]["status"] == "ok"

    def test_advisory_regression_warns_but_stays_green(self, pg, r10):
        fresh = copy.deepcopy(r10)
        fresh["scheduler_waves"]["solve"]["p50_ms"] = \
            r10["scheduler_waves"]["solve"]["p50_ms"] * 2.0
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "green"
        assert any("solve_p50_ms" in w for w in res["warnings"])
        assert res["keys"]["solve_p50_ms"]["status"] == "regressed"
        assert res["keys"]["solve_p50_ms"]["required"] is False

    def test_dropped_required_key_is_red(self, pg, r10):
        fresh = copy.deepcopy(r10)
        del fresh["apiserver"]["frame_cache_hit_rate"]
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "red"
        assert res["keys"]["frame_cache_hit_rate"]["status"] == "missing"

    def test_dropped_advisory_key_only_warns(self, pg, r10):
        fresh = copy.deepcopy(r10)
        del fresh["latency"]["e2e_p50_s"]
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "green"
        assert any("e2e_p50_s" in w for w in res["warnings"])

    def test_frame_cache_hit_rate_band(self, pg, r10):
        fresh = copy.deepcopy(r10)
        base_rate = r10["apiserver"]["frame_cache_hit_rate"]
        fresh["apiserver"]["frame_cache_hit_rate"] = \
            round(base_rate * 0.90, 3)  # 10% relative drop >> 2% band
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "red"

    def test_improvement_is_green_everywhere(self, pg, r10):
        fresh = copy.deepcopy(r10)
        fresh["sustained_pods_per_s"] = r10["sustained_pods_per_s"] * 1.3
        fresh["scheduler_waves"]["solve"]["p50_ms"] = 100.0
        fresh["cpu_budget_s"]["apiserver"] = 50.0
        res = pg.compare(fresh, r10)
        assert res["verdict"] == "green"
        assert res["warnings"] == [] and res["failures"] == []


class TestShapeAndBaseline:
    def test_shape_key_separates_load_topologies(self, pg, r10):
        clean = pg.shape_key(r10)
        fan = copy.deepcopy(r10)
        fan["apiserver"]["observer_watchers"] = 8
        storm = copy.deepcopy(r10)
        storm["lag_storm"] = 2
        assert pg.shape_key(fan) != clean
        assert pg.shape_key(storm) != clean
        assert pg.shape_key(fan) != pg.shape_key(storm)

    def test_priority_storm_is_its_own_topology_class(self, pg, r10):
        """kube-preempt: a priority-storm record offers into a FULL
        cluster — its sustained rate is an evict+bind number and must
        never baseline-gate the clean 50k/10k series (or vice versa)."""
        clean = pg.shape_key(r10)
        pr = copy.deepcopy(r10)
        pr["priority_storm"] = {"fill_pods": 8000, "storm_pods": 4000}
        assert pg.shape_key(pr) != clean
        lag = copy.deepcopy(r10)
        lag["lag_storm"] = 2
        assert pg.shape_key(pr) != pg.shape_key(lag)
        # and the baseline search honors the split: a clean fresh record
        # must not pick the storm as its best prior even at a higher rate
        storm_rec = copy.deepcopy(r10)
        storm_rec["priority_storm"] = {"storm_pods": 1}
        storm_rec["sustained_pods_per_s"] = 99999.0
        import json as _json
        import tempfile, os as _os
        with tempfile.TemporaryDirectory() as td:
            for name, rec in (("CHURN_MP_r20_storm.json", storm_rec),
                              ("CHURN_MP_r21_clean.json", r10)):
                with open(_os.path.join(td, name), "w") as fh:
                    _json.dump(rec, fh)
            fresh = copy.deepcopy(r10)
            _path, base = pg.find_baseline(fresh, 22, td)
            assert base is not None
            assert not base.get("priority_storm")
            assert base["sustained_pods_per_s"] == \
                r10["sustained_pods_per_s"]

    def test_baseline_is_best_prior_not_latest(self, pg, r10):
        # r10's search space holds r05 (333), r07 (232), r08 (426), r09
        # (453): best == r09's sustained rate, regardless of file order
        path, base = pg.find_baseline(r10, 10, _REPO)
        assert base is not None
        best = max(rec["sustained_pods_per_s"]
                   for p, rec in pg.committed_records(_REPO)
                   if pg.round_of(p) < 10 and pg._eligible_baseline(rec)
                   and pg.shape_key(rec) == pg.shape_key(r10))
        assert base["sustained_pods_per_s"] == best

    def test_error_records_are_skipped_not_gated(self, pg, tmp_path):
        p = tmp_path / "CHURN_MP_r99_broken.json"
        p.write_text(json.dumps({"error": "feeder failures",
                                 "created": 10}))
        res = pg.gate(str(p), repo=_REPO)
        assert res["verdict"] == "skipped"

    def test_cli_exit_codes(self, pg, r10, tmp_path):
        good = tmp_path / "CHURN_MP_r12_fullshape.json"
        good.write_text(json.dumps(r10))
        against = tmp_path / "base.json"
        against.write_text(json.dumps(r10))
        assert pg.main([str(good), "--against", str(against)]) == 0
        bad_rec = copy.deepcopy(r10)
        bad_rec["sustained_pods_per_s"] *= 0.5
        bad = tmp_path / "CHURN_MP_r12_bad.json"
        bad.write_text(json.dumps(bad_rec))
        assert pg.main([str(bad), "--against", str(against)]) == 1
        assert pg.main(["--check-committed", "--repo", _REPO]) == 0
