"""Wave contention — concurrent schedulers racing over one store.

SURVEY §7 hard part (e): when multiple schedulers (or one scheduler's
waves against a churning store) land binds concurrently, the Binding CAS
(set spec.host iff empty — registry/resources.BindingREST, ref:
pkg/registry/pod/etcd/etcd.go:98-152) must guarantee every pod binds
EXACTLY once, losers requeue with backoff, and no wave deadlocks — even
with injected CAS conflicts and stale node/pod stores.
"""

import time


from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.apiserver.master import Master, MasterConfig
from kubernetes_tpu.client.client import Client, InProcessTransport
from kubernetes_tpu.scheduler.driver import ConfigFactory, Scheduler
from kubernetes_tpu.scheduler.tpu_batch import BatchScheduler
from kubernetes_tpu.storage.memstore import ErrCASConflict, MemStore


def mk_node(name, cpu="16", mem="64Gi"):
    return api.Node(metadata=api.ObjectMeta(name=name),
                    spec=api.NodeSpec(capacity={"cpu": Quantity(cpu),
                                                "memory": Quantity(mem)}))


def mk_pod(name, cpu_m=100):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="i",
            resources=api.ResourceRequirements(limits={
                "cpu": Quantity(f"{cpu_m}m"),
                "memory": Quantity("64Mi")}))]))


def wait_for(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def start_batch(master, wave_size=16, linger=0.05):
    client = Client(InProcessTransport(master))
    factory = ConfigFactory(client, node_poll_period=0.1)
    config = factory.create()
    sched = BatchScheduler(config, factory, client, wave_size=wave_size,
                           wave_linger_s=linger).run()
    return sched, factory


def start_serial(master):
    client = Client(InProcessTransport(master))
    factory = ConfigFactory(client, node_poll_period=0.1)
    config = factory.create()
    sched = Scheduler(config).run()
    return sched, factory


def all_bound(client, n):
    pods = client.pods().list().items
    return len(pods) == n and all(p.spec.host for p in pods)


def test_two_batch_schedulers_bind_every_pod_exactly_once():
    """Both schedulers see every unassigned pod (their reflectors watch the
    same store); the Binding CAS picks one winner per pod, the loser
    requeues and drops it after the refetch. Nothing double-binds, nothing
    starves."""
    m = Master()
    admin = Client(InProcessTransport(m))
    for i in range(4):
        admin.nodes().create(mk_node(f"n{i}"))
    s1, f1 = start_batch(m)
    s2, f2 = start_batch(m)
    try:
        time.sleep(0.3)
        for i in range(48):
            admin.pods().create(mk_pod(f"p{i:03d}"))
        assert wait_for(lambda: all_bound(admin, 48)), \
            "contended pods never all bound"
        hosts = {p.metadata.name: p.spec.host
                 for p in admin.pods().list().items}
        assert all(h.startswith("n") for h in hosts.values())
        # stability: nobody rebinds an already-bound pod (CAS would 409)
        time.sleep(0.3)
        hosts2 = {p.metadata.name: p.spec.host
                  for p in admin.pods().list().items}
        assert hosts == hosts2
    finally:
        s1.stop(); s2.stop(); f1.stop(); f2.stop()


def test_serial_and_batch_scheduler_race():
    m = Master()
    admin = Client(InProcessTransport(m))
    for i in range(3):
        admin.nodes().create(mk_node(f"n{i}"))
    sb, fb = start_batch(m)
    ss, fs = start_serial(m)
    try:
        time.sleep(0.3)
        for i in range(30):
            admin.pods().create(mk_pod(f"mix{i:03d}"))
        assert wait_for(lambda: all_bound(admin, 30)), \
            "mixed-scheduler pods never all bound"
    finally:
        sb.stop(); ss.stop(); fb.stop(); fs.stop()


def test_injected_binding_cas_conflicts_requeue_and_converge():
    """Forced CAS conflicts on the bind path: the wave hands the pod to the
    error handler (backoff + refetch + requeue) and a later wave binds it."""
    store = MemStore()
    m = Master(MasterConfig(store=store))
    admin = Client(InProcessTransport(m))
    admin.nodes().create(mk_node("n0"))
    # every pod's first two bind attempts lose the CAS race
    for i in range(6):
        store.inject_error("compare_and_swap",
                           f"/registry/pods/default/cas{i}",
                           ErrCASConflict("injected bind race"), times=2)
    sched, factory = start_batch(m, wave_size=8, linger=0.02)
    try:
        time.sleep(0.3)
        for i in range(6):
            admin.pods().create(mk_pod(f"cas{i}"))
        assert wait_for(lambda: all_bound(admin, 6), timeout=45.0), \
            "pods behind injected CAS conflicts never bound"
    finally:
        sched.stop(); factory.stop()


def test_wave_against_stale_node_store_converges():
    """A wave solved against a node set containing a just-deleted node may
    emit bindings for it; the system must converge — pods bound to the
    dead node are not our concern (node controller evicts them), but pods
    NOT yet bound must keep scheduling onto surviving nodes, and waves
    must not wedge."""
    m = Master()
    admin = Client(InProcessTransport(m))
    for i in range(3):
        admin.nodes().create(mk_node(f"n{i}", cpu="2"))
    sched, factory = start_batch(m, wave_size=8, linger=0.1)
    try:
        time.sleep(0.3)  # node store synced with 3 nodes
        # delete a node; the poller refreshes every 0.1s but the first
        # wave may still see it
        admin.nodes().delete("n2")
        for i in range(12):
            admin.pods().create(mk_pod(f"st{i:02d}", cpu_m=300))
        assert wait_for(lambda: all_bound(admin, 12), timeout=45.0), \
            "pods never converged after node deletion mid-wave"
        # eventually-consistent: after the poller caught up, later binds
        # must only target live nodes; allow early ones on n2
        live = {p.spec.host for p in admin.pods().list().items}
        assert live <= {"n0", "n1", "n2"}
        # capacity proof that survivors carried the load: 12x300m needs
        # more than one 2-cpu node
        assert len(live & {"n0", "n1"}) == 2
    finally:
        sched.stop(); factory.stop()


def test_concurrent_waves_with_churning_deletes():
    """Pods deleted while queued or mid-wave must not wedge the scheduler:
    the error handler's refetch drops vanished pods."""
    m = Master()
    admin = Client(InProcessTransport(m))
    admin.nodes().create(mk_node("n0"))
    sched, factory = start_batch(m, wave_size=4, linger=0.1)
    try:
        time.sleep(0.3)
        for i in range(20):
            admin.pods().create(mk_pod(f"ch{i:02d}"))
        # delete half while waves are in flight
        for i in range(0, 20, 2):
            try:
                admin.pods().delete(f"ch{i:02d}")
            except Exception:
                pass  # already bound+running is fine too
        def survivors_bound():
            pods = admin.pods().list().items
            return all(p.spec.host for p in pods)
        assert wait_for(survivors_bound, timeout=45.0), \
            "survivor pods never bound amid churn deletes"
    finally:
        sched.stop(); factory.stop()


def test_batched_bindings_transactional_commit():
    """The bindings batch endpoint: one store pass, per-item CAS results."""
    m = Master()
    admin = Client(InProcessTransport(m))
    admin.nodes().create(mk_node("n0"))
    for i in range(4):
        admin.pods().create(mk_pod(f"b{i}"))
    # pre-bind b2 so its slot conflicts
    admin.pods().bind(api.Binding(
        metadata=api.ObjectMeta(name="b2", namespace="default"),
        pod_name="b2", host="n0"))
    blist = api.BindingList(items=[
        api.Binding(metadata=api.ObjectMeta(name=f"b{i}",
                                            namespace="default"),
                    pod_name=f"b{i}", host="n0")
        for i in range(4)] + [
        api.Binding(metadata=api.ObjectMeta(name="ghost",
                                            namespace="default"),
                    pod_name="ghost", host="n0"),
        api.Binding(metadata=api.ObjectMeta(namespace="default"))])
    results = admin.pods().bind_many(blist)
    by_name = {r.pod_name: r for r in results.items}
    assert by_name["b0"].error == "" and by_name["b1"].error == ""
    assert by_name["b3"].error == ""
    assert "already assigned" in by_name["b2"].error
    assert by_name["ghost"].code == 404
    assert by_name[""].code == 400
    # winners really bound
    for i in (0, 1, 3):
        assert admin.pods().get(f"b{i}").spec.host == "n0"


def test_two_batch_schedulers_race_batched_binds():
    """Both schedulers commit whole waves through the batched CAS: still
    exactly-once binding under contention."""
    m = Master()
    admin = Client(InProcessTransport(m))
    for i in range(4):
        admin.nodes().create(mk_node(f"n{i}"))
    s1, f1 = start_batch(m, wave_size=32, linger=0.02)
    s2, f2 = start_batch(m, wave_size=32, linger=0.02)
    try:
        time.sleep(0.3)
        for i in range(64):
            admin.pods().create(mk_pod(f"bb{i:03d}"))
        assert wait_for(lambda: all_bound(admin, 64), timeout=45.0)
        hosts = {p.metadata.name: p.spec.host
                 for p in admin.pods().list().items}
        time.sleep(0.3)
        hosts2 = {p.metadata.name: p.spec.host
                  for p in admin.pods().list().items}
        assert hosts == hosts2
    finally:
        s1.stop(); s2.stop(); f1.stop(); f2.stop()


def test_batched_bindings_reject_cross_namespace_items():
    """Items naming another namespace are refused per-item: authz and
    admission ran against the request namespace only."""
    m = Master()
    admin = Client(InProcessTransport(m))
    admin.nodes().create(mk_node("n0"))
    admin.pods().create(mk_pod("same-ns"))
    blist = api.BindingList(items=[
        api.Binding(metadata=api.ObjectMeta(name="same-ns",
                                            namespace="default"),
                    pod_name="same-ns", host="n0"),
        api.Binding(metadata=api.ObjectMeta(name="sneaky",
                                            namespace="victim"),
                    pod_name="sneaky", host="n0")])
    results = admin.pods().bind_many(blist)
    by_name = {r.pod_name: r for r in results.items}
    assert by_name["same-ns"].error == ""
    assert by_name["sneaky"].code == 403
    assert "does not match request namespace" in by_name["sneaky"].error
