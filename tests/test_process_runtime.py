"""ProcessRuntime — real workloads end-to-end.

The framework's answer to VERDICT r1 "the kubelet cannot run a real
workload": pods become local process groups with the native pause binary
as the sandbox (ref: pkg/kubelet/dockertools/docker.go + kubelet.go:1025
createPodInfraContainer). These tests run an actual HTTP server as a pod,
probe it over real sockets, read its real logs, exec real commands, and
watch the kubelet restart a killed process per RestartPolicy.
"""

import os
import signal
import socket
import time
import urllib.request

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.cluster import Cluster, ClusterConfig
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime, find_pause_binary


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def mk_pod(name, command, restart=api.RestartPolicyAlways, probe=None,
           labels=None):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels=labels or {"app": name}),
        spec=api.PodSpec(
            restart_policy=restart,
            containers=[api.Container(
                name="main", image="local/script",
                command=command, liveness_probe=probe,
                resources=api.ResourceRequirements(limits={
                    "cpu": Quantity("100m"), "memory": Quantity("64Mi")}))]))


@pytest.fixture
def runtime(tmp_path):
    rt = ProcessRuntime(str(tmp_path))
    if rt.pause_binary is None:
        pytest.skip("no pause binary and no toolchain to build one")
    yield rt
    rt.shutdown()


# ---------------------------------------------------------------------------
# runtime unit tests
# ---------------------------------------------------------------------------

def test_runtime_runs_and_reaps_real_process(runtime, tmp_path):
    pod = mk_pod("echoer", ["python3", "-c", "print('hello from pod')"])
    pod.metadata.uid = "uid-echoer"
    runtime.pull_image("local/script")
    cid = runtime.create_container(pod, pod.spec.containers[0], 0)
    runtime.start_container(cid)
    deadline = time.time() + 10
    while time.time() < deadline:
        rec = runtime.inspect_container(cid)
        if not rec.running:
            break
        time.sleep(0.05)
    rec = runtime.inspect_container(cid)
    assert not rec.running and rec.exit_code == 0
    assert "hello from pod" in runtime.container_logs(cid)


def test_runtime_stop_escalates_to_kill(runtime):
    # a process that ignores SIGTERM must still die within the grace period
    pod = mk_pod("stubborn", ["python3", "-c",
                              "import signal, time;"
                              "signal.signal(signal.SIGTERM, signal.SIG_IGN);"
                              "print('ready', flush=True);"
                              "time.sleep(300)"])
    pod.metadata.uid = "uid-stubborn"
    runtime.pull_image("local/script")
    runtime.stop_grace_s = 0.5
    cid = runtime.create_container(pod, pod.spec.containers[0], 0)
    runtime.start_container(cid)
    deadline = time.time() + 10
    while "ready" not in runtime.container_logs(cid):
        assert time.time() < deadline, "process never installed its handler"
        time.sleep(0.05)
    t0 = time.time()
    runtime.stop_container(cid)
    rec = runtime.inspect_container(cid)
    assert not rec.running
    assert time.time() - t0 < 10
    assert rec.exit_code == 128 + signal.SIGKILL  # killed, not graceful


def test_runtime_exec_and_exit_codes(runtime):
    pod = mk_pod("sleeper", ["python3", "-c", "import time; time.sleep(60)"])
    pod.metadata.uid = "uid-sleeper"
    runtime.pull_image("local/script")
    cid = runtime.create_container(pod, pod.spec.containers[0], 0)
    runtime.start_container(cid)
    rc, out = runtime.exec_in_container(cid, ["echo", "exec-works"])
    assert rc == 0 and "exec-works" in out
    rc, _ = runtime.exec_in_container(cid, ["sh", "-c", "exit 3"])
    assert rc == 3
    runtime.stop_container(cid)
    rc, out = runtime.exec_in_container(cid, ["echo", "nope"])
    assert rc == 1 and "not running" in out


def test_pause_sandbox_is_running_process(runtime):
    pod = mk_pod("sandboxed", ["python3", "-c", "import time; time.sleep(60)"])
    pod.metadata.uid = "uid-sandboxed"
    cid = runtime.create_infra_container(pod)
    runtime.start_container(cid)
    rec = runtime.inspect_container(cid)
    assert rec.running and rec.ip == "127.0.0.1"
    pid = runtime._procs[cid].popen.pid
    # the sandbox holder is a live PID running the native pause binary
    assert os.path.exists(f"/proc/{pid}")
    with open(f"/proc/{pid}/cmdline", "rb") as f:
        assert b"pause" in f.read()
    runtime.stop_container(cid)
    rec = runtime.inspect_container(cid)
    assert not rec.running and rec.exit_code == 0  # graceful TERM exit


# ---------------------------------------------------------------------------
# full-cluster e2e: a real HTTP server pod
# ---------------------------------------------------------------------------

@pytest.fixture
def cluster():
    if find_pause_binary() is None:
        pytest.skip("no pause binary and no toolchain to build one")
    c = Cluster(ClusterConfig(
        num_nodes=1, process_runtime=True, kubelet_http=True,
        rc_sync_period=0.2, kubelet_resync=0.2)).start()
    yield c
    c.stop()


def test_real_http_server_pod_probe_logs_exec(cluster):
    port = free_port()
    probe = api.Probe(http_get=api.HTTPGetAction(port=port, path="/"),
                      initial_delay_seconds=3, timeout_seconds=2)
    pod = mk_pod("webserver",
                 ["python3", "-u", "-m", "http.server", str(port),
                  "--bind", "127.0.0.1"],
                 probe=probe)
    cluster.client.pods().create(pod)
    assert cluster.wait_pods_running(1, timeout=30.0), "pod never ran"

    # the pod is a real server: a real HTTP request succeeds (this is also
    # what the kubelet's liveness probe hits every sync). Running means the
    # process started; give it a moment to bind its socket.
    deadline = time.time() + 15
    status = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/",
                                        timeout=5) as r:
                status = r.status
                break
        except OSError:
            time.sleep(0.2)
    assert status == 200, "pod HTTP server never answered"

    # real logs via the kubelet server (kubectl log path)
    deadline = time.time() + 10
    logs = ""
    while time.time() < deadline:
        logs = cluster.pod_logs("default", "webserver")
        if "GET /" in logs:
            break
        time.sleep(0.2)
    assert "GET /" in logs, f"no request log, got: {logs!r}"

    # real exec via the kubelet server /run endpoint (kubectl exec path)
    rc, out = cluster.pod_exec("default", "webserver", "main",
                               ["echo", "exec-through-kubelet"])
    assert rc == 0 and "exec-through-kubelet" in out

    # pod status carries the loopback pod IP from the pause sandbox
    live = cluster.client.pods().get("webserver")
    assert live.status.phase == api.PodRunning
    assert live.status.pod_ip == "127.0.0.1"


def test_restart_policy_always_restarts_killed_process(cluster):
    pod = mk_pod("worker", ["python3", "-c", "import time; time.sleep(300)"])
    cluster.client.pods().create(pod)
    assert cluster.wait_pods_running(1, timeout=30.0)
    handle = cluster.nodes["node-0"]
    rt: ProcessRuntime = handle.runtime

    def main_records():
        return [r for r in rt.list_containers(include_dead=True)
                if r.parsed and r.parsed[0] == "main"]

    [rec] = main_records()
    time.sleep(0.5)  # let the container settle past the spawn-kill guard
    pid = rt._procs[rec.id].popen.pid
    os.kill(pid, signal.SIGKILL)  # the process dies out from under us
    # kubelet notices the dead container and starts attempt 1
    assert cluster.wait_for(
        lambda: any(r.running and r.parsed[4] == 1 for r in main_records()),
        timeout=30.0), "killed container was not restarted"


def test_restart_policy_never_leaves_pod_dead(cluster):
    pod = mk_pod("oneshot", ["python3", "-c", "print('done')"],
                 restart=api.RestartPolicyNever)
    cluster.client.pods().create(pod)
    handle = cluster.nodes["node-0"]
    rt: ProcessRuntime = handle.runtime

    def attempts():
        return [r.parsed[4] for r in rt.list_containers(include_dead=True)
                if r.parsed and r.parsed[0] == "main"]

    assert cluster.wait_for(lambda: len(attempts()) >= 1, timeout=30.0)
    time.sleep(1.0)  # several resync periods
    assert attempts() == [0], f"RestartPolicy Never restarted: {attempts()}"


def test_exec_stream_live_output(runtime):
    """ProcessRuntime streams output chunks as produced, exit code last."""
    pod = mk_pod("streamer", command=["sleep", "30"])
    rt = runtime
    rt.pull_image("local/script")
    cid = rt.create_container(pod, pod.spec.containers[0], 0)
    rt.start_container(cid)
    items = list(rt.exec_stream_in_container(
        cid, ["sh", "-c", "echo first; echo second; exit 3"]))
    assert items[-1] == 3
    out = b"".join(i for i in items[:-1])
    assert out == b"first\nsecond\n"


def test_process_runtime_container_stats(runtime):
    """ProcessRuntimeStatsProvider reads real /proc accounting for a live
    container process (the cAdvisor per-container seam)."""
    from kubernetes_tpu.kubelet.stats import ProcessRuntimeStatsProvider

    pod = mk_pod("stat-me", command=["sleep", "30"])
    pod.metadata.uid = "uid-stat"
    rt = runtime
    rt.pull_image("local/script")
    cid = rt.create_container(pod, pod.spec.containers[0], 0)
    rt.start_container(cid)
    provider = ProcessRuntimeStatsProvider(rt)
    st = provider.container_stats("uid-stat", "main")
    assert st is not None
    assert st.memory_usage_bytes > 0          # VmRSS of a live sleep
    assert st.cpu_usage_core_seconds >= 0.0
    assert provider.container_stats("uid-stat", "ghost") is None
    # node-level numbers still come from /proc
    node = provider.node_stats()
    assert node.memory_usage_bytes > 0


def test_group_stats_include_forked_children(runtime):
    """Accounting covers the whole process group, not just the leader."""
    pod = mk_pod("forky", command=["sh", "-c",
                                   "sleep 30 & sleep 30 & wait"])
    pod.metadata.uid = "uid-forky"
    rt = runtime
    rt.pull_image("local/script")
    cid = rt.create_container(pod, pod.spec.containers[0], 0)
    rt.start_container(cid)
    time.sleep(0.3)  # children spawn
    gs = rt.group_stats(cid)
    assert gs is not None
    cpu, rss = gs
    # leader sh + two sleeps: group RSS well above a single sleep's
    assert rss > 200_000
    rt.stop_container(cid)
    assert rt.group_stats(cid) is None  # dead group -> None, not zeros


class TestPythonPauseFallback:
    """Toolchain-less environments: the pure-Python sandbox
    (native/pause/pause.py) stands in for the native pause binary, so the
    flagship runtime never skips for lack of g++."""

    def _fallback_runtime(self, tmp_path):
        script = os.path.join(os.path.dirname(__file__), "..",
                              "native", "pause", "pause.py")
        return ProcessRuntime(str(tmp_path), pause_binary=script)

    def test_sandbox_runs_and_stops_gracefully(self, tmp_path):
        rt = self._fallback_runtime(tmp_path)
        try:
            assert rt.pause_cmd[0].endswith("python") \
                or "python" in os.path.basename(rt.pause_cmd[0])
            pod = mk_pod("fb", ["true"])
            cid = rt.create_infra_container(pod)
            rt.start_container(cid)
            time.sleep(0.5)
            recs = {r.id: r for r in rt.list_containers(include_dead=True)}
            assert recs[cid].running
            rt.stop_container(cid)
            recs = {r.id: r for r in rt.list_containers(include_dead=True)}
            assert not recs[cid].running
            assert recs[cid].exit_code == 0  # graceful TERM exit
        finally:
            rt.shutdown()

    def test_commandless_container_holds_slot_via_fallback(self, tmp_path):
        rt = self._fallback_runtime(tmp_path)
        try:
            rt.pull_image("img:slot")
            pod = mk_pod("fb2", ["true"])
            c = api.Container(name="slot", image="img:slot")
            cid = rt.create_container(pod, c, 0)
            rt.start_container(cid)
            time.sleep(0.5)
            recs = {r.id: r for r in rt.list_containers(include_dead=True)}
            assert recs[cid].running
        finally:
            rt.shutdown()
