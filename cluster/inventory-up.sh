#!/usr/bin/env bash
# Provider-driven cluster bring-up (ref: cluster/kube-up.sh — the
# reference reads a cloud provider's config and provisions master +
# minions; here the provider seam does the discovery and the components
# come up one process per instance, DESIGN.md-style).
#
# Reads a cloud inventory (the "inventory" provider's JSON: zone +
# instances, each with a DISTINCT loopback address and optional
# cpu/memory capacity), then launches:
#   - kube-store + apiserver (SO_REUSEPORT-ready) with
#     --cloud-provider inventory
#   - controller-manager with --cloud-provider inventory, which
#     registers every discovered instance as a Node (capacity and
#     addresses from the inventory, zone from the Zones facet)
#   - scheduler (tpu-batch), one kubelet PER INSTANCE bound to that
#     instance's address on the STANDARD kubelet port — so the
#     monitoring/logging addons and the apiserver's node proxy reach
#     each node at <address>:10250 exactly like a real fleet
#   - the dns/monitoring/logging addons.
#
# Usage: cluster/inventory-up.sh inventory.json [port]
# Inventory example (distinct 127/8 loopback addresses):
#   {"zone": {"failure_domain": "cell-a", "region": "local"},
#    "instances": [
#      {"name": "node-a", "addresses": ["127.0.1.1"], "cpu": "8",
#       "memory": "16Gi"},
#      {"name": "node-b", "addresses": ["127.0.1.2"], "cpu": "8",
#       "memory": "16Gi"}]}

set -euo pipefail
INVENTORY="$(realpath "${1:?usage: cluster/inventory-up.sh inventory.json [port]}")"
cd "$(dirname "$0")/.."

PORT="${2:-8080}"
STORE_PORT=$((PORT + 1))
MASTER="http://127.0.0.1:${PORT}"
PIDS=()
trap 'kill "${PIDS[@]}" 2>/dev/null || true' EXIT INT TERM

# name:address pairs up front — fail fast on a malformed inventory
PAIRS=$(python - "$INVENTORY" <<'EOF'
import json, sys
data = json.load(open(sys.argv[1]))
for inst in data["instances"]:
    addrs = inst.get("addresses") or []
    if not addrs:
        sys.exit(f"instance {inst['name']!r} needs a distinct loopback "
                 f"address (e.g. 127.0.1.N) so its kubelet is reachable "
                 f"on the standard port")
    print(f"{inst['name']}:{addrs[0]}")
EOF
)

python -m kubernetes_tpu.cmd.storeserver --port "${STORE_PORT}" &
PIDS+=($!)
KTPU_CLOUD_INVENTORY="${INVENTORY}" \
python -m kubernetes_tpu.cmd.apiserver --port "${PORT}" \
    --store-server "127.0.0.1:${STORE_PORT}" --reuse-port \
    --cloud-provider inventory &
PIDS+=($!)
for i in $(seq 1 60); do
    curl -sf "${MASTER}/healthz" >/dev/null 2>&1 && break
    sleep 0.5
done
curl -sf "${MASTER}/healthz" >/dev/null 2>&1 \
    || { echo "apiserver failed to become healthy on ${MASTER}" >&2; exit 1; }

KTPU_CLOUD_INVENTORY="${INVENTORY}" \
python -m kubernetes_tpu.cmd.controller_manager --master "${MASTER}" \
    --cloud-provider inventory &
PIDS+=($!)
python -m kubernetes_tpu.cmd.scheduler --master "${MASTER}" \
    --algorithm tpu-batch &
PIDS+=($!)

# one kubelet per discovered instance, each on its own loopback address
# at the standard port (the fleet shape addons and node proxy expect)
for pair in ${PAIRS}; do
    name="${pair%%:*}"
    addr="${pair#*:}"
    python -m kubernetes_tpu.cmd.kubelet --api-servers "${MASTER}" \
        --hostname-override "${name}" --address "${addr}" --port 10250 \
        --root-dir "/tmp/ktpu-${name}" &
    PIDS+=($!)
done

python -m kubernetes_tpu.cmd.dns --master "${MASTER}" --port 10053 &
PIDS+=($!)
python -m kubernetes_tpu.cmd.monitoring --master "${MASTER}" --port 10251 &
PIDS+=($!)
python -m kubernetes_tpu.cmd.logging --master "${MASTER}" --port 10252 &
PIDS+=($!)

echo "inventory cluster up: ${MASTER}"
echo "  instances: ${PAIRS}"
echo "  dashboard: ${MASTER}/ui/"
wait
