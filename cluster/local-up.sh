#!/usr/bin/env bash
# Bring up a local all-in-one cluster (ref: cluster/kube-up.sh + hack's
# local-up-cluster; the cloud provider scripts' slot — gce/aws/azure — is
# filled by the 'local' provider since this framework targets TPU pods,
# not cloud VMs).
#
# Usage: cluster/local-up.sh [port] [nodes]

set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${1:-8080}"
NODES="${2:-2}"

echo "Starting kubernetes-tpu standalone: apiserver :${PORT}, ${NODES} nodes"
echo "  dashboard: http://127.0.0.1:${PORT}/ui/"
echo "  kubectl:   python -m kubernetes_tpu.cmd.hyperkube kubectl --namespace default get pods"
exec python -m kubernetes_tpu.cmd.standalone --port "${PORT}" --nodes "${NODES}"
