#!/usr/bin/env bash
# Bring up the control plane as SEPARATE processes — apiserver,
# controller-manager, scheduler, one kubelet — wired only through HTTP,
# the way the reference deploys its binaries (ref: cluster/saltbase
# service layout). Ctrl-C tears everything down.
#
# KTPU_DATA_DIR=<dir> makes the cluster CRASH-DURABLE
# (docs/design/ha.md): a kube-store process owns a DurableStore
# (WAL + snapshots) on that directory and the apiserver speaks to it
# over --store-server — kill any process, restart the stack on the same
# dir, and the cluster resumes with its resourceVersions intact. Empty
# keeps the historical in-memory in-process store.
#
# Usage: cluster/multi-process-up.sh [port]

set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${1:-8080}"
MASTER="http://127.0.0.1:${PORT}"
PIDS=()
cleanup() { kill "${PIDS[@]}" 2>/dev/null || true; }
trap cleanup EXIT INT TERM

SOLVERD_PORT="${SOLVERD_PORT:-10450}"
KTPU_DATA_DIR="${KTPU_DATA_DIR:-}"
STORE_PORT="${STORE_PORT:-2379}"
STORE_METRICS_PORT="${STORE_METRICS_PORT:-10460}"

if [[ -n "${KTPU_DATA_DIR}" ]]; then
    mkdir -p "${KTPU_DATA_DIR}"
    python -m kubernetes_tpu.cmd.storeserver --port "${STORE_PORT}" \
        --data-dir "${KTPU_DATA_DIR}" \
        --metrics-port "${STORE_METRICS_PORT}" &
    PIDS+=($!)
    # the store must answer before the apiserver's first list
    for _ in $(seq 1 60); do
        if (exec 3<>"/dev/tcp/127.0.0.1/${STORE_PORT}") 2>/dev/null; then
            exec 3>&- 3<&-
            break
        fi
        sleep 0.5
    done
    python -m kubernetes_tpu.cmd.apiserver --port "${PORT}" \
        --store-server "127.0.0.1:${STORE_PORT}" &
else
    python -m kubernetes_tpu.cmd.apiserver --port "${PORT}" &
fi
PIDS+=($!)
sleep 1
python -m kubernetes_tpu.cmd.controller_manager --master "${MASTER}" &
PIDS+=($!)
# the shared solver daemon: every tpu-batch scheduler worker points at it
# (waves coalesce into batched solves in one hot runtime); schedulers fall
# back to in-process solving automatically if it dies
python -m kubernetes_tpu.cmd.solverd --port "${SOLVERD_PORT}" &
PIDS+=($!)
# the daemon must own its socket before the scheduler's first wave, or
# the RemoteSolver starts out in its unhealthy-fallback cooldown
for _ in $(seq 1 60); do
    if (exec 3<>"/dev/tcp/127.0.0.1/${SOLVERD_PORT}") 2>/dev/null; then
        exec 3>&- 3<&-
        break
    fi
    sleep 0.5
done
python -m kubernetes_tpu.cmd.scheduler --master "${MASTER}" \
    --algorithm tpu-batch --solver-addr "127.0.0.1:${SOLVERD_PORT}" &
PIDS+=($!)
python -m kubernetes_tpu.cmd.kubelet --api-servers "${MASTER}" \
    --hostname-override "$(hostname)" --register-node --port 10250 \
    --root-dir /tmp/kubelet-tpu &
PIDS+=($!)
# addons (ref: cluster/addons/{dns,cluster-monitoring,fluentd-elasticsearch})
python -m kubernetes_tpu.cmd.dns --master "${MASTER}" --port 10053 &
PIDS+=($!)
python -m kubernetes_tpu.cmd.monitoring --master "${MASTER}" --port 10251 &
PIDS+=($!)
python -m kubernetes_tpu.cmd.logging --master "${MASTER}" --port 10252 &
PIDS+=($!)

echo "control plane up: ${MASTER} (Ctrl-C to stop)"
echo "  solverd:    tcp://127.0.0.1:${SOLVERD_PORT}  (shared wave solver)"
echo "  dns:        udp://127.0.0.1:10053  (<svc>.<ns>.cluster.local)"
echo "  monitoring: http://127.0.0.1:10251/api/v1/model"
echo "  logging:    http://127.0.0.1:10252/logs?namespace=default"
wait
