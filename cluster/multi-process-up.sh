#!/usr/bin/env bash
# Bring up the control plane as SEPARATE processes — apiserver,
# controller-manager, scheduler, one kubelet — wired only through HTTP,
# the way the reference deploys its binaries (ref: cluster/saltbase
# service layout). Ctrl-C tears everything down.
#
# Usage: cluster/multi-process-up.sh [port]

set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${1:-8080}"
MASTER="http://127.0.0.1:${PORT}"
PIDS=()
cleanup() { kill "${PIDS[@]}" 2>/dev/null || true; }
trap cleanup EXIT INT TERM

python -m kubernetes_tpu.cmd.apiserver --port "${PORT}" &
PIDS+=($!)
sleep 1
python -m kubernetes_tpu.cmd.controller_manager --master "${MASTER}" &
PIDS+=($!)
python -m kubernetes_tpu.cmd.scheduler --master "${MASTER}" &
PIDS+=($!)
python -m kubernetes_tpu.cmd.kubelet --api-servers "${MASTER}" \
    --hostname-override "$(hostname)" --register-node --port 10250 \
    --root-dir /tmp/kubelet-tpu &
PIDS+=($!)
# addons (ref: cluster/addons/{dns,cluster-monitoring,fluentd-elasticsearch})
python -m kubernetes_tpu.cmd.dns --master "${MASTER}" --port 10053 &
PIDS+=($!)
python -m kubernetes_tpu.cmd.monitoring --master "${MASTER}" --port 10251 &
PIDS+=($!)
python -m kubernetes_tpu.cmd.logging --master "${MASTER}" --port 10252 &
PIDS+=($!)

echo "control plane up: ${MASTER} (Ctrl-C to stop)"
echo "  dns:        udp://127.0.0.1:10053  (<svc>.<ns>.cluster.local)"
echo "  monitoring: http://127.0.0.1:10251/api/v1/model"
echo "  logging:    http://127.0.0.1:10252/logs?namespace=default"
wait
