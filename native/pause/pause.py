"""Pure-Python pod-sandbox fallback for environments without a C++
toolchain (and no prebuilt ``pause``).

Mirrors native/pause/pause.cc, our redesign of the reference's one
native artifact (ref: third_party/pause/pause.asm:44-55 — a minimal
process that parks forever and exits cleanly on termination, holding
the pod sandbox alive):

- parks until SIGTERM/SIGINT, then exits 0 (graceful);
- stray-signal hardening: ProcessRuntime spawns the sandbox with TERM
  blocked (spawn-time strays must not kill a fresh sandbox); after
  installing handlers this script unblocks and discards at most ONE
  TERM arriving inside the startup window, exactly like pause.cc. The
  runtime compensates by re-sending TERM every 0.5s during a graceful
  stop, so a real stop is never lost.
"""

import signal
import sys
import time

_T0 = time.monotonic()
_STRAY_WINDOW_S = 0.25
_strays = 0


def _on_term(signum, frame):
    global _strays
    if time.monotonic() - _T0 < _STRAY_WINDOW_S and _strays == 0:
        _strays = 1  # spawn-time stray: discard once
        return
    sys.exit(0)


signal.signal(signal.SIGTERM, _on_term)
signal.signal(signal.SIGINT, _on_term)
try:
    signal.pthread_sigmask(signal.SIG_UNBLOCK,
                           {signal.SIGTERM, signal.SIGINT})
except (AttributeError, ValueError):
    pass

while True:
    time.sleep(3600)
