// The pod infra ("pause") container binary.
//
// ref: third_party/pause/pause.asm — the reference's only native component:
// a minimal executable whose sole job is to exist, holding the pod's
// network/IPC namespaces open while real containers come and go around it
// (ref: pkg/kubelet/kubelet.go:1025 createPodInfraContainer).
//
// The reference issues one bare pause() syscall and exits when any signal
// arrives. This version keeps the same "do nothing, cheaply" contract but
// terminates cleanly on SIGINT/SIGTERM (exit 0) so pod teardown is graceful
// under runtimes that deliver TERM before KILL, and loops on other wakeups
// (e.g. SIGCHLD when acting as PID 1) instead of dying.
//
// Build: `make` here, or `make -C native` from the repo root. Static,
// no libc-beyond-syscall dependencies in the hot path.

#include <csignal>
#include <cstdlib>
#include <unistd.h>

namespace {

volatile sig_atomic_t shutting_down = 0;

void handle_terminate(int) { shutting_down = 1; }

}  // namespace

int main() {
  struct sigaction sa = {};
  sa.sa_handler = handle_terminate;
  sigaction(SIGINT, &sa, nullptr);
  sigaction(SIGTERM, &sa, nullptr);

  // Spawn-kill hardening: the runtime may start us with SIGTERM/SIGINT
  // blocked because some supervised environments deliver a stray TERM to
  // freshly-spawned processes before any handler can install. Discard
  // exactly one pending stray (deliver it into SIG_IGN), then restore the
  // graceful handler and unblock — later, legitimate TERMs still land.
  sigset_t pending;
  sigpending(&pending);
  if (sigismember(&pending, SIGTERM) || sigismember(&pending, SIGINT)) {
    struct sigaction ign = {};
    ign.sa_handler = SIG_IGN;
    sigaction(SIGTERM, &ign, nullptr);
    sigaction(SIGINT, &ign, nullptr);
    sigset_t unblock;
    sigemptyset(&unblock);
    sigaddset(&unblock, SIGTERM);
    sigaddset(&unblock, SIGINT);
    sigprocmask(SIG_UNBLOCK, &unblock, nullptr);  // stray delivered, ignored
    sigaction(SIGTERM, &sa, nullptr);
    sigaction(SIGINT, &sa, nullptr);
  } else {
    sigset_t unblock;
    sigemptyset(&unblock);
    sigaddset(&unblock, SIGTERM);
    sigaddset(&unblock, SIGINT);
    sigprocmask(SIG_UNBLOCK, &unblock, nullptr);
  }

  // Reap children if we are PID 1 of the sandbox: ignore SIGCHLD with
  // SA_NOCLDWAIT so zombies never accumulate.
  struct sigaction reap = {};
  reap.sa_handler = SIG_IGN;
  reap.sa_flags = SA_NOCLDWAIT;
  sigaction(SIGCHLD, &reap, nullptr);

  while (!shutting_down) {
    pause();  // sleeps until any signal; zero CPU while parked
  }
  return 0;
}
