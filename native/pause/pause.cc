// The pod infra ("pause") container binary.
//
// ref: third_party/pause/pause.asm — the reference's only native component:
// a minimal executable whose sole job is to exist, holding the pod's
// network/IPC namespaces open while real containers come and go around it
// (ref: pkg/kubelet/kubelet.go:1025 createPodInfraContainer).
//
// The reference issues one bare pause() syscall and exits when any signal
// arrives. This version keeps the same "do nothing, cheaply" contract but
// terminates cleanly on SIGINT/SIGTERM (exit 0) so pod teardown is graceful
// under runtimes that deliver TERM before KILL, and loops on other wakeups
// (e.g. SIGCHLD when acting as PID 1) instead of dying.
//
// Build: `make` here, or `make -C native` from the repo root. Static,
// no libc-beyond-syscall dependencies in the hot path.

#include <csignal>
#include <cstdlib>
#include <unistd.h>

namespace {

volatile sig_atomic_t shutting_down = 0;

void handle_terminate(int) { shutting_down = 1; }

}  // namespace

int main() {
  struct sigaction sa = {};
  sa.sa_handler = handle_terminate;
  sigaction(SIGINT, &sa, nullptr);
  sigaction(SIGTERM, &sa, nullptr);

  // Reap children if we are PID 1 of the sandbox: ignore SIGCHLD with
  // SA_NOCLDWAIT so zombies never accumulate.
  struct sigaction reap = {};
  reap.sa_handler = SIG_IGN;
  reap.sa_flags = SA_NOCLDWAIT;
  sigaction(SIGCHLD, &reap, nullptr);

  while (!shutting_down) {
    pause();  // sleeps until any signal; zero CPU while parked
  }
  return 0;
}
