// The pod infra ("pause") container binary.
//
// ref: third_party/pause/pause.asm — the reference's only native component:
// a minimal executable whose sole job is to exist, holding the pod's
// network/IPC namespaces open while real containers come and go around it
// (ref: pkg/kubelet/kubelet.go:1025 createPodInfraContainer).
//
// The reference issues one bare pause() syscall and exits when any signal
// arrives. This version keeps the same "do nothing, cheaply" contract but
// terminates cleanly on SIGINT/SIGTERM (exit 0) so pod teardown is graceful
// under runtimes that deliver TERM before KILL, and loops on other wakeups
// (e.g. SIGCHLD when acting as PID 1) instead of dying.
//
// Spawn-kill hardening: some supervised environments deliver one stray
// SIGTERM to freshly-spawned processes within ~1ms of exec. The runtime
// spawns us with TERM/INT blocked (kubelet/process_runtime.py) so the stray
// parks as pending until our handler is installed; the handler then treats
// AT MOST ONE terminate signal arriving inside a short startup window as
// that stray and discards it. Every later signal — or a second early one —
// shuts us down. The runtime re-sends TERM during its grace period, so even
// a legitimate stop that lands inside the stray window only costs one
// re-send, never a KILL escalation. (This replaces an earlier sigpending/
// SIG_IGN handshake that could eat a legitimate TERM arriving between its
// pending-check and re-arm — the cause of a 137-on-graceful-stop flake.)
//
// Build: `make` here, or `make -C native` from the repo root. Static,
// no libc-beyond-syscall dependencies in the hot path.

#include <csignal>
#include <ctime>
#include <unistd.h>

namespace {

volatile sig_atomic_t shutting_down = 0;
volatile sig_atomic_t stray_budget = 1;
struct timespec start_ts;

// Window after exec inside which a single terminate signal is presumed to
// be the supervisor's spawn-kill stray rather than a real stop request.
constexpr long kStrayWindowNs = 250L * 1000 * 1000;  // 250ms

void handle_terminate(int) {
  // clock_gettime is async-signal-safe (POSIX.1-2008).
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  long long elapsed_ns =
      (long long)(now.tv_sec - start_ts.tv_sec) * 1000000000LL +
      (now.tv_nsec - start_ts.tv_nsec);
  if (elapsed_ns < kStrayWindowNs && stray_budget > 0) {
    stray_budget = 0;  // discard exactly one early stray
    return;
  }
  shutting_down = 1;
}

}  // namespace

int main() {
  clock_gettime(CLOCK_MONOTONIC, &start_ts);

  struct sigaction sa = {};
  sa.sa_handler = handle_terminate;
  // Serialize TERM/INT delivery: without this, two pending signals could
  // nest their handlers and both pass the stray_budget check, discarding a
  // legitimate stop alongside the stray.
  sigemptyset(&sa.sa_mask);
  sigaddset(&sa.sa_mask, SIGTERM);
  sigaddset(&sa.sa_mask, SIGINT);
  sigaction(SIGINT, &sa, nullptr);
  sigaction(SIGTERM, &sa, nullptr);

  // Reap children if we are PID 1 of the sandbox: ignore SIGCHLD with
  // SA_NOCLDWAIT so zombies never accumulate.
  struct sigaction reap = {};
  reap.sa_handler = SIG_IGN;
  reap.sa_flags = SA_NOCLDWAIT;
  sigaction(SIGCHLD, &reap, nullptr);

  // Handlers are armed — release any signals the runtime spawned us with
  // blocked. A pending stray delivers straight into handle_terminate, which
  // classifies it by arrival time instead of guessing from sigpending.
  sigset_t unblock;
  sigemptyset(&unblock);
  sigaddset(&unblock, SIGTERM);
  sigaddset(&unblock, SIGINT);
  sigprocmask(SIG_UNBLOCK, &unblock, nullptr);

  while (!shutting_down) {
    pause();  // sleeps until any signal; zero CPU while parked
  }
  return 0;
}
