"""Version stamping (ref: pkg/version/ — git-derived build info served at
/version by the apiserver and printed by `ktpu version`)."""

from __future__ import annotations

import platform
from dataclasses import dataclass

__all__ = ["Info", "get"]

MAJOR = "0"
MINOR = "1"
GIT_VERSION = "v0.1.0-tpu"


@dataclass(frozen=True)
class Info:
    """ref: pkg/version/version.go Info struct."""

    major: str
    minor: str
    git_version: str
    git_commit: str
    platform: str

    def as_dict(self) -> dict:
        return {"major": self.major, "minor": self.minor,
                "gitVersion": self.git_version, "gitCommit": self.git_commit,
                "platform": self.platform}

    def __str__(self) -> str:
        return self.git_version


def get() -> Info:
    return Info(major=MAJOR, minor=MINOR, git_version=GIT_VERSION,
                git_commit="", platform=f"{platform.system().lower()}/{platform.machine()}")
