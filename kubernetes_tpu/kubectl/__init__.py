"""kubectl-equivalent CLI layer (ref: pkg/kubectl/).

The reference's CLI is a cobra command tree over a generic resource
Builder/Visitor pipeline (ref: pkg/kubectl/resource/builder.go:36) plus
per-kind printers and imperative helpers (resize, stop, rolling-update,
run, expose). The rebuild keeps the same layering:

- ``resource``        — Builder -> Info -> Visitor pipeline
- ``printers``        — human/json/yaml/template printers
- ``describe``        — per-kind describers
- ``generators``      — run-container and expose generators
- ``resize``/``stop``/``rolling_updater`` — imperative cluster surgery
- ``cmd``             — the argparse command tree (cobra equivalent)
"""

from kubernetes_tpu.kubectl.cmd import KubectlError, main, run_kubectl  # noqa: F401
