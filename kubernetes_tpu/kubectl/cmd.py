"""The kubectl command tree (ref: pkg/kubectl/cmd/cmd.go).

The reference builds a cobra tree whose commands share a ``Factory`` that
supplies the client, mapper, printers and describers (``cmd.go NewFactory``).
Here the tree is argparse subcommands over the same Factory seam, so tests
(and the hyperkube-style binaries) can inject an in-process client.

Commands (parity with pkg/kubectl/cmd/):
get, describe, create, update, delete, label, namespace, log, run-container,
expose, resize, stop, rolling-update, version, api-versions, cluster-info,
config (view/use-context/set-context — see clientcmd).
"""

from __future__ import annotations

import argparse
import sys
from typing import Callable, List, Optional

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.latest import VERSIONS, scheme as default_scheme
from kubernetes_tpu.api.meta import default_rest_mapper
from kubernetes_tpu.kubectl import generators
from kubernetes_tpu.kubectl import scale as scalepkg
from kubernetes_tpu.kubectl.describe import describe as describe_obj
from kubernetes_tpu.kubectl.printers import printer_for
from kubernetes_tpu.kubectl.resource import Builder, ResourceError, resolve_resource
from kubernetes_tpu import version as versionpkg

__all__ = ["Factory", "KubectlError", "run_kubectl", "main"]


class KubectlError(Exception):
    pass


class Factory:
    """DI seam (ref: cmd.go Factory struct: Mapper/Typer/Client/Printer...)."""

    def __init__(self, client, scheme=None, mapper=None,
                 out=None, err=None, stdin=None,
                 pod_logs: Optional[Callable[[str, str, str], str]] = None,
                 pod_exec: Optional[Callable] = None,
                 node_locator: Optional[Callable[[str], Optional[str]]] = None,
                 apiserver_url: str = ""):
        self.client = client
        self.scheme = scheme or default_scheme
        self.mapper = mapper or default_rest_mapper()
        self.out = out or sys.stdout
        self.err = err or sys.stderr
        self.stdin = stdin or sys.stdin
        self._pod_logs = pod_logs
        self._pod_exec = pod_exec
        self._node_locator = node_locator
        # base URL of the API server, for proxy/exec-over-HTTP; derived
        # from an HTTPTransport when not given explicitly
        self.apiserver_url = apiserver_url or \
            getattr(getattr(client, "transport", None), "base_url", "")

    def builder(self, ns: str = "") -> Builder:
        b = Builder(self.scheme, self.mapper)
        if ns:
            b.namespace(ns)
        return b

    def pod_logs(self, namespace: str, name: str, container: str = "") -> str:
        """Wired to the node's log endpoint by the cluster harness, or via
        the apiserver node proxy over HTTP
        (ref: kubectl/cmd/log.go fetches via apiserver /proxy/minions/...)."""
        if self._pod_logs is not None:
            return self._pod_logs(namespace, name, container)
        if self.apiserver_url:
            import urllib.request
            pod = self.client.resource("pods", namespace).get(name)
            host = pod.spec.host or pod.status.host
            if not host:
                raise KubectlError(f"pod {name} is not scheduled")
            container = container or pod.spec.containers[0].name
            url = (f"{self.apiserver_url}/api/{self.scheme.default_version}"
                   f"/proxy/nodes/{host}/containerLogs/{namespace}/{name}/"
                   f"{container}")
            with urllib.request.urlopen(url, timeout=10) as r:
                return r.read().decode()
        raise KubectlError(
            "log: no node log source configured (requires a running "
            "cluster with kubelet read-only servers)")

    def pod_exec(self, namespace: str, name: str, container: str,
                 command) -> tuple:
        """-> (exit_code, output). ref: kubectl/cmd/exec.go — runs through
        the node's /run endpoint (the SPDY-exec slot), reached via the
        apiserver node proxy; a nonzero exit arrives as a 500 whose body is
        still the command output."""
        if self._pod_exec is not None:
            return self._pod_exec(namespace, name, container, command)
        if self.apiserver_url:
            import urllib.error
            import urllib.parse
            import urllib.request
            pod = self.client.resource("pods", namespace).get(name)
            host = pod.spec.host or pod.status.host
            if not host:
                raise KubectlError(f"pod {name} is not scheduled")
            container = container or pod.spec.containers[0].name
            qs = urllib.parse.urlencode([("cmd", c) for c in command])
            url = (f"{self.apiserver_url}/api/{self.scheme.default_version}"
                   f"/proxy/nodes/{host}/run/{namespace}/{name}/"
                   f"{container}?{qs}")
            try:
                with urllib.request.urlopen(url, timeout=30) as r:
                    return 0, r.read().decode()
            except urllib.error.HTTPError as e:
                return 1, e.read().decode()
        raise KubectlError("exec: no node exec path configured")

    def kubelet_address(self, namespace: str, pod_name: str) -> tuple:
        """-> (host, "addr:port" of its kubelet) for port-forward."""
        pod = self.client.resource("pods", namespace).get(pod_name)
        host = pod.spec.host or pod.status.host
        if not host:
            raise KubectlError(f"pod {pod_name} is not scheduled")
        if self._node_locator is not None:
            loc = self._node_locator(host)
            if loc:
                return host, loc
        raise KubectlError(
            "port-forward: no kubelet locator configured")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubectl", description="kubectl controls the cluster manager.",
        exit_on_error=False)
    p.add_argument("--namespace", "-n", default="", help="namespace scope")
    p.add_argument("--api-version", default="", help="API version for output")
    sub = p.add_subparsers(dest="command")

    def out_flags(sp):
        sp.add_argument("--output", "-o", default="",
                        help="human|json|yaml|template|jsonpath")
        sp.add_argument("--template", "-t", default="",
                        help="template string for -o template/jsonpath")
        sp.add_argument("--no-headers", action="store_true")

    sp = sub.add_parser("get", exit_on_error=False)
    sp.add_argument("args", nargs="+")
    sp.add_argument("--selector", "-l", default="")
    sp.add_argument("--all-namespaces", action="store_true")
    sp.add_argument("--watch", "-w", action="store_true")
    out_flags(sp)

    sp = sub.add_parser("describe", exit_on_error=False)
    sp.add_argument("args", nargs=2, metavar=("RESOURCE", "NAME"))

    for verb in ("create", "update"):
        sp = sub.add_parser(verb, exit_on_error=False)
        sp.add_argument("--filename", "-f", action="append", required=True)

    sp = sub.add_parser("delete", exit_on_error=False)
    sp.add_argument("args", nargs="*")
    sp.add_argument("--filename", "-f", action="append", default=[])
    sp.add_argument("--selector", "-l", default="")

    sp = sub.add_parser("label", exit_on_error=False)
    sp.add_argument("args", nargs="+",
                    help="RESOURCE NAME KEY_1=VAL_1 ... KEY_N=VAL_N or KEY-")
    sp.add_argument("--overwrite", action="store_true")
    out_flags(sp)

    sp = sub.add_parser("namespace", exit_on_error=False)
    sp.add_argument("ns", nargs="?", default="")

    sp = sub.add_parser("log", exit_on_error=False)
    sp.add_argument("pod")
    sp.add_argument("container", nargs="?", default="")

    sp = sub.add_parser("run-container", aliases=["run"], exit_on_error=False)
    sp.add_argument("name")
    sp.add_argument("--image", required=True)
    sp.add_argument("--replicas", "-r", type=int, default=1)
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--labels", "-l", default="")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--overrides", default="")
    out_flags(sp)

    sp = sub.add_parser("expose", exit_on_error=False)
    sp.add_argument("name")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("--selector", default="")
    sp.add_argument("--service-name", default="")
    sp.add_argument("--container-port", "--target-port", type=int, default=0)
    sp.add_argument("--protocol", default="TCP")
    sp.add_argument("--create-external-load-balancer", action="store_true")
    sp.add_argument("--public-ip", default="")
    sp.add_argument("--dry-run", action="store_true")
    out_flags(sp)

    sp = sub.add_parser("resize", aliases=["scale"], exit_on_error=False)
    sp.add_argument("args", nargs=2, metavar=("RESOURCE", "NAME"))
    sp.add_argument("--replicas", type=int, required=True)
    sp.add_argument("--current-replicas", type=int, default=-1)
    sp.add_argument("--resource-version", default="")

    sp = sub.add_parser("stop", exit_on_error=False)
    sp.add_argument("args", nargs=2, metavar=("RESOURCE", "NAME"))

    sp = sub.add_parser("rolling-update", aliases=["rollingupdate"],
                        exit_on_error=False)
    sp.add_argument("old_name")
    sp.add_argument("--filename", "-f", required=True)
    sp.add_argument("--update-period", type=float, default=0.0)
    sp.add_argument("--timeout", type=float, default=60.0)

    sp = sub.add_parser("exec", exit_on_error=False)
    sp.add_argument("--pod", "-p", default="")
    sp.add_argument("--container", "-c", default="")
    sp.add_argument("words", nargs="*",
                    help="[POD] -- COMMAND [args...] (v0 form: -p POD CMD)")

    sp = sub.add_parser("port-forward", exit_on_error=False)
    sp.add_argument("--pod", "-p", default="")
    sp.add_argument("words", nargs="+",
                    help="[POD] LOCAL_PORT:POD_PORT [...]")
    sp.add_argument("--once", action="store_true",
                    help="serve one connection then exit (tests)")

    sp = sub.add_parser("proxy", exit_on_error=False)
    sp.add_argument("--port", type=int, default=8001)
    sp.add_argument("--www", default="", help="ignored; parity flag")
    sp.add_argument("--api-prefix", default="/api")
    sp.add_argument("--once", action="store_true",
                    help="serve one request then exit (tests)")

    for verb in ("cordon", "uncordon", "drain"):
        sp = sub.add_parser(verb, exit_on_error=False)
        sp.add_argument("node")

    sub.add_parser("version", exit_on_error=False)
    sub.add_parser("api-versions", exit_on_error=False)
    sub.add_parser("cluster-info", aliases=["clusterinfo"], exit_on_error=False)

    sp = sub.add_parser("config", exit_on_error=False)
    sp.add_argument("config_args", nargs="+",
                    help="view | use-context NAME | set-cluster NAME "
                         "--server=... | set-context NAME --cluster=... "
                         "--user=... | set-credentials NAME --token=...")
    sp.add_argument("--kubeconfig", default="")
    sp.add_argument("--server", default="")
    sp.add_argument("--cluster", default="")
    sp.add_argument("--user", default="")
    sp.add_argument("--token", default="")
    sp.add_argument("--username", default="")
    sp.add_argument("--password", default="")
    return p


def _cmd_config(f: Factory, opts) -> int:
    """ref: pkg/kubectl/cmd/config/ (view/set-cluster/set-context/
    set-credentials/use-context over the kubeconfig file)."""
    import os

    import yaml as _yaml

    from kubernetes_tpu.client import clientcmd

    sub = opts.config_args[0]
    path = opts.kubeconfig or os.environ.get("KUBECONFIG", "").split(os.pathsep)[0] \
        or os.path.join(os.path.expanduser("~"), ".kube", "config")
    # Mutations operate on the single target file only — merging other
    # kubeconfigs here would copy their credentials into this file.
    cfg = clientcmd.KubeConfig()
    if os.path.exists(path):
        cfg = clientcmd.load_file(path)
    if sub == "view":
        _yaml.safe_dump(cfg.to_wire(), f.out, default_flow_style=False,
                        sort_keys=False)
        return 0
    if sub == "use-context":
        if len(opts.config_args) != 2:
            raise KubectlError("usage: config use-context NAME")
        if opts.config_args[1] not in cfg.contexts:
            raise KubectlError(f"no context exists with the name "
                               f"{opts.config_args[1]!r}")
        cfg.current_context = opts.config_args[1]
    elif sub == "set-cluster":
        cfg.clusters[opts.config_args[1]] = clientcmd.Cluster(server=opts.server)
    elif sub == "set-context":
        cfg.contexts[opts.config_args[1]] = clientcmd.Context(
            cluster=opts.cluster, user=opts.user)
    elif sub == "set-credentials":
        cfg.users[opts.config_args[1]] = clientcmd.AuthInfo(
            token=opts.token, username=opts.username, password=opts.password)
    else:
        raise KubectlError(f"unknown config subcommand {sub!r}")
    if os.path.dirname(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        _yaml.safe_dump(cfg.to_wire(), fh, default_flow_style=False,
                        sort_keys=False)
    return 0


def _print_infos(f: Factory, infos, ns: str, output: str, template: str,
                 no_headers: bool, version: str,
                 empty_resource: str = "pods") -> None:
    printer = printer_for(output, f.scheme, template=template,
                          no_headers=no_headers, version=version)
    if output in ("", "wide"):
        # group human output by resource so each table gets one header
        by_resource: dict = {}
        for info in infos:
            by_resource.setdefault(info.resource, []).append(info)
        first = True
        for resource, group in by_resource.items():
            if not first:
                f.out.write("\n")
            first = False
            lt = f.mapper.list_type_for(resource)
            lst = lt(items=[i.obj for i in group])
            printer.print_obj(lst, f.out)
    elif not infos and output in ("json", "yaml"):
        # zero matches still produce a well-formed document (the reference
        # prints an empty versioned List, not nothing)
        try:
            lt = f.mapper.list_type_for(empty_resource) or api.PodList
        except KeyError:
            lt = api.PodList
        printer.print_obj(lt(items=[]), f.out)
    else:
        for info in infos:
            printer.print_obj(info.obj, f.out)


def _cmd_get(f: Factory, ns: str, opts) -> int:
    b = f.builder(ns).selector(opts.selector) \
        .all_namespaces(opts.all_namespaces) \
        .resource_type_or_name(*opts.args)
    infos = b.infos(f.client)
    from kubernetes_tpu.kubectl.resource import resolve_resource
    empty_resource = resolve_resource(
        opts.args[0].split("/", 1)[0]) if opts.args else "pods"
    _print_infos(f, infos, ns, opts.output, opts.template,
                 opts.no_headers, opts.api_version,
                 empty_resource=empty_resource)
    if opts.watch:
        if len({i.resource for i in infos}) != 1:
            raise KubectlError("watch requires a single resource type")
        resource = infos[0].resource
        # resume from the printed list's resourceVersion so no event in the
        # list->watch gap is dropped (ref: cmd/get.go watch path)
        ns_arg = "" if opts.all_namespaces else (ns or "default")
        lst = f.client.resource(resource, ns_arg).list(
            label_selector=opts.selector)
        rv = lst.metadata.resource_version or ""
        w = f.client.resource(resource, ns_arg) \
            .watch(label_selector=opts.selector, resource_version=rv)
        printer = printer_for(opts.output, f.scheme, template=opts.template,
                              no_headers=True, version=opts.api_version)
        for ev in w:
            printer.print_obj(ev.object, f.out)
    return 0


def _cmd_create_or_update(f: Factory, ns: str, opts, update: bool) -> int:
    b = f.builder(ns).filename(*opts.filename).stdin(f.stdin)
    count = 0
    for info in b.infos():
        rc = f.client.resource(info.resource, info.namespace)
        if update:
            rc.update(info.obj)
            f.out.write(f"{info.name}\n")
        else:
            created = rc.create(info.obj)
            f.out.write(f"{created.metadata.name}\n")
        count += 1
    if count == 0:
        raise KubectlError("no objects passed to create")
    return 0


def _cmd_delete(f: Factory, ns: str, opts) -> int:
    b = f.builder(ns).selector(opts.selector)
    if opts.filename:
        b.filename(*opts.filename).stdin(f.stdin)
    if opts.args:
        b.resource_type_or_name(*opts.args)
    for info in b.infos(f.client):
        f.client.resource(info.resource, info.namespace).delete(info.name)
        f.out.write(f"{info.name}\n")
    return 0


def _cmd_label(f: Factory, ns: str, opts) -> int:
    """ref: cmd/label.go — add/remove labels with conflict detection."""
    args = opts.args
    if len(args) < 3:
        raise KubectlError("usage: label RESOURCE NAME KEY=VAL ... or KEY-")
    resource = resolve_resource(args[0], f.mapper)
    name = args[1]
    adds: dict = {}
    removes: List[str] = []
    for spec in args[2:]:
        if spec.endswith("-"):
            removes.append(spec[:-1])
        elif "=" in spec:
            k, _, v = spec.partition("=")
            adds[k] = v
        else:
            raise KubectlError(f"unknown label spec {spec!r}")
    namespaced = f.mapper.is_namespaced(resource)
    rc = f.client.resource(resource, (ns or "default") if namespaced else "")
    obj = rc.get(name)
    labels = obj.metadata.labels
    if not opts.overwrite:
        for k, v in adds.items():
            if k in labels and labels[k] != v:
                raise KubectlError(
                    f"'{k}' already has a value ({labels[k]}), and --overwrite "
                    f"is false")
    labels.update(adds)
    for k in removes:
        labels.pop(k, None)
    obj = rc.update(obj)
    if opts.output:
        printer = printer_for(opts.output, f.scheme, template=opts.template,
                              no_headers=opts.no_headers,
                              version=opts.api_version)
        printer.print_obj(obj, f.out)
    else:
        f.out.write(f"{name} labeled\n")
    return 0


def _cmd_resize(f: Factory, ns: str, opts) -> int:
    resource = resolve_resource(opts.args[0], f.mapper)
    if resource != "replicationcontrollers":
        raise KubectlError("resize is only supported for replicationcontrollers")
    precond = scalepkg.ResizePrecondition(opts.current_replicas,
                                          opts.resource_version)
    scalepkg.Resizer(f.client).resize(ns or "default", opts.args[1],
                                      opts.replicas, preconditions=precond)
    f.out.write("resized\n")
    return 0


def _cmd_stop(f: Factory, ns: str, opts) -> int:
    resource = resolve_resource(opts.args[0], f.mapper)
    reaper = scalepkg.reaper_for(resource, f.client)
    msg = reaper.stop(ns or "default", opts.args[1])
    f.out.write(msg + "\n")
    return 0


def _cmd_run(f: Factory, ns: str, opts) -> int:
    labels = generators.parse_labels(opts.labels)
    rc = generators.generate_rc(opts.name, opts.image, opts.replicas,
                                labels or None, opts.port)
    if not opts.dry_run:
        rc = f.client.resource("replicationcontrollers",
                               ns or "default").create(rc)
    printer = printer_for(opts.output, f.scheme, template=opts.template,
                          no_headers=opts.no_headers, version=opts.api_version)
    printer.print_obj(rc, f.out)
    return 0


def _cmd_expose(f: Factory, ns: str, opts) -> int:
    selector = generators.parse_labels(opts.selector)
    if not selector:
        # default to the target RC's selector (ref: cmd/expose.go)
        try:
            rc = f.client.resource("replicationcontrollers",
                                   ns or "default").get(opts.name)
            selector = dict(rc.spec.selector)
        except errors.StatusError:
            raise KubectlError(
                "--selector is required when no replication controller "
                "with that name exists")
    svc = generators.generate_service(
        opts.service_name or opts.name, selector, opts.port,
        container_port=opts.container_port, protocol=opts.protocol,
        create_external_load_balancer=opts.create_external_load_balancer,
        public_ips=[opts.public_ip] if opts.public_ip else None)
    if not opts.dry_run:
        svc = f.client.resource("services", ns or "default").create(svc)
    printer = printer_for(opts.output, f.scheme, template=opts.template,
                          no_headers=opts.no_headers, version=opts.api_version)
    printer.print_obj(svc, f.out)
    return 0


def _cmd_rolling_update(f: Factory, ns: str, opts) -> int:
    b = f.builder(ns).filename(opts.filename).stdin(f.stdin)
    infos = b.infos()
    if len(infos) != 1 or infos[0].resource != "replicationcontrollers":
        raise KubectlError(
            "rolling-update requires exactly one ReplicationController file")
    updater = scalepkg.RollingUpdater(f.client, ns or "default")
    final = updater.update(opts.old_name, infos[0].obj,
                           update_period=opts.update_period,
                           timeout=opts.timeout)
    f.out.write(f"{final.metadata.name}\n")
    return 0


def _cmd_cordon(f: Factory, opts, on: bool) -> int:
    """ref: kubectl cordon/uncordon/drain — flips ``spec.unschedulable``.

    ``drain`` is cordon plus hand-off: pods are not evicted inline (there
    is no synchronous eviction API here); the descheduler treats every
    movable pod on a cordoned node as a mandatory migration candidate and
    empties the node on its next wave.
    """
    rc = f.client.resource("nodes", "")
    node = rc.get(opts.node)
    already = bool(node.spec.unschedulable) == on
    if not already:
        node.spec.unschedulable = on
        rc.update(node)
    verb = "cordoned" if on else "uncordoned"
    f.out.write(f"node/{opts.node} {'already ' if already else ''}{verb}\n")
    if opts.command == "drain":
        f.out.write(f"node/{opts.node} draining "
                    f"(pods migrate on the next descheduler wave)\n")
    return 0


def _cmd_exec(f: Factory, ns: str, opts) -> int:
    """ref: cmd/exec.go — `exec -p POD -c CONTAINER CMD...` or
    `exec POD -- CMD...`."""
    words = list(opts.words)
    pod = opts.pod
    if not pod:
        if not words:
            raise KubectlError("exec: pod name required")
        pod = words.pop(0)
    if not words:
        raise KubectlError("exec: command required")
    code, out = f.pod_exec(ns or "default", pod, opts.container, words)
    f.out.write(out)
    return 0 if code == 0 else 1


def _cmd_port_forward(f: Factory, ns: str, opts) -> int:
    """ref: cmd/portforward.go — local listener tunneling to the pod's port
    through the kubelet's stream-upgrade endpoint."""
    import socket
    import threading

    from kubernetes_tpu.util.stream import relay_bidirectional

    words = list(opts.words)
    pod = opts.pod
    if not pod:
        pod = words.pop(0)
    if not words:
        raise KubectlError("port-forward: PORT or LOCAL:POD mapping required")
    mappings = []
    for w in words:
        local_s, _, remote_s = w.partition(":")
        local_port = int(local_s)
        mappings.append((local_port, int(remote_s) if remote_s else local_port))
    host, kubelet_addr = f.kubelet_address(ns or "default", pod)
    khost, _, kport = kubelet_addr.rpartition(":")

    def tunnel(conn, pod_port) -> bool:
        backend = None
        try:
            backend = socket.create_connection((khost, int(kport)), timeout=10)
            req = (f"POST /portForward/{ns or 'default'}/{pod}?port={pod_port} "
                   f"HTTP/1.1\r\nHost: {kubelet_addr}\r\n"
                   f"Content-Length: 0\r\n\r\n").encode()
            backend.sendall(req)
            buf = b""
            while b"\r\n\r\n" not in buf:  # read the upgrade response
                chunk = backend.recv(1024)
                if not chunk:
                    f.err.write("port-forward: kubelet closed the tunnel\n")
                    return False
                buf += chunk
            status_line = buf.split(b"\r\n", 1)[0]
            if b"101" not in status_line:
                f.err.write(f"port-forward: kubelet refused the tunnel: "
                            f"{status_line.decode(errors='replace')}\n")
                return False
            extra = buf.split(b"\r\n\r\n", 1)[1]
            if extra:
                conn.sendall(extra)
            relay_bidirectional(conn, backend, idle_timeout=60.0)
            return True
        except OSError as e:
            f.err.write(f"port-forward: {e}\n")
            return False
        finally:
            conn.close()
            if backend is not None:
                backend.close()

    listeners = []
    for local_port, pod_port in mappings:
        listener = socket.socket()
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", local_port))
        listener.listen(8)
        bound = listener.getsockname()[1]
        listeners.append((listener, pod_port))
        f.out.write(f"Forwarding from 127.0.0.1:{bound} -> {pod}:{pod_port} "
                    f"(node {host})\n")
    f.out.flush()

    def serve(listener, pod_port, once_result=None):
        while True:
            try:
                conn, _addr = listener.accept()
            except OSError:
                return
            ok = tunnel(conn, pod_port)
            if once_result is not None:
                once_result.append(ok)
                return

    try:
        if opts.once:
            # serve exactly one connection on the first mapping (tests)
            result: list = []
            serve(listeners[0][0], listeners[0][1], result)
            return 0 if result and result[0] else 1
        threads = [threading.Thread(target=serve, args=(l, p), daemon=True)
                   for l, p in listeners]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        for listener, _ in listeners:
            listener.close()


def _cmd_proxy(f: Factory, opts) -> int:
    """ref: cmd/proxy.go — local HTTP proxy to the apiserver."""
    import http.server
    import urllib.error
    import urllib.request

    if not f.apiserver_url:
        raise KubectlError("proxy requires an HTTP API server connection")
    base = f.apiserver_url
    prefix = opts.api_prefix

    class H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _relay(self):
            if not self.path.startswith(prefix):
                body = b"404: only " + prefix.encode() + b" is proxied\n"
                self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n) if n else None
            req = urllib.request.Request(base + self.path, data=body,
                                         method=self.command)
            if body is not None:
                req.add_header("Content-Type",
                               self.headers.get("Content-Type",
                                                "application/json"))
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    payload = r.read()
                    self.send_response(r.status)
                    ctype = r.headers.get("Content-Type", "application/json")
            except urllib.error.HTTPError as e:
                payload = e.read()
                self.send_response(e.code)
                ctype = e.headers.get("Content-Type", "application/json")
            except (urllib.error.URLError, OSError) as e:
                # apiserver unreachable -> a clean 502, not a dropped socket
                payload = f"502: apiserver unreachable: {e}\n".encode()
                self.send_response(502)
                ctype = "text/plain; charset=utf-8"
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _relay

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", opts.port), H)
    f.out.write(f"Starting to serve on 127.0.0.1:"
                f"{httpd.server_address[1]}\n")
    f.out.flush()
    try:
        if opts.once:
            httpd.timeout = 30
            httpd.handle_request()
        else:
            httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


def run_kubectl(argv: List[str], factory: Factory) -> int:
    """Parse + execute; returns a process exit code. All output goes to the
    factory's out/err streams (testable like cmd_test.go)."""
    parser = _build_parser()
    try:
        opts = parser.parse_args(argv)
    except argparse.ArgumentError as e:
        factory.err.write(f"error: {e}\n")
        return 1
    except SystemExit:
        return 1
    if not opts.command:
        parser.print_usage(factory.err)
        return 1
    ns = opts.namespace
    f = factory
    try:
        if opts.command == "get":
            return _cmd_get(f, ns, opts)
        if opts.command == "describe":
            resource = resolve_resource(opts.args[0], f.mapper)
            namespaced = f.mapper.is_namespaced(resource)
            f.out.write(describe_obj(f.client, resource,
                                     (ns or "default") if namespaced else "",
                                     opts.args[1]))
            return 0
        if opts.command == "create":
            return _cmd_create_or_update(f, ns, opts, update=False)
        if opts.command == "update":
            return _cmd_create_or_update(f, ns, opts, update=True)
        if opts.command == "delete":
            return _cmd_delete(f, ns, opts)
        if opts.command == "label":
            return _cmd_label(f, ns, opts)
        if opts.command == "namespace":
            if opts.ns:
                f.out.write(f"Using namespace {opts.ns}\n")
            else:
                f.out.write("Using namespace default\n")
            return 0
        if opts.command == "log":
            f.out.write(f.pod_logs(ns or "default", opts.pod, opts.container))
            return 0
        if opts.command == "exec":
            return _cmd_exec(f, ns, opts)
        if opts.command == "port-forward":
            return _cmd_port_forward(f, ns, opts)
        if opts.command == "proxy":
            return _cmd_proxy(f, opts)
        if opts.command in ("run-container", "run"):
            return _cmd_run(f, ns, opts)
        if opts.command == "expose":
            return _cmd_expose(f, ns, opts)
        if opts.command in ("resize", "scale"):
            return _cmd_resize(f, ns, opts)
        if opts.command == "stop":
            return _cmd_stop(f, ns, opts)
        if opts.command in ("rolling-update", "rollingupdate"):
            return _cmd_rolling_update(f, ns, opts)
        if opts.command in ("cordon", "uncordon", "drain"):
            return _cmd_cordon(f, opts, on=opts.command != "uncordon")
        if opts.command == "version":
            f.out.write(f"Client Version: {versionpkg.get()}\n")
            return 0
        if opts.command == "api-versions":
            f.out.write("Available Server Api Versions: "
                        + ", ".join(VERSIONS) + "\n")
            return 0
        if opts.command == "config":
            return _cmd_config(f, opts)
        if opts.command in ("cluster-info", "clusterinfo"):
            svcs = f.client.resource("services", "").list(
                label_selector="kubernetes.io/cluster-service=true")
            f.out.write("Kubernetes master is running\n")
            for s in svcs.items:
                f.out.write(f"  {s.metadata.name} is running at "
                            f"{s.spec.portal_ip}:{s.spec.port}\n")
            return 0
        factory.err.write(f"error: unknown command {opts.command!r}\n")
        return 1
    except (KubectlError, ResourceError, ValueError) as e:
        f.err.write(f"error: {e}\n")
        return 1
    except errors.StatusError as e:
        f.err.write(f"Error from server: {e}\n")
        return 1


class _NoClusterClient:
    """Placeholder client when no kubeconfig resolves — commands that never
    touch the server (config, version) still work; anything else gets a
    clear error instead of a traceback."""

    transport = None  # Factory introspects this attribute at construction

    def __init__(self, reason: str):
        self.reason = reason

    def resource(self, *a, **kw):
        raise KubectlError(
            f"no cluster configured: {self.reason} "
            f"(set one up with 'kubectl config set-cluster ...')")

    def __getattr__(self, name):
        raise KubectlError(
            f"no cluster configured: {self.reason} "
            f"(set one up with 'kubectl config set-cluster ...')")


def main(argv: Optional[List[str]] = None) -> int:
    """Entry point for the real binary: connects over HTTP using kubeconfig
    (ref: cmd/kubectl/kubectl.go). kubeconfig resolution is lazy-tolerant:
    `kubectl config ...` must work before any cluster is configured."""
    from kubernetes_tpu.client.clientcmd import ConfigError, client_from_config
    try:
        client = client_from_config()
    except ConfigError as e:
        client = _NoClusterClient(str(e))
    return run_kubectl(argv if argv is not None else sys.argv[1:],
                       Factory(client))
