"""Resize (scale) + reapers (stop) + rolling update.

ref: pkg/kubectl/resize.go (ReplicationControllerResizer: precondition
check + retry-on-conflict), pkg/kubectl/stop.go (RCReaper: resize to 0,
wait, delete), pkg/kubectl/rolling_updater.go (RollingUpdater.Update:
scale new RC up one replica at a time while scaling the old one down).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api

__all__ = ["ResizePrecondition", "Resizer", "RCReaper", "RollingUpdater",
           "RetryParams"]


@dataclass
class ResizePrecondition:
    """ref: resize.go ResizePrecondition{Size, ResourceVersion}."""

    size: int = -1                 # -1 = don't check
    resource_version: str = ""     # "" = don't check

    def validate(self, rc: api.ReplicationController) -> None:
        if self.size >= 0 and rc.spec.replicas != self.size:
            raise PreconditionError(
                f"Expected replicas to be {self.size}, was {rc.spec.replicas}")
        if self.resource_version and \
                rc.metadata.resource_version != self.resource_version:
            raise PreconditionError(
                f"Expected resource version {self.resource_version}, "
                f"was {rc.metadata.resource_version}")


class PreconditionError(Exception):
    pass


@dataclass
class RetryParams:
    """ref: resize.go RetryParams{Interval, Timeout}."""

    interval: float = 0.1
    timeout: float = 10.0


class Resizer:
    """ref: resize.go ReplicationControllerResizer."""

    def __init__(self, client):
        self.client = client

    def resize_simple(self, namespace: str, name: str,
                      preconditions: Optional[ResizePrecondition],
                      new_size: int) -> api.ReplicationController:
        rcs = self.client.resource("replicationcontrollers", namespace)
        rc = rcs.get(name)
        if preconditions:
            preconditions.validate(rc)
        rc.spec.replicas = new_size
        return rcs.update(rc)

    def resize(self, namespace: str, name: str, new_size: int,
               preconditions: Optional[ResizePrecondition] = None,
               retry: Optional[RetryParams] = None,
               wait_for_replicas: Optional[RetryParams] = None,
               ) -> api.ReplicationController:
        """Retry conflicts (ref: resize.go ResizeCondition + RetryConflict);
        optionally wait until status catches up."""
        retry = retry or RetryParams()
        deadline = time.monotonic() + retry.timeout
        while True:
            try:
                rc = self.resize_simple(namespace, name, preconditions, new_size)
                break
            except errors.StatusError as e:
                if not errors.is_conflict(e) or time.monotonic() >= deadline:
                    raise
                time.sleep(retry.interval)
        if wait_for_replicas:
            rcs = self.client.resource("replicationcontrollers", namespace)
            deadline = time.monotonic() + wait_for_replicas.timeout
            while time.monotonic() < deadline:
                rc = rcs.get(name)
                if rc.status.replicas == rc.spec.replicas:
                    return rc
                time.sleep(wait_for_replicas.interval)
            raise TimeoutError(
                f"timed out waiting for {namespace}/{name} to reach "
                f"{new_size} replicas (at {rc.status.replicas})")
        return rc


class RCReaper:
    """ref: stop.go ReplicationControllerReaper — resize to 0, wait for the
    manager to delete the pods, then delete the RC."""

    def __init__(self, client, interval: float = 0.1, timeout: float = 30.0):
        self.client = client
        self.interval = interval
        self.timeout = timeout

    def stop(self, namespace: str, name: str) -> str:
        resizer = Resizer(self.client)
        resizer.resize(namespace, name, 0,
                       retry=RetryParams(self.interval, self.timeout),
                       wait_for_replicas=RetryParams(self.interval, self.timeout))
        self.client.resource("replicationcontrollers", namespace).delete(name)
        return f"{name} stopped"


class PodReaper:
    """Pods have no children; plain delete (ref: stop.go falls through to
    ObjectReaper/plain deletion for other kinds)."""

    def __init__(self, client):
        self.client = client

    def stop(self, namespace: str, name: str) -> str:
        self.client.resource("pods", namespace).delete(name)
        return f"{name} stopped"


class ServiceReaper:
    def __init__(self, client):
        self.client = client

    def stop(self, namespace: str, name: str) -> str:
        self.client.resource("services", namespace).delete(name)
        return f"{name} stopped"


def reaper_for(resource: str, client):
    """ref: stop.go ReaperFor."""
    if resource == "replicationcontrollers":
        return RCReaper(client)
    if resource == "pods":
        return PodReaper(client)
    if resource == "services":
        return ServiceReaper(client)
    raise ValueError(f"no reaper for resource {resource!r}")


class RollingUpdater:
    """ref: rolling_updater.go RollingUpdater.Update — one replica at a
    time: newRc +1, wait ready, oldRc -1, repeat; then delete oldRc
    (rolling_updater.go:144-145 — the new controller KEEPS its new name,
    as the update-demo transcript shows: `stop rc update-demo-kitten`).
    rename=True is an opt-in convenience for same-name image rolls."""

    def __init__(self, client, namespace: str,
                 sleep: Callable[[float], None] = time.sleep):
        self.client = client
        self.namespace = namespace
        self.sleep = sleep

    def update(self, old_name: str, new_rc: api.ReplicationController,
               update_period: float = 0.0, interval: float = 0.1,
               timeout: float = 60.0, rename: bool = False) -> api.ReplicationController:
        rcs = self.client.resource("replicationcontrollers", self.namespace)
        old_rc = rcs.get(old_name)
        if new_rc.metadata.name == old_name:
            raise ValueError("the new RC must have a different name")
        if new_rc.spec.selector == old_rc.spec.selector:
            raise ValueError("the new RC must have a different selector "
                             "(ref: rolling_updater.go validation)")
        desired = new_rc.spec.replicas or old_rc.spec.replicas
        new_rc.spec.replicas = 0
        new_rc.metadata.namespace = self.namespace
        try:
            created = rcs.create(new_rc)
        except errors.StatusError as e:
            if not errors.is_already_exists(e):
                raise
            created = rcs.get(new_rc.metadata.name)  # resume an interrupted update
        resizer = Resizer(self.client)
        wait = RetryParams(interval, timeout)
        while created.spec.replicas < desired or old_rc.spec.replicas > 0:
            if created.spec.replicas < desired:
                created = resizer.resize(
                    self.namespace, created.metadata.name,
                    created.spec.replicas + 1, wait_for_replicas=wait)
                if update_period:
                    self.sleep(update_period)
            if old_rc.spec.replicas > 0:
                old_rc = resizer.resize(
                    self.namespace, old_name,
                    old_rc.spec.replicas - 1, wait_for_replicas=wait)
        rcs.delete(old_name)
        if rename:
            # delete+recreate under the old name (ref: rolling_updater.go Rename)
            rcs.delete(created.metadata.name)
            created.metadata = api.ObjectMeta(
                name=old_name, namespace=self.namespace,
                labels=dict(created.metadata.labels))
            created = rcs.create(created)
        return created
