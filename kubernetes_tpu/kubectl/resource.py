"""Generic resource Builder -> Info -> Visitor pipeline.

ref: pkg/kubectl/resource/builder.go:36 (Builder), visitor.go (Info,
Visitor chain). The Builder turns CLI inputs — filenames (JSON/YAML, multi
-document, directories, "-" for stdin), resource/name arguments
("pods", "pods/web", "pod web x y"), label selectors — into a stream of
``Info`` objects that commands visit uniformly. This is the seam that lets
get/create/update/delete/label share one input grammar.
"""

from __future__ import annotations

import glob
import io
import json
import os
import sys
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import yaml

from kubernetes_tpu.api.meta import default_rest_mapper

__all__ = ["Info", "Builder", "ResourceError", "RESOURCE_ALIASES"]


class ResourceError(Exception):
    pass


# Short names + singular forms accepted on the CLI
# (ref: pkg/kubectl/kubectl.go expandResourceShortcut + alias table).
RESOURCE_ALIASES = {
    "po": "pods", "pod": "pods",
    "rc": "replicationcontrollers", "replicationcontroller": "replicationcontrollers",
    "controllers": "replicationcontrollers", "controller": "replicationcontrollers",
    "svc": "services", "service": "services",
    "ep": "endpoints", "endpoint": "endpoints",
    "no": "nodes", "node": "nodes", "minion": "nodes", "minions": "nodes",
    "ev": "events", "event": "events",
    "ns": "namespaces", "namespace": "namespaces",
    "secret": "secrets",
    "limit": "limitranges", "limitrange": "limitranges", "limits": "limitranges",
    "quota": "resourcequotas", "resourcequota": "resourcequotas",
    "pc": "priorityclasses", "priorityclass": "priorityclasses",
}


def resolve_resource(arg: str, mapper=None) -> str:
    mapper = mapper or default_rest_mapper()
    r = arg.lower()
    r = RESOURCE_ALIASES.get(r, r)
    if not mapper.has_resource(r):
        raise ResourceError(f"unknown resource type {arg!r}")
    return r


@dataclass
class Info:
    """One visitable object (ref: resource/visitor.go Info)."""

    resource: str = ""
    namespace: str = ""
    name: str = ""
    obj: Any = None
    source: str = ""          # filename or "arg"

    def refresh(self, client) -> "Info":
        """Re-fetch from the server (ref: Info.Get)."""
        self.obj = client.resource(self.resource, self.namespace).get(self.name)
        return self


class Builder:
    """ref: resource/builder.go Builder — chainable input collector."""

    def __init__(self, scheme, mapper=None, default_namespace: str = "default"):
        self.scheme = scheme
        self.mapper = mapper or default_rest_mapper()
        self.default_namespace = default_namespace
        self._filenames: List[str] = []
        self._stdin: Optional[io.TextIOBase] = None
        self._resource_args: List[str] = []
        self._selector: str = ""
        self._namespace: str = ""
        self._all_namespaces = False

    # -- chainable configuration ------------------------------------------
    def filename(self, *names: str) -> "Builder":
        self._filenames.extend(names)
        return self

    def stdin(self, stream=None) -> "Builder":
        self._stdin = stream or sys.stdin
        return self

    def namespace(self, ns: str) -> "Builder":
        self._namespace = ns
        return self

    def all_namespaces(self, flag: bool = True) -> "Builder":
        self._all_namespaces = flag
        return self

    def selector(self, sel: str) -> "Builder":
        self._selector = sel
        return self

    def resource_type_or_name(self, *args: str) -> "Builder":
        self._resource_args.extend(args)
        return self

    # -- file parsing ------------------------------------------------------
    def _decode_doc(self, doc: Any, source: str) -> Info:
        if not isinstance(doc, dict):
            raise ResourceError(f"{source}: expected an object, got {type(doc).__name__}")
        kind = doc.get("kind", "")
        if not kind:
            raise ResourceError(f"{source}: object has no kind")
        obj = self.scheme.decode_from_wire(
            doc, default_version=doc.get("apiVersion", ""))
        resource = self.mapper.resource_for(kind)
        meta = getattr(obj, "metadata", None)
        ns = ""
        if self.mapper.is_namespaced(resource):
            ns = (meta.namespace if meta and meta.namespace else
                  self._namespace or self.default_namespace)
            if meta is not None:
                meta.namespace = ns
        return Info(resource=resource, namespace=ns,
                    name=meta.name if meta else "", obj=obj, source=source)

    def _parse_stream(self, text: str, source: str) -> List[Info]:
        infos = []
        stripped = text.lstrip()
        if stripped.startswith("{") or stripped.startswith("["):
            docs = json.loads(text)
            docs = docs if isinstance(docs, list) else [docs]
        else:
            docs = [d for d in yaml.safe_load_all(text) if d is not None]
        for doc in docs:
            # v1beta3-style List objects flatten into their items
            if isinstance(doc, dict) and doc.get("kind", "").endswith("List") \
                    and "items" in doc:
                for item in doc["items"]:
                    infos.append(self._decode_doc(item, source))
            else:
                infos.append(self._decode_doc(doc, source))
        return infos

    def _expand_paths(self) -> List[str]:
        out = []
        for name in self._filenames:
            if name == "-":
                out.append(name)
                continue
            if os.path.isdir(name):
                for ext in ("*.json", "*.yaml", "*.yml"):
                    out.extend(sorted(glob.glob(os.path.join(name, ext))))
            elif os.path.exists(name):
                out.append(name)
            else:
                matches = sorted(glob.glob(name))
                if not matches:
                    raise ResourceError(f"the path {name!r} does not exist")
                out.extend(matches)
        return out

    # -- resource/name argument grammar -----------------------------------
    def _parse_resource_args(self, client) -> List[Info]:
        """Grammar (ref: builder.go ResourceTypeOrNameArgs):
        <resource>                     -> list (with selector)
        <resource>/<name> ...          -> those objects
        <resource> <name1> <name2> ... -> those objects
        """
        args = self._resource_args
        if not args:
            return []
        infos: List[Info] = []
        pairs: List[tuple] = []
        if all("/" in a for a in args):
            for a in args:
                r, _, n = a.partition("/")
                pairs.append((resolve_resource(r, self.mapper), n))
        else:
            if any("/" in a for a in args):
                raise ResourceError(
                    "there is no need to specify a resource type as a separate "
                    "argument when passing arguments in resource/name form "
                    "(e.g. 'get resource/<resource_name>' instead of "
                    "'get resource resource/<resource_name>')")
            resource = resolve_resource(args[0], self.mapper)
            names = args[1:]
            if not names:
                pairs.append((resource, ""))
            else:
                pairs.extend((resource, n) for n in names)

        for resource, name in pairs:
            namespaced = self.mapper.is_namespaced(resource)
            ns = "" if (not namespaced or self._all_namespaces) else \
                (self._namespace or self.default_namespace)
            if name:
                obj = client.resource(resource, ns).get(name)
                infos.append(Info(resource=resource, namespace=ns, name=name,
                                  obj=obj, source="arg"))
            else:
                lst = client.resource(resource, ns).list(
                    label_selector=self._selector)
                for item in lst.items:
                    m = item.metadata
                    infos.append(Info(resource=resource,
                                      namespace=m.namespace, name=m.name,
                                      obj=item, source="arg"))
        return infos

    # -- execution ---------------------------------------------------------
    def infos(self, client=None) -> List[Info]:
        """Materialize all inputs. ``client`` is only needed for
        resource/name args (file inputs never hit the server)."""
        infos: List[Info] = []
        for path in self._expand_paths():
            if path == "-":
                stream = self._stdin or sys.stdin
                infos.extend(self._parse_stream(stream.read(), "stdin"))
            else:
                with open(path, "r", encoding="utf-8") as f:
                    infos.extend(self._parse_stream(f.read(), path))
        if self._resource_args:
            if client is None:
                raise ResourceError("resource arguments require a client")
            infos.extend(self._parse_resource_args(client))
        if not infos and not self._filenames and not self._resource_args:
            raise ResourceError("no resources specified")
        return infos

    def visit(self, fn: Callable[[Info], None], client=None) -> int:
        """Apply ``fn`` to each Info; returns the count visited
        (ref: visitor.go Visit). Errors from individual items propagate."""
        infos = self.infos(client)
        for info in infos:
            fn(info)
        return len(infos)
