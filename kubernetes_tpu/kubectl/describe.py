"""Per-kind describers (ref: pkg/kubectl/describe.go).

Each describer renders one object plus related state (a pod's events, an
RC's pod statuses, a service's endpoints) the way ``kubectl describe``
does: Name/Labels/key-fields blocks followed by an events table.
"""

from __future__ import annotations

import io
from typing import Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.kubectl.printers import HumanReadablePrinter, _join_labels

__all__ = ["describe", "PodDescriber", "ReplicationControllerDescriber",
           "ServiceDescriber", "NodeDescriber", "NamespaceDescriber",
           "SecretDescriber", "LimitRangeDescriber", "ResourceQuotaDescriber",
           "PriorityClassDescriber"]


def _events_for(client, obj, namespace: str) -> Optional[api.EventList]:
    try:
        name = obj.metadata.name
        kind = getattr(obj, "kind", "")
        evs = client.resource("events", namespace).list(
            field_selector=f"involvedObject.name={name},involvedObject.kind={kind}")
        return evs
    except Exception:
        return None


def _write_events(out, events: Optional[api.EventList]) -> None:
    if not events or not events.items:
        out.write("No events.\n")
        return
    out.write("Events:\n")
    HumanReadablePrinter().print_obj(events, out)


class PodDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        pod = client.resource("pods", namespace).get(name)
        out = io.StringIO()
        out.write(f"Name:\t{pod.metadata.name}\n")
        out.write(f"Namespace:\t{pod.metadata.namespace}\n")
        out.write(f"Image(s):\t{', '.join(c.image for c in pod.spec.containers)}\n")
        out.write(f"Host:\t{pod.spec.host or pod.status.host or '<unscheduled>'}\n")
        out.write(f"Labels:\t{_join_labels(pod.metadata.labels)}\n")
        prio = pod.spec.priority
        if prio is not None or pod.spec.priority_class_name:
            out.write(f"Priority:\t{0 if prio is None else prio}\n")
            if pod.spec.priority_class_name:
                out.write(f"Priority Class Name:\t"
                          f"{pod.spec.priority_class_name}\n")
        out.write(f"Status:\t{pod.status.phase or 'Pending'}\n")
        if pod.status.pod_ip:
            out.write(f"IP:\t{pod.status.pod_ip}\n")
        if pod.status.message:
            out.write(f"Message:\t{pod.status.message}\n")
        for cs in pod.status.container_statuses:
            state = "unknown"
            if cs.state.running:
                state = "Running"
            elif cs.state.waiting:
                state = f"Waiting ({cs.state.waiting.reason})"
            elif cs.state.termination:
                state = f"Terminated (exit {cs.state.termination.exit_code})"
            out.write(f"Container:\t{cs.name}\t{state}\trestarts={cs.restart_count}\n")
        _write_events(out, _events_for(client, pod, namespace))
        return out.getvalue()


class ReplicationControllerDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        rc = client.resource("replicationcontrollers", namespace).get(name)
        out = io.StringIO()
        out.write(f"Name:\t{rc.metadata.name}\n")
        out.write(f"Namespace:\t{rc.metadata.namespace}\n")
        tmpl = rc.spec.template
        images = [c.image for c in tmpl.spec.containers] if tmpl else []
        out.write(f"Image(s):\t{', '.join(images)}\n")
        out.write(f"Selector:\t{_join_labels(rc.spec.selector)}\n")
        out.write(f"Labels:\t{_join_labels(rc.metadata.labels)}\n")
        out.write(f"Replicas:\t{rc.status.replicas} current / "
                  f"{rc.spec.replicas} desired\n")
        # pod status tally (ref: describe.go getPodStatusForController)
        running = waiting = succeeded = failed = 0
        try:
            sel = ",".join(f"{k}={v}" for k, v in sorted(rc.spec.selector.items()))
            pods = client.resource("pods", namespace).list(label_selector=sel)
            for p in pods.items:
                phase = p.status.phase
                if phase == api.PodRunning:
                    running += 1
                elif phase == api.PodSucceeded:
                    succeeded += 1
                elif phase == api.PodFailed:
                    failed += 1
                else:
                    waiting += 1
        except Exception:
            pass
        out.write(f"Pods Status:\t{running} Running / {waiting} Waiting / "
                  f"{succeeded} Succeeded / {failed} Failed\n")
        _write_events(out, _events_for(client, rc, namespace))
        return out.getvalue()


class ServiceDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        svc = client.resource("services", namespace).get(name)
        out = io.StringIO()
        out.write(f"Name:\t{svc.metadata.name}\n")
        out.write(f"Namespace:\t{svc.metadata.namespace}\n")
        out.write(f"Labels:\t{_join_labels(svc.metadata.labels)}\n")
        out.write(f"Selector:\t{_join_labels(svc.spec.selector)}\n")
        out.write(f"IP:\t{svc.spec.portal_ip}\n")
        out.write(f"Port:\t{svc.spec.port}\n")
        try:
            ep = client.resource("endpoints", namespace).get(name)
            eps = ",".join(f"{e.ip}:{e.port}" for e in ep.endpoints) or "<none>"
        except Exception:
            eps = "<none>"
        out.write(f"Endpoints:\t{eps}\n")
        out.write(f"Session Affinity:\t{svc.spec.session_affinity or 'None'}\n")
        _write_events(out, _events_for(client, svc, namespace))
        return out.getvalue()


class NodeDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        node = client.resource("nodes", "").get(name)
        out = io.StringIO()
        out.write(f"Name:\t{node.metadata.name}\n")
        out.write(f"Labels:\t{_join_labels(node.metadata.labels)}\n")
        out.write(f"Unschedulable:\t{'true' if node.spec.unschedulable else 'false'}\n")
        out.write("Conditions:\n")
        for c in node.status.conditions:
            out.write(f"  {c.type}\t{c.status}\t{c.reason}\n")
        if node.spec.capacity:
            out.write("Capacity:\n")
            for k, v in sorted(node.spec.capacity.items()):
                out.write(f"  {k}:\t{v}\n")
        # pods on this node (ref: describe.go describeNode)
        try:
            pods = client.resource("pods", "").list(
                field_selector=f"spec.host={name}")
            out.write(f"Pods:\t({len(pods.items)} in total)\n")
            for p in pods.items:
                out.write(f"  {p.metadata.namespace}/{p.metadata.name}\n")
        except Exception:
            pass
        _write_events(out, _events_for(client, node, ""))
        return out.getvalue()


class NamespaceDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        ns = client.resource("namespaces", "").get(name)
        out = io.StringIO()
        out.write(f"Name:\t{ns.metadata.name}\n")
        out.write(f"Labels:\t{_join_labels(ns.metadata.labels)}\n")
        out.write(f"Status:\t{ns.status.phase or 'Active'}\n")
        return out.getvalue()


class PriorityClassDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        pc = client.resource("priorityclasses", "").get(name)
        out = io.StringIO()
        out.write(f"Name:\t{pc.metadata.name}\n")
        out.write(f"Value:\t{pc.value}\n")
        out.write(f"GlobalDefault:\t{pc.global_default}\n")
        out.write(f"PreemptionPolicy:\t"
                  f"{pc.preemption_policy or api.PreemptLowerPriority}\n")
        if pc.description:
            out.write(f"Description:\t{pc.description}\n")
        _write_events(out, _events_for(client, pc, ""))
        return out.getvalue()


class SecretDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        s = client.resource("secrets", namespace).get(name)
        out = io.StringIO()
        out.write(f"Name:\t{s.metadata.name}\n")
        out.write(f"Type:\t{s.type}\n")
        out.write("Data:\n")
        for k, v in sorted(s.data.items()):
            out.write(f"  {k}:\t{len(v)} bytes\n")
        return out.getvalue()


class LimitRangeDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        lr = client.resource("limitranges", namespace).get(name)
        out = io.StringIO()
        out.write(f"Name:\t{lr.metadata.name}\n")
        out.write("Type\tResource\tMin\tMax\n")
        for item in lr.spec.limits:
            resources = set(item.min) | set(item.max)
            for r in sorted(resources):
                out.write(f"{item.type}\t{r}\t{item.min.get(r, '-')}\t"
                          f"{item.max.get(r, '-')}\n")
        return out.getvalue()


class ResourceQuotaDescriber:
    def describe(self, client, namespace: str, name: str) -> str:
        q = client.resource("resourcequotas", namespace).get(name)
        out = io.StringIO()
        out.write(f"Name:\t{q.metadata.name}\n")
        out.write("Resource\tUsed\tHard\n")
        hard = q.status.hard or q.spec.hard
        for r in sorted(hard):
            out.write(f"{r}\t{q.status.used.get(r, '0')}\t{hard[r]}\n")
        return out.getvalue()


_DESCRIBERS = {
    "pods": PodDescriber,
    "replicationcontrollers": ReplicationControllerDescriber,
    "services": ServiceDescriber,
    "nodes": NodeDescriber,
    "namespaces": NamespaceDescriber,
    "secrets": SecretDescriber,
    "limitranges": LimitRangeDescriber,
    "resourcequotas": ResourceQuotaDescriber,
    "priorityclasses": PriorityClassDescriber,
}


def describe(client, resource: str, namespace: str, name: str) -> str:
    """ref: describe.go DescriberFor."""
    cls = _DESCRIBERS.get(resource)
    if cls is None:
        raise ValueError(f"no describer for resource {resource!r}")
    return cls().describe(client, namespace, name)
