"""Output printers (ref: pkg/kubectl/resource_printer.go).

- ``HumanReadablePrinter`` — per-kind column tables (columns mirror
  resource_printer.go:231-240)
- ``JSONPrinter`` / ``YAMLPrinter`` — codec round-trip to wire form
- ``TemplatePrinter`` — Python format-string over the wire dict (the
  reference uses Go templates; str.format over the same wire data is the
  idiomatic equivalent)
- ``JSONPathPrinter`` — minimal jsonpath: dotted paths, [idx], .items[*]
  (ref: resource_printer.go jsonpath support)
"""

from __future__ import annotations

import datetime
import json
import re
from typing import Any, Dict, List

import yaml

from kubernetes_tpu.api import types as api

__all__ = ["HumanReadablePrinter", "JSONPrinter", "YAMLPrinter",
           "TemplatePrinter", "JSONPathPrinter", "printer_for"]


def _join_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return "<none>"
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def _age(meta: api.ObjectMeta) -> str:
    ts = meta.creation_timestamp
    if not ts:
        return "<unknown>"
    now = datetime.datetime.now(datetime.timezone.utc)
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=datetime.timezone.utc)
    delta = now - ts
    secs = int(delta.total_seconds())
    if secs < 120:
        return f"{secs}s"
    if secs < 2 * 3600:
        return f"{secs // 60}m"
    if secs < 2 * 86400:
        return f"{secs // 3600}h"
    return f"{secs // 86400}d"


# -- per-kind column definitions (ref: resource_printer.go:231-240) --------

def _pod_rows(pod: api.Pod):
    containers = pod.spec.containers
    first = containers[0] if containers else None
    rows = [[pod.metadata.name, pod.status.pod_ip or "",
             first.name if first else "", first.image if first else "",
             pod.spec.host or pod.status.host or "",
             _join_labels(pod.metadata.labels),
             pod.status.phase or "Pending", _age(pod.metadata)]]
    for c in containers[1:]:
        rows.append(["", "", c.name, c.image, "", "", "", ""])
    return rows


def _rc_rows(rc: api.ReplicationController):
    tmpl = rc.spec.template
    containers = tmpl.spec.containers if tmpl else []
    first = containers[0] if containers else None
    rows = [[rc.metadata.name,
             first.name if first else "", first.image if first else "",
             _join_labels(rc.spec.selector), str(rc.spec.replicas)]]
    for c in containers[1:]:
        rows.append(["", c.name, c.image, "", ""])
    return rows


def _svc_rows(svc: api.Service):
    return [[svc.metadata.name, _join_labels(svc.metadata.labels),
             _join_labels(svc.spec.selector), svc.spec.portal_ip or "",
             str(svc.spec.port)]]


def _endpoints_rows(ep: api.Endpoints):
    eps = ",".join(f"{e.ip}:{e.port}" for e in ep.endpoints) or "<none>"
    return [[ep.metadata.name, eps]]


def _node_status(node: api.Node) -> str:
    conds = [c for c in node.status.conditions if c.status == api.ConditionTrue]
    names = [c.type for c in conds]
    status = ",".join(names) if names else "Unknown"
    if node.spec.unschedulable:
        # cordoned (ref: printers.go appends SchedulingDisabled)
        status += ",SchedulingDisabled"
    return status


def _node_rows(node: api.Node):
    return [[node.metadata.name, _join_labels(node.metadata.labels),
             _node_status(node)]]


def _event_rows(ev: api.Event):
    fmt = "%Y-%m-%d %H:%M:%S"
    first = ev.first_timestamp.strftime(fmt) if ev.first_timestamp else ""
    last = ev.last_timestamp.strftime(fmt) if ev.last_timestamp else ""
    ref = ev.involved_object
    src = ev.source.component + (f" {ev.source.host}" if ev.source.host else "")
    return [[first, last, str(ev.count or 1), ref.name, ref.kind,
             ref.field_path, ev.reason, src, ev.message]]


def _ns_rows(ns: api.Namespace):
    return [[ns.metadata.name, _join_labels(ns.metadata.labels),
             ns.status.phase or "Active"]]


def _secret_rows(s: api.Secret):
    return [[s.metadata.name, s.type, str(len(s.data))]]


def _limitrange_rows(lr: api.LimitRange):
    return [[lr.metadata.name]]


def _quota_rows(q: api.ResourceQuota):
    return [[q.metadata.name]]


def _status_rows(st: api.Status):
    return [[st.status]]


def _priorityclass_rows(pc: api.PriorityClass):
    return [[pc.metadata.name, str(pc.value),
             "true" if pc.global_default else "false",
             pc.preemption_policy or api.PreemptLowerPriority,
             _age(pc.metadata)]]


_HANDLERS: Dict[str, tuple] = {
    # kind -> (columns, row fn)   columns ref: resource_printer.go:231-240
    "Pod": (["POD", "IP", "CONTAINER(S)", "IMAGE(S)", "HOST", "LABELS",
             "STATUS", "CREATED"], _pod_rows),
    "ReplicationController": (["CONTROLLER", "CONTAINER(S)", "IMAGE(S)",
                               "SELECTOR", "REPLICAS"], _rc_rows),
    "Service": (["NAME", "LABELS", "SELECTOR", "IP", "PORT"], _svc_rows),
    "Endpoints": (["NAME", "ENDPOINTS"], _endpoints_rows),
    "Node": (["NAME", "LABELS", "STATUS"], _node_rows),
    "Event": (["FIRSTSEEN", "LASTSEEN", "COUNT", "NAME", "KIND", "SUBOBJECT",
               "REASON", "SOURCE", "MESSAGE"], _event_rows),
    "Namespace": (["NAME", "LABELS", "STATUS"], _ns_rows),
    "Secret": (["NAME", "TYPE", "DATA"], _secret_rows),
    "LimitRange": (["NAME"], _limitrange_rows),
    "ResourceQuota": (["NAME"], _quota_rows),
    "PriorityClass": (["NAME", "VALUE", "GLOBAL-DEFAULT",
                       "PREEMPTIONPOLICY", "AGE"], _priorityclass_rows),
    "Status": (["STATUS"], _status_rows),
}


class HumanReadablePrinter:
    """Tab-aligned tables, one handler per kind
    (ref: resource_printer.go HumanReadablePrinter)."""

    def __init__(self, no_headers: bool = False):
        self.no_headers = no_headers

    def print_obj(self, obj: Any, out) -> None:
        kind = getattr(obj, "kind", type(obj).__name__) or type(obj).__name__
        if kind.endswith("List") and hasattr(obj, "items"):
            item_kind = kind[:-4]
            self._print_table(item_kind, list(obj.items), out)
            return
        self._print_table(kind, [obj], out)

    def _print_table(self, kind: str, items: List[Any], out) -> None:
        spec = _HANDLERS.get(kind)
        if spec is None:
            raise ValueError(f"no printer handler for kind {kind!r}")
        columns, row_fn = spec
        rows: List[List[str]] = []
        for item in items:
            rows.extend(row_fn(item))
        widths = [len(c) for c in columns]
        for row in rows:
            for i, cell in enumerate(row):
                widths[i] = max(widths[i], len(str(cell)))
        def emit(cells):
            out.write("   ".join(str(c).ljust(widths[i])
                                 for i, c in enumerate(cells)).rstrip() + "\n")
        if not self.no_headers:
            emit(columns)
        for row in rows:
            emit(row)


class JSONPrinter:
    def __init__(self, scheme, version: str = ""):
        self.scheme = scheme
        self.version = version or None

    def print_obj(self, obj: Any, out) -> None:
        wire = self.scheme.encode_to_wire(obj, self.version)
        json.dump(wire, out, indent=4, sort_keys=True)
        out.write("\n")


class YAMLPrinter(JSONPrinter):
    def print_obj(self, obj: Any, out) -> None:
        wire = self.scheme.encode_to_wire(obj, self.version)
        yaml.safe_dump(wire, out, default_flow_style=False, sort_keys=True)


class TemplatePrinter:
    """Python .format template over the wire dict. ``{.x.y}``-style access is
    spelled ``{x[y]}``; bare ``{field}`` works for top-level fields."""

    def __init__(self, scheme, template: str, version: str = ""):
        self.scheme = scheme
        self.template = template
        self.version = version or None

    def print_obj(self, obj: Any, out) -> None:
        wire = self.scheme.encode_to_wire(obj, self.version)
        out.write(self.template.format(**wire))
        if not self.template.endswith("\n"):
            out.write("\n")


_JSONPATH_TOKEN = re.compile(r"\.([A-Za-z_][A-Za-z0-9_\-]*)|\[(\*|\d+|'[^']*')\]")


class JSONPathPrinter:
    """Minimal jsonpath: ``{.a.b[0].c}``, ``[*]`` fan-out, quoted keys."""

    def __init__(self, scheme, path: str, version: str = ""):
        self.scheme = scheme
        self.version = version or None
        self.exprs = re.findall(r"\{([^}]*)\}", path)
        self.literal_parts = re.split(r"\{[^}]*\}", path)

    def _eval(self, expr: str, data: Any) -> List[Any]:
        expr = expr.strip()
        if expr.startswith("$"):
            expr = expr[1:]
        current = [data]
        for m in _JSONPATH_TOKEN.finditer(expr):
            name, idx = m.group(1), m.group(2)
            nxt: List[Any] = []
            for c in current:
                if name is not None:
                    if isinstance(c, dict) and name in c:
                        nxt.append(c[name])
                elif idx == "*":
                    if isinstance(c, list):
                        nxt.extend(c)
                    elif isinstance(c, dict):
                        nxt.extend(c.values())
                elif idx.startswith("'"):
                    if isinstance(c, dict) and idx[1:-1] in c:
                        nxt.append(c[idx[1:-1]])
                else:
                    i = int(idx)
                    if isinstance(c, list) and i < len(c):
                        nxt.append(c[i])
            current = nxt
        return current

    def print_obj(self, obj: Any, out) -> None:
        wire = self.scheme.encode_to_wire(obj, self.version)
        pieces = [self.literal_parts[0]]
        for i, expr in enumerate(self.exprs):
            vals = self._eval(expr, wire)
            pieces.append(" ".join(
                v if isinstance(v, str) else json.dumps(v) for v in vals))
            pieces.append(self.literal_parts[i + 1])
        out.write("".join(pieces))
        out.write("\n")


def printer_for(output: str, scheme, template: str = "",
                no_headers: bool = False, version: str = ""):
    """ref: resource_printer.go GetPrinter."""
    if output in ("", "wide"):
        return HumanReadablePrinter(no_headers=no_headers)
    if output == "json":
        return JSONPrinter(scheme, version)
    if output == "yaml":
        return YAMLPrinter(scheme, version)
    if output == "template":
        if not template:
            raise ValueError("template format specified but no template given")
        return TemplatePrinter(scheme, template, version)
    if output == "jsonpath":
        if not template:
            raise ValueError("jsonpath format specified but no expression given")
        return JSONPathPrinter(scheme, template, version)
    raise ValueError(f"unknown output format {output!r}")
