"""Object generators for imperative commands.

ref: pkg/kubectl/run.go (BasicReplicationController generator used by
``kubectl run``) and pkg/kubectl/service.go (service generator used by
``kubectl expose``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api

__all__ = ["generate_rc", "generate_service"]


def parse_labels(spec: str) -> Dict[str, str]:
    """"a=b,c=d" -> dict (ref: kubectl.go ParseLabels)."""
    out: Dict[str, str] = {}
    if not spec:
        return out
    for part in spec.split(","):
        if "=" not in part:
            raise ValueError(f"invalid label {part!r}: expected key=value")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


def generate_rc(name: str, image: str, replicas: int = 1,
                labels: Optional[Dict[str, str]] = None,
                port: int = 0) -> api.ReplicationController:
    """ref: run.go BasicReplicationController.Generate — labels default to
    {"run": name} so the selector always matches the template."""
    labels = dict(labels) if labels else {"run": name}
    container = api.Container(name=name, image=image)
    if port > 0:
        container.ports = [api.ContainerPort(container_port=port)]
    return api.ReplicationController(
        metadata=api.ObjectMeta(name=name, labels=dict(labels)),
        spec=api.ReplicationControllerSpec(
            replicas=replicas,
            selector=dict(labels),
            template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels=dict(labels)),
                spec=api.PodSpec(containers=[container]))))


def generate_service(name: str, selector: Dict[str, str], port: int,
                     container_port: int = 0, protocol: str = api.ProtocolTCP,
                     labels: Optional[Dict[str, str]] = None,
                     create_external_load_balancer: bool = False,
                     public_ips: Optional[List[str]] = None) -> api.Service:
    """ref: service.go ServiceGenerator.Generate."""
    if not selector:
        raise ValueError("a selector is required to expose a service")
    if port <= 0:
        raise ValueError("a positive --port is required")
    return api.Service(
        metadata=api.ObjectMeta(name=name, labels=dict(labels or {})),
        spec=api.ServiceSpec(
            port=port,
            protocol=protocol,
            selector=dict(selector),
            container_port=container_port or port,
            create_external_load_balancer=create_external_load_balancer,
            public_ips=list(public_ips or [])))
