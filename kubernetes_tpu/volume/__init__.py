"""Volume plugin framework (ref: pkg/volume/).

- ``VolumePlugin``  — can_support(spec) + new_builder/new_cleaner
  (ref: pkg/volume/plugins.go:34-43)
- ``Builder.set_up()`` / ``Cleaner.tear_down()``
  (ref: pkg/volume/volume.go:33-55)
- ``VolumePluginMgr`` — plugin registry + find-by-spec
  (ref: plugins.go VolumePluginMgr.FindPluginBySpec)

Plugins: empty_dir, host_path, git_repo, secret, nfs, gce_pd
(ref: pkg/volume/{empty_dir,host_path,git_repo,secret,nfs,gce_pd}/).
Network/cloud plugins (nfs, gce_pd) take mounter/attacher seams so tests
run without privileges, exactly like the reference's mount.Interface fake.
"""

from kubernetes_tpu.volume.plugins import (Builder, Cleaner,  # noqa: F401
                                           VolumePlugin, VolumePluginMgr,
                                           new_default_plugin_mgr)
