"""Volume plugins (ref: pkg/volume/).

Volume directories live under the kubelet root:
``<root>/pods/<pod-uid>/volumes/<escaped-plugin-name>/<volume-name>``
(ref: pkg/kubelet/kubelet.go GetPodVolumesDir + volume paths in each
plugin). ``set_up`` makes the directory exist with the right contents;
``tear_down`` removes it. Cloud/network mounts go through injectable
seams (``Mounter`` for nfs, ``DiskManager`` for gce_pd) so everything is
testable unprivileged — the reference does the same with mount.Interface.
"""

from __future__ import annotations

import base64
import binascii
import os
import shutil
import subprocess
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from kubernetes_tpu.api import types as api

__all__ = ["Builder", "Cleaner", "VolumePlugin", "VolumePluginMgr",
           "Mounter", "FakeMounter", "ExecMounter", "DiskManager",
           "FakeDiskManager", "RefusingDiskManager",
           "new_default_plugin_mgr", "escape_plugin_name"]


def escape_plugin_name(name: str) -> str:
    """"kubernetes.io/empty-dir" -> "kubernetes.io~empty-dir"
    (ref: pkg/volume/plugins.go EscapePluginName)."""
    return name.replace("/", "~")


@dataclass
class VolumeHost:
    """What plugins need from the kubelet (ref: plugins.go VolumeHost)."""

    root_dir: str
    kubelet_client: Any = None       # for secret fetch

    def pod_volume_dir(self, pod_uid: str, plugin_name: str,
                       volume_name: str) -> str:
        return os.path.join(self.root_dir, "pods", pod_uid, "volumes",
                            escape_plugin_name(plugin_name), volume_name)

    def pod_volumes_dir(self, pod_uid: str) -> str:
        return os.path.join(self.root_dir, "pods", pod_uid, "volumes")


class Builder:
    """ref: volume.go Builder interface."""

    def set_up(self) -> None:
        raise NotImplementedError

    def get_path(self) -> str:
        raise NotImplementedError


class Cleaner:
    """ref: volume.go Cleaner interface."""

    def tear_down(self) -> None:
        raise NotImplementedError


class VolumePlugin:
    """ref: plugins.go VolumePlugin interface."""

    name = ""

    def init(self, host: VolumeHost) -> None:
        self.host = host

    def can_support(self, volume: api.Volume) -> bool:
        raise NotImplementedError

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        raise NotImplementedError

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        raise NotImplementedError


class _DirBuilder(Builder, Cleaner):
    """Common directory-backed builder/cleaner."""

    def __init__(self, plugin: VolumePlugin, volume_name: str, pod_uid: str):
        self.plugin = plugin
        self.volume_name = volume_name
        self.pod_uid = pod_uid

    def get_path(self) -> str:
        return self.plugin.host.pod_volume_dir(
            self.pod_uid, self.plugin.name, self.volume_name)

    def tear_down(self) -> None:
        path = self.get_path()
        if os.path.lexists(path):
            if os.path.islink(path):
                os.unlink(path)
            else:
                shutil.rmtree(path, ignore_errors=True)


# ---------------------------------------------------------------------------
# empty_dir (ref: pkg/volume/empty_dir/)
# ---------------------------------------------------------------------------

class EmptyDirPlugin(VolumePlugin):
    name = "kubernetes.io/empty-dir"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.source is not None and volume.source.empty_dir is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        b = _DirBuilder(self, volume.name, pod.metadata.uid)
        def set_up():
            os.makedirs(b.get_path(), exist_ok=True)
        b.set_up = set_up
        return b

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self, volume_name, pod_uid)


# ---------------------------------------------------------------------------
# host_path (ref: pkg/volume/host_path/ — just hands out the host path)
# ---------------------------------------------------------------------------

class HostPathPlugin(VolumePlugin):
    name = "kubernetes.io/host-path"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.source is not None and volume.source.host_path is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        path = volume.source.host_path.path

        class _B(Builder):
            def set_up(self) -> None:  # nothing to do (ref: host_path.go SetUp)
                pass

            def get_path(self) -> str:
                return path
        return _B()

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        class _C(Cleaner):
            def tear_down(self) -> None:  # host dirs are never deleted
                pass
        return _C()


# ---------------------------------------------------------------------------
# git_repo (ref: pkg/volume/git_repo/ — clone into the volume dir)
# ---------------------------------------------------------------------------

class GitRepoPlugin(VolumePlugin):
    name = "kubernetes.io/git-repo"

    def __init__(self, exec_fn=None):
        # injectable for tests (ref: git_repo.go uses exec.Interface)
        self.exec_fn = exec_fn or self._real_exec

    @staticmethod
    def _real_exec(args: List[str], cwd: str) -> None:
        subprocess.run(args, cwd=cwd, check=True, capture_output=True)

    def can_support(self, volume: api.Volume) -> bool:
        return volume.source is not None and volume.source.git_repo is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        b = _DirBuilder(self, volume.name, pod.metadata.uid)
        src = volume.source.git_repo

        def set_up():
            path = b.get_path()
            if os.path.exists(path) and os.listdir(path):
                return  # idempotent resync
            os.makedirs(path, exist_ok=True)
            self.exec_fn(["git", "clone", src.repository, "."], path)
            if src.revision:
                self.exec_fn(["git", "checkout", src.revision], path)
        b.set_up = set_up
        return b

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self, volume_name, pod_uid)


# ---------------------------------------------------------------------------
# secret (ref: pkg/volume/secret/ — fetch Secret, write decoded files)
# ---------------------------------------------------------------------------

class SecretPlugin(VolumePlugin):
    name = "kubernetes.io/secret"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.source is not None and volume.source.secret is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        b = _DirBuilder(self, volume.name, pod.metadata.uid)
        secret_name = volume.source.secret.secret_name
        namespace = pod.metadata.namespace
        client = self.host.kubelet_client

        def set_up():
            if client is None:
                raise RuntimeError(
                    "secret volumes need an API client on the kubelet")
            secret = client.secrets(namespace).get(secret_name)
            path = b.get_path()
            os.makedirs(path, exist_ok=True)
            for key, value in secret.data.items():
                # defense in depth vs. SecretStrategy.validate: a key that is
                # not a plain filename ('../x', 'a/b', '') could escape the
                # pod volume dir and overwrite arbitrary kubelet-host files
                if os.path.basename(key) != key or key in ("", ".", ".."):
                    raise ValueError(
                        f"secret {secret_name!r}: unsafe data key {key!r}")
                try:
                    raw = base64.b64decode(value, validate=True)
                except (binascii.Error, ValueError):
                    raw = value.encode()  # stored unencoded
                with open(os.path.join(path, key), "wb") as f:
                    f.write(raw)
        b.set_up = set_up
        return b

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self, volume_name, pod_uid)


# ---------------------------------------------------------------------------
# nfs (ref: pkg/volume/nfs/ — mount -t nfs server:path dir)
# ---------------------------------------------------------------------------

class Mounter:
    """ref: pkg/util/mount Interface (incl. the IsMountPoint check the
    reference's plugins use for SetUp idempotency)."""

    def mount(self, source: str, target: str, fstype: str,
              options: List[str]) -> None:
        raise NotImplementedError

    def unmount(self, target: str) -> None:
        raise NotImplementedError

    def is_mounted(self, target: str) -> bool:
        raise NotImplementedError

    def device_for(self, target: str) -> Optional[str]:
        """Source device mounted at ``target`` (for detach bookkeeping —
        ref: gce_pd.go TearDown reads the device back from the mount table)."""
        return None


class FakeMounter(Mounter):
    def __init__(self):
        self.mounts: Dict[str, tuple] = {}
        self.log: List[tuple] = []

    def mount(self, source, target, fstype, options):
        self.mounts[target] = (source, fstype, tuple(options))
        self.log.append(("mount", source, target, fstype))

    def unmount(self, target):
        self.mounts.pop(target, None)
        self.log.append(("unmount", target))

    def is_mounted(self, target):
        return target in self.mounts

    def device_for(self, target):
        entry = self.mounts.get(target)
        return entry[0] if entry else None


class ExecMounter(Mounter):
    def mount(self, source, target, fstype, options):
        cmd = ["mount", "-t", fstype]
        if options:
            cmd += ["-o", ",".join(options)]
        cmd += [source, target]
        subprocess.run(cmd, check=True, capture_output=True)

    def unmount(self, target):
        subprocess.run(["umount", target], check=True, capture_output=True)

    def is_mounted(self, target):
        real = os.path.realpath(target)
        try:
            with open("/proc/mounts") as f:
                return any(line.split()[1] == real for line in f)
        except OSError:
            return False

    def device_for(self, target):
        real = os.path.realpath(target)
        try:
            with open("/proc/mounts") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[1] == real:
                        return parts[0]
        except OSError:
            pass
        return None


class NFSPlugin(VolumePlugin):
    name = "kubernetes.io/nfs"

    def __init__(self, mounter: Optional[Mounter] = None):
        self.mounter = mounter or FakeMounter()

    def can_support(self, volume: api.Volume) -> bool:
        return volume.source is not None and volume.source.nfs is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        b = _DirBuilder(self, volume.name, pod.metadata.uid)
        src = volume.source.nfs
        mounter = self.mounter

        def set_up():
            path = b.get_path()
            if mounter.is_mounted(path):
                return  # resync idempotency (ref: nfs.go SetUp IsMountPoint)
            os.makedirs(path, exist_ok=True)
            options = ["ro"] if src.read_only else []
            mounter.mount(f"{src.server}:{src.path}", path, "nfs", options)
        b.set_up = set_up
        return b

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        base = _DirBuilder(self, volume_name, pod_uid)
        mounter = self.mounter

        def tear_down():
            # gate on IsMountPoint as the reference does (nfs.go TearDown):
            # a dir left behind by a failed mount must not abort cleanup
            if mounter.is_mounted(base.get_path()):
                mounter.unmount(base.get_path())
            _DirBuilder.tear_down(base)
        base.tear_down = tear_down
        return base


# ---------------------------------------------------------------------------
# gce_pd (ref: pkg/volume/gce_pd/ — attach via cloud, mount by device)
# ---------------------------------------------------------------------------

class DiskManager:
    """ref: gce_pd.go diskManager (AttachDisk/DetachDisk seams)."""

    def attach_disk(self, pd_name: str, read_only: bool) -> str:
        """-> device path"""
        raise NotImplementedError

    def detach_disk(self, pd_name: str) -> None:
        raise NotImplementedError


class FakeDiskManager(DiskManager):
    def __init__(self):
        self.attached: Dict[str, bool] = {}
        self.log: List[tuple] = []

    def attach_disk(self, pd_name, read_only):
        self.attached[pd_name] = read_only
        self.log.append(("attach", pd_name, read_only))
        return f"/dev/disk/by-id/google-{pd_name}"

    def detach_disk(self, pd_name):
        self.attached.pop(pd_name, None)
        self.log.append(("detach", pd_name))


class RefusingDiskManager(DiskManager):
    """Installed when no real cloud disk backend exists: attaching fails
    loudly so the pod is rejected with a mount error instead of silently
    running against an empty local dir (advisor finding r1 #2)."""

    def attach_disk(self, pd_name, read_only):
        raise RuntimeError(
            f"cannot attach GCE PD {pd_name!r}: no disk manager configured "
            "on this kubelet (no cloud provider)")

    def detach_disk(self, pd_name):
        raise RuntimeError(
            f"cannot detach GCE PD {pd_name!r}: no disk manager configured "
            "on this kubelet (no cloud provider)")


def _device_to_pd_name(device: str) -> Optional[str]:
    """Map a mounted device back to its GCE pd name. The mount table holds
    the resolved node (/dev/sdb), not the /dev/disk/by-id/google-<pd> alias
    mount(8) was given — reverse it through the by-id symlinks."""
    name = os.path.basename(device)
    if name.startswith("google-"):
        return name[len("google-"):]
    by_id = "/dev/disk/by-id"
    try:
        real = os.path.realpath(device)
        for entry in os.listdir(by_id):
            if not entry.startswith("google-"):
                continue
            if os.path.realpath(os.path.join(by_id, entry)) == real:
                return entry[len("google-"):]
    except OSError:
        pass
    return None


class GCEPersistentDiskPlugin(VolumePlugin):
    name = "kubernetes.io/gce-pd"

    def __init__(self, disk_manager: Optional[DiskManager] = None,
                 mounter: Optional[Mounter] = None):
        self.disks = disk_manager or FakeDiskManager()
        self.mounter = mounter or FakeMounter()

    def can_support(self, volume: api.Volume) -> bool:
        return volume.source is not None and \
            volume.source.gce_persistent_disk is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        b = _DirBuilder(self, volume.name, pod.metadata.uid)
        src = volume.source.gce_persistent_disk
        disks, mounter = self.disks, self.mounter

        def set_up():
            path = b.get_path()
            if mounter.is_mounted(path):
                return  # resync idempotency (ref: gce_pd.go SetUp IsMountPoint)
            device = disks.attach_disk(src.pd_name, src.read_only)
            os.makedirs(path, exist_ok=True)
            options = ["ro"] if src.read_only else []
            mounter.mount(device, path, src.fs_type or "ext4", options)
        b.set_up = set_up
        b.pd_name = src.pd_name
        return b

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        base = _DirBuilder(self, volume_name, pod_uid)
        disks, mounter = self.disks, self.mounter

        def tear_down():
            # read the device back from the mount table to recover the pd
            # name, as the reference's TearDown does (gce_pd.go), so the
            # cloud attachment is released and not leaked
            device = mounter.device_for(base.get_path())
            if mounter.is_mounted(base.get_path()):
                mounter.unmount(base.get_path())
            if device:
                pd_name = _device_to_pd_name(device)
                if pd_name:
                    disks.detach_disk(pd_name)
            _DirBuilder.tear_down(base)
        base.tear_down = tear_down
        return base


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------

class VolumePluginMgr:
    """ref: plugins.go VolumePluginMgr.{InitPlugins,FindPluginBySpec}."""

    def __init__(self, plugins: List[VolumePlugin], host: VolumeHost):
        self.plugins = list(plugins)
        self.host = host
        for p in self.plugins:
            p.init(host)

    def find_plugin(self, volume: api.Volume) -> VolumePlugin:
        matches = [p for p in self.plugins if p.can_support(volume)]
        if not matches:
            raise ValueError(f"no volume plugin matched {volume.name!r}")
        if len(matches) > 1:
            raise ValueError(
                f"multiple volume plugins matched: "
                f"{', '.join(p.name for p in matches)}")
        return matches[0]

    def find_plugin_by_name(self, name: str) -> Optional[VolumePlugin]:
        for p in self.plugins:
            if p.name == name or escape_plugin_name(p.name) == name:
                return p
        return None

    # -- kubelet-facing helpers (ref: kubelet.go mountExternalVolumes
    #    :974-1005 and getPodVolumesFromDisk) -----------------------------
    def mount_volumes(self, pod: api.Pod) -> Dict[str, Builder]:
        out: Dict[str, Builder] = {}
        for volume in pod.spec.volumes:
            plugin = self.find_plugin(volume)
            builder = plugin.new_builder(volume, pod)
            builder.set_up()
            out[volume.name] = builder
        return out

    def volumes_on_disk(self, pod_uid: str) -> List[tuple]:
        """[(plugin, volume_name)] found under the pod's volumes dir."""
        root = self.host.pod_volumes_dir(pod_uid)
        found = []
        if not os.path.isdir(root):
            return found
        for plugin_dir in sorted(os.listdir(root)):
            plugin = self.find_plugin_by_name(plugin_dir)
            for name in sorted(os.listdir(os.path.join(root, plugin_dir))):
                found.append((plugin, name))
        return found

    def cleanup_orphaned_volumes(self, active_pod_uids: List[str]) -> int:
        """Tear down volumes of pods that no longer exist
        (ref: kubelet.go cleanupOrphanedVolumes:1523-1556)."""
        removed = 0
        pods_root = os.path.join(self.host.root_dir, "pods")
        if not os.path.isdir(pods_root):
            return 0
        active = set(active_pod_uids)
        for uid in sorted(os.listdir(pods_root)):
            if uid in active:
                continue
            vols = self.volumes_on_disk(uid)
            if any(plugin is None for plugin, _ in vols):
                # an unrecognized plugin dir may hold a live mount we can't
                # tear down — deleting through it would destroy its contents
                # (the reference likewise skips pods it cannot clean,
                # kubelet.go:1523-1556)
                continue
            for plugin, name in vols:
                plugin.new_cleaner(name, uid).tear_down()
                removed += 1
            shutil.rmtree(os.path.join(pods_root, uid), ignore_errors=True)
        return removed


def new_default_plugin_mgr(root_dir: str, kubelet_client=None,
                           mounter: Optional[Mounter] = None,
                           disk_manager: Optional[DiskManager] = None,
                           git_exec=None) -> VolumePluginMgr:
    """ref: cmd/kubelet ProbeVolumePlugins."""
    host = VolumeHost(root_dir=root_dir, kubelet_client=kubelet_client)
    return VolumePluginMgr([
        EmptyDirPlugin(),
        HostPathPlugin(),
        GitRepoPlugin(exec_fn=git_exec),
        SecretPlugin(),
        NFSPlugin(mounter=mounter),
        GCEPersistentDiskPlugin(disk_manager=disk_manager, mounter=mounter),
    ], host)
