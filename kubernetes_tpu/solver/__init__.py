"""kube-solverd — the batch solver as a shared service.

One accelerator-grade solver process (``service.SolverService``, the
``cmd/solverd.py`` binary) serves solve requests from any number of
scheduler workers over a local socket (``client.RemoteSolver``), merging
concurrent waves into one padded batched device call (wave coalescing).
See docs/design/solver.md for the design.
"""
