"""MeshExecutor — the device-mesh production solve inside kube-solverd.

This is the piece that lifts ``parallel/mesh.py`` from a dryrun artifact
into the daemon's default multi-device dispatch. The daemon's resident
plane cache (the delta-wire v2 reconstruction target, solver/service.py)
gains a device half: the node/group/zone planes live on the mesh as
sharded/replicated jax buffers placed per ``parallel.mesh.input_shardings``,
and consecutive waves of one (worker, shape-bucket) pair touch the device
only O(changed rows + pod planes) per wave:

- **identity-anchored residency**: the service's copy-on-write delta
  reconstruction means an unchanged plane is the SAME numpy object wave
  to wave — the executor keys its device buffers on that object identity,
  so an "S" plane costs zero transfer and zero reshard;
- **deltas apply copy-on-write onto sharded planes**: a changed plane
  arrives as (base, rows, vals); when the resident buffer matches
  ``base`` by identity, the rows are scattered into the device array
  (``base.at[rows].set``) — the old buffer is donated, the result keeps
  the plane's NamedSharding, and only the rows cross the host boundary;
- **exact-shape programs**: waves run at the client's resident shape
  padded only to the mesh's node multiple (``pad_inputs_for_mesh``, pad
  widths memoized per (N, shards)) instead of the vmap fallback's pow-2
  node bucket — at the 50k/10k contract shape that alone removes a
  16384-vs-10000 node-axis scan waste;
- **donated pod planes, pre-partitioned outs**: the compiled program
  (``parallel.mesh.sharded_program``) donates the per-wave pod planes and
  pins in/out shardings, so back-to-back waves never reshard or copy the
  resident state (SNIPPETS.md [1-3]).

**Dispatch is a measured crossover, not a blind shard.** On real
multi-chip hardware the GSPMD scan is the capacity path (node planes
beyond one chip's HBM); on a CPU sub-mesh
(--xla_force_host_platform_device_count) the per-step tie-break
collectives make the fully-sharded scan SLOWER than one device (measured
3.1s vs 0.83s at 10k nodes x 1024 pods on the 24-core build box, matching
the 4k-node measurement in solve_sharded's docstring). The executor
therefore times both layouts once per (backend, device count, pods_axis,
plane shape) — the probe doubles as a live bit-identity check — picks the
winner, and persists the calibration in the warm-start dir
(``util/warmstart.mesh_cal_path``) so restarts skip the probe. The loser
layout stays armed: ``dispatch="shard"`` forces the full mesh (the
capacity story and the MULTICHIP live record), ``"single"`` pins the
1x1 submesh.

Decisions are bit-identical to the single-device and serial paths by the
same argument as ``solve_sharded`` (layout changes, arithmetic does not),
and the executor keeps that claim *live*: the first mesh wave of a run
(and every wave under ``probe="all"``) is re-solved on one device and
compared bitwise, counted in ``solverd_mesh_parity_*``.
"""

from __future__ import annotations

import contextlib
import functools
import json
import logging
import os
import threading
import time
import warnings
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from kubernetes_tpu.util import metrics, tracing, warmstart

__all__ = ["MeshExecutor"]

_log = logging.getLogger("kubernetes_tpu.solver.mesh_exec")


@contextlib.contextmanager
def _donation_warnings_scoped():
    """The sharded program donates the per-wave pod planes; most cannot
    alias an output or carry buffer (the scan carry is [N]-shaped and
    sourced from the NON-donated resident planes — by design), so XLA
    reports them unusable once per compiled program. Expected here, but
    the warning stays live for everyone else in the process."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@functools.lru_cache(maxsize=256)
def _scatter_fn(sharding, donate: bool = True):
    """Row scatter that keeps the plane's sharding and (by default)
    donates the old buffer: the copy-on-write delta apply, on device.

    Donation is safe ONLY for buffers XLA itself produced (a previous
    scatter's output): the executor owns those exclusively and the
    previous wave's solve has been read back before the next delta
    arrives (the solve thread is single). A buffer that came from
    ``jax.device_put`` of a host numpy array may ALIAS that array's
    memory on the CPU backend (zero-copy when alignment allows) — the
    delta cache keeps the host array alive for identity chaining, so
    donating the aliased device buffer frees memory numpy still owns and
    corrupts the native heap (observed live as ``malloc(): unsorted
    double linked list corrupted`` killing the daemon mid-churn; the
    in-process path in parallel/mesh.py documents the same hazard).
    The first delta after a fresh establish therefore uses the
    non-donating variant; every later delta donates."""
    import jax

    def f(base, rows, vals):
        return base.at[rows].set(vals)

    return jax.jit(f, out_shardings=sharding,
                   donate_argnums=(0,) if donate else ())


def _pow2_rows(rows: np.ndarray, vals: np.ndarray):
    """Bucket a delta's changed-row count to the next power of two by
    repeating the last (row, value) pair — idempotent under scatter-set
    (same index, same value) — so _scatter_fn compiles O(log k) programs
    per plane instead of one per distinct row count the churn happens to
    produce."""
    k = len(rows)
    want = 1 << max(k - 1, 0).bit_length()
    if k == 0 or want == k:
        return rows, vals
    extra = want - k
    rows = np.concatenate([rows, np.repeat(rows[-1:], extra, axis=0)])
    vals = np.concatenate([vals, np.repeat(vals[-1:], extra, axis=0)])
    return rows, vals


class MeshExecutor:
    """Owns the mesh, the dispatch calibration, and the device-resident
    plane cache. One instance per SolverService; all device work happens
    on the daemon's single solver thread."""

    def __init__(self, pods_axis: int = 1,
                 min_nodes: Optional[int] = None,
                 dispatch: str = "auto",
                 probe: str = "first",
                 cache_entries: int = 64):
        import jax

        from kubernetes_tpu.parallel import mesh as pm

        if dispatch not in ("auto", "shard", "single"):
            raise ValueError(
                f"mesh dispatch={dispatch!r}: expected auto|shard|single")
        if probe not in ("first", "all", "off"):
            raise ValueError(
                f"mesh probe={probe!r}: expected first|all|off")
        self.mesh = pm.make_mesh(pods_axis=pods_axis)
        self.submesh = pm.make_mesh(jax.devices()[:1], pods_axis=1)
        self.pods_axis = pods_axis
        self.min_nodes = (pm.DEFAULT_MESH_MIN_NODES
                          if min_nodes is None else int(min_nodes))
        self.dispatch = dispatch
        self.probe = probe
        self.cache_entries = cache_entries
        self._pm = pm
        # (wid, bucket) -> {"mesh": Mesh,
        #                   "planes": {name: (src, dev, xla_owned)}}
        # src: the host numpy object (identity chain anchor); dev: the
        # device buffer; xla_owned: True only when dev came out of an
        # XLA program (scatter output) — a device_put-established dev
        # may ALIAS src on the CPU backend and must NEVER be donated
        # (see _scatter_fn)
        self._resident: "OrderedDict[tuple, dict]" = OrderedDict()
        self._resident_bytes = 0
        # keys whose residency was LRU-evicted: their next wave's full
        # re-transfer counts as reshard (lost residency), not cold
        # first-contact transfer. Bounded: cleared when it outgrows the
        # cache several times over (stale entries only ever over-report).
        self._evicted: set = set()
        self._cal: Dict[str, dict] = {}
        self._cal_lock = threading.Lock()
        self._probed_once = False
        self._submesh_probed = False
        self._m = metrics.solverd_mesh_metrics()
        self._sm = metrics.solverd_submesh_metrics()
        self._m.devices.set(jax.device_count())
        self._m.pods_axis.set(pods_axis)
        self._load_cal()
        # exposed for tests and the startup banner
        self.mesh_waves = 0
        self.parity_checks = 0
        self.parity_divergent = 0
        self.submesh_waves = 0
        self.submesh_parity_divergent = 0

    # -- calibration persistence (warm start, keyed by mesh shape) ---------
    def _load_cal(self) -> None:
        if not warmstart.enabled():
            return
        try:
            with open(warmstart.mesh_cal_path()) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return
        if isinstance(data, dict) and data.get("v") == 1 \
                and isinstance(data.get("cals"), dict):
            self._cal.update(data["cals"])

    def _save_cal(self) -> None:
        if not warmstart.enabled():
            return
        path = warmstart.mesh_cal_path()
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with self._cal_lock:
                blob = json.dumps({"v": 1, "cals": self._cal})
            with open(tmp, "w") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        except OSError:
            pass

    def _cal_key(self, inp, pol, gangs: bool) -> str:
        import jax

        from kubernetes_tpu.solver import protocol
        fp = protocol.solver_fingerprint(pol, bool(gangs))[:8]
        return (f"{jax.default_backend()}x{jax.device_count()}"
                f"|pods_axis{self.pods_axis}"
                f"|N{inp.cap.shape[0]}|P{inp.req.shape[0]}"
                f"|R{inp.cap.shape[1]}|{inp.cap.dtype.str}|{fp}")

    # -- eligibility --------------------------------------------------------
    def eligible(self, inp, pol, gangs: bool) -> bool:
        """Kernel-vs-mesh-vs-single, the daemon half: waves below the
        node floor (or inside the Pallas kernel's domain on a
        kernel-capable backend) keep the padded vmap fallback — the
        measured numbers in solve_sharded's docstring say sharding buys
        them nothing. Everything else takes the mesh executor."""
        if int(inp.cap.shape[0]) < self.min_nodes:
            return False
        import jax

        from kubernetes_tpu.models.batch_solver import peer_bound_of
        from kubernetes_tpu.models.policy import BatchPolicy
        from kubernetes_tpu.ops import pallas_solver
        mode = os.environ.get("KTPU_PALLAS", "auto")
        if mode in ("auto", "interpret"):
            kernel_capable = (mode == "interpret"
                              or jax.default_backend() == "tpu")
            if kernel_capable and pallas_solver.eligible(
                    inp, pol or BatchPolicy(), gangs, peer_bound_of(inp)):
                return False
        return True

    @property
    def node_shards(self) -> int:
        return int(self.mesh.shape["nodes"])

    # -- the solve ----------------------------------------------------------
    def _active_mesh(self, inp, pol, gangs: bool):
        """The layout this wave runs under, probing the crossover once
        per calibration key when dispatch is auto. Returns
        (mesh, probe_result_or_None): a probe already solved the wave in
        both layouts, so its winner's answer is returned for reuse."""
        if self.dispatch == "single":
            return self.submesh, None
        if self.dispatch == "shard" or self.node_shards == 1:
            return self.mesh, None
        key = self._cal_key(inp, pol, gangs)
        with self._cal_lock:
            cal = self._cal.get(key)
        if cal is not None:
            return (self.mesh if cal.get("winner") == "shard"
                    else self.submesh), None
        single_res, single_s = self._time_layout(self.submesh, inp, pol,
                                                 gangs)
        shard_res, shard_s = self._time_layout(self.mesh, inp, pol, gangs)
        divergent = not (np.array_equal(single_res[0], shard_res[0])
                         and np.array_equal(single_res[1], shard_res[1]))
        # this probe IS a bitwise both-layouts comparison: the separate
        # first-wave parity probe would only repeat it
        self._probed_once = True
        self.parity_checks += 1
        self._m.parity_checks.inc()
        self._m.single_probe_s.observe(single_s)
        if divergent:
            # must never happen (the bit-identity contract); refuse to
            # cache a winner and serve the single-device answer
            self.parity_divergent += 1
            self._m.parity_divergent.inc()
            _log.error("mesh dispatch probe DIVERGED at %s "
                       "(sharded != single-device); pinning single", key)
            return self.submesh, single_res
        winner = "shard" if shard_s < single_s else "single"
        with self._cal_lock:
            self._cal[key] = {"winner": winner,
                              "sharded_s": round(shard_s, 4),
                              "single_s": round(single_s, 4)}
        self._save_cal()
        _log.info("mesh dispatch probe %s: sharded %.3fs vs single %.3fs "
                  "-> %s", key, shard_s, single_s, winner)
        return (self.mesh if winner == "shard" else self.submesh), (
            shard_res if winner == "shard" else single_res)

    def _time_layout(self, mesh, inp, pol, gangs: bool):
        """One full placed solve in ``mesh``'s layout -> (result, steady
        seconds). Compile + first run are untimed (warm start covers
        them across restarts); the timed run is the steady per-wave
        cost the dispatch decision is about."""
        import jax
        import jax.numpy as jnp

        padded, _n = self._pm.pad_inputs_for_mesh(inp, mesh)
        sh = self._pm.input_shardings(mesh)
        fn = self._pm.sharded_program(mesh, pol, gangs, donate=False)

        def place():
            res = tuple(jax.device_put(getattr(padded, f), getattr(sh, f))
                        for f in self._pm.RESIDENT_FIELDS)
            wav = tuple(jax.device_put(getattr(padded, f), getattr(sh, f))
                        for f in self._pm.WAVE_FIELDS)
            return res, wav

        res, wav = place()
        chosen, scores = fn(res, wav)
        both = np.asarray(jnp.stack([chosen, scores]))
        t0 = time.perf_counter()
        chosen, scores = fn(res, wav)
        both = np.asarray(jnp.stack([chosen, scores]))
        return (both[0], both[1]), time.perf_counter() - t0

    def solve(self, inp, pol, gangs: bool, cache_key: Optional[tuple] = None,
              delta: Optional[dict] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Solve one wave from (mostly) device-resident planes.

        ``inp`` is the service's reconstructed host-side SolverInputs;
        ``cache_key`` is the delta-wire (wid, bucket) pair the resident
        device planes are keyed under (None = no residency, e.g. a v1
        client); ``delta`` maps field name -> (base, rows, vals) for
        planes this wave changed, enabling the on-device scatter apply
        when the resident buffer matches ``base``."""
        import jax
        import jax.numpy as jnp

        t_wave = time.perf_counter()
        # kube-trace: the service's mesh path installs the wave's ambient
        # span before calling in; tctx None = untraced (free)
        tctx = tracing.current()
        t_pl0 = time.monotonic_ns()
        mesh, probed = self._active_mesh(inp, pol, gangs)
        self.mesh_waves += 1
        self._m.waves.inc()
        self._m.node_shards.set(mesh.shape["nodes"])
        pm = self._pm
        sh = pm.input_shardings(mesh)
        pad = int(pm._pad_width(int(inp.cap.shape[0]), mesh.shape["nodes"]))
        transfer = 0
        reshard = 0
        was_new = cache_key is not None and cache_key not in self._resident
        entry = self._resident.get(cache_key) if cache_key else None
        # freed covers the entry as it WAS, so a layout flip (same key
        # rebuilt under the other mesh) can't leak resident_bytes upward
        freed = sum(rec[1].nbytes for rec in entry["planes"].values()) \
            if entry is not None else 0
        lost_layout = entry is not None and entry["mesh"] is not mesh
        # residency lost wholesale (layout flip, or this key was LRU-
        # evicted since its last wave): every re-establish below is
        # reshard traffic, the signal back-to-back waves must keep near
        # zero — NOT cold first-contact transfer
        lost_residency = lost_layout or (was_new
                                         and cache_key in self._evicted)
        if entry is None or lost_layout:
            entry = {"mesh": mesh, "planes": {}}
        resident_dev = []
        for name in pm.RESIDENT_FIELDS:
            cur = getattr(inp, name)
            rec = entry["planes"].get(name)
            if rec is not None and rec[0] is cur:
                resident_dev.append(rec[1])
                continue
            d = delta.get(name) if delta else None
            if rec is not None and d is not None and d[0] is rec[0]:
                _src, base_dev, base_xla_owned = rec
                rows, vals = d[1], d[2]
                vals = self._pad_vals(name, vals, pad)
                rows, vals = _pow2_rows(np.ascontiguousarray(rows),
                                        np.ascontiguousarray(vals))
                # donate only XLA-owned bases: a device_put-established
                # base may alias the cached host array (see _scatter_fn)
                with _donation_warnings_scoped():
                    dev = _scatter_fn(getattr(sh, name),
                                      donate=base_xla_owned)(base_dev,
                                                             rows, vals)
                transfer += rows.nbytes + vals.nbytes
                xla_owned = True
            else:
                # host-side single-plane pad (PAD_SPEC): only THIS plane
                # is re-established — never a full padded input set.
                # The device buffer may ALIAS arr on the CPU backend
                # (zero-copy device_put): xla_owned=False keeps it out of
                # every donation path
                arr = pm.pad_plane(name, cur, pad)
                dev = jax.device_put(np.ascontiguousarray(arr),
                                     getattr(sh, name))
                transfer += arr.nbytes
                xla_owned = False
                if rec is not None or lost_residency:
                    # had residency, lost the identity chain (out-of-
                    # order base, eviction, layout flip): the cost this
                    # path must keep near zero between back-to-back waves
                    reshard += arr.nbytes
            entry["planes"][name] = (cur, dev, xla_owned)
            resident_dev.append(dev)
        if cache_key is not None:
            self._resident[cache_key] = entry
            self._resident.move_to_end(cache_key)
            self._evicted.discard(cache_key)
            self._resident_bytes += sum(
                rec[1].nbytes for rec in entry["planes"].values()) - freed
            while len(self._resident) > self.cache_entries:
                _k, old = self._resident.popitem(last=False)
                if len(self._evicted) > 16 * self.cache_entries:
                    self._evicted.clear()
                self._evicted.add(_k)
                self._resident_bytes -= sum(
                    rec[1].nbytes for rec in old["planes"].values())
            self._m.resident_bytes.set(self._resident_bytes)
            if was_new:
                # once per bucket: the per-device footprint evidence
                # (HBM headroom) the churn record scrapes
                self.memory_report(inp)
        if probed is not None:
            # the dispatch probe already solved this wave in BOTH layouts
            # (and compared them bitwise); residency was still installed
            # above so the NEXT wave rides the identity chain instead of
            # paying a full re-transfer
            self._m.transfer_bytes.inc(by=transfer)
            self._m.reshard_bytes.inc(by=reshard)
            return probed
        # kube-horizon active sub-mesh (models/submesh.py): on the
        # single-device layout — the measured winner at the contract
        # shape (r15: node_shards 1) — compact the node axis to the
        # nodes that could possibly place this wave before the dense
        # scan. Bit-identical by the keep-rule argument in the module
        # docstring, and probed live against the full plane below. The
        # gather runs ON DEVICE over the same resident planes, so
        # residency and the delta identity chain are untouched.
        plan = None
        zone_bf16 = False
        if int(mesh.shape["nodes"]) == 1:
            from kubernetes_tpu.models import submesh as sm
            t_k0 = time.perf_counter()
            plan = sm.plan_wave(inp, pol)
            if plan is not None:
                self._sm.compact_s.observe(time.perf_counter() - t_k0)
                self._sm.waves.inc()
                self._sm.nodes_kept.inc(by=plan.n_kept)
                self._sm.nodes_total.inc(by=plan.n_total)
                self.submesh_waves += 1
                zone_bf16 = sm.zone_bf16_ok(inp, pol)
            else:
                self._sm.full_waves.inc()
        wave_dev = []
        for name in pm.WAVE_FIELDS:
            arr = getattr(inp, name)
            if plan is not None and name == "pod_host_idx":
                # host pins move to compact indices host-side (pinned
                # nodes are kept by construction, so no pin is lost)
                from kubernetes_tpu.models import submesh as sm
                arr = sm.remap_pod_host_idx(arr, plan)
            wave_dev.append(jax.device_put(np.ascontiguousarray(arr),
                                           getattr(sh, name)))
            transfer += arr.nbytes
        if tctx is not None:
            # plane residency/transfer leg vs the device program itself —
            # the split the reshard-bytes wall analysis had to infer
            tracing.record("mesh.planes", t_pl0, time.monotonic_ns(),
                           parent=tctx, transfer=transfer, reshard=reshard)
        t_dv0 = time.monotonic_ns()
        # donate=False: every wave plane above came from device_put of a
        # request-owned host array and may alias it on the CPU backend —
        # donating an aliased buffer hands numpy-owned memory to XLA's
        # allocator and corrupts the native heap (the malloc() abort that
        # killed the daemon mid-churn until flightrec pinned the timing).
        # The wave planes are [P]-scale; forgoing their reuse costs ~KBs.
        if plan is not None:
            from kubernetes_tpu.models import submesh as sm
            fn = sm.submesh_program(pol, gangs, zone_bf16)
            chosen, scores = fn(tuple(resident_dev), tuple(wave_dev),
                                plan.keep_idx, plan.valid)
            both = np.asarray(jnp.stack([chosen, scores]))
        else:
            fn = pm.sharded_program(mesh, pol, gangs, donate=False)
            with _donation_warnings_scoped():
                chosen, scores = fn(tuple(resident_dev), tuple(wave_dev))
                both = np.asarray(jnp.stack([chosen, scores]))
        if tctx is not None:
            tracing.record("mesh.device_solve", t_dv0, time.monotonic_ns(),
                           parent=tctx,
                           node_shards=int(mesh.shape["nodes"]),
                           submesh=plan.n_kept if plan is not None else 0)
        self._m.transfer_bytes.inc(by=transfer)
        self._m.reshard_bytes.inc(by=reshard)
        self._m.solve_s.observe(time.perf_counter() - t_wave)
        out = (both[0], both[1])
        if plan is not None and (self.probe == "all"
                                 or not self._submesh_probed):
            self._submesh_probed = True
            self._submesh_parity_probe(inp, pol, gangs, mesh, out)
        if self.probe == "all" or (self.probe == "first"
                                   and not self._probed_once):
            self._probed_once = True
            self._parity_probe(inp, pol, gangs, mesh, out)
        return out

    def _submesh_parity_probe(self, inp, pol, gangs, mesh, out) -> None:
        """Re-solve a compacted wave on the FULL node plane (same mesh,
        no compaction) and compare bitwise — the live evidence that the
        keep rule, the index remap, and any gated precision downgrade
        (zone_bf16) changed the layout and nothing else. Runs on the
        first submesh wave of a run, every wave under probe='all';
        never under probe='off'."""
        if self.probe == "off":
            return
        try:
            res, _t = self._time_layout(mesh, inp, pol, gangs)
        except Exception as e:  # noqa: BLE001 — a probe must never kill a wave
            _log.warning("submesh parity probe failed to run: %s", e)
            return
        self._sm.parity_checks.inc()
        if not (np.array_equal(res[0], out[0])
                and np.array_equal(res[1], out[1])):
            self.submesh_parity_divergent += 1
            self._sm.parity_divergent.inc()
            _log.error("submesh parity probe DIVERGED: compacted vs full "
                       "plane — keep rule or remap violated bit-identity")

    def _parity_probe(self, inp, pol, gangs, active_mesh, out) -> None:
        """Re-solve the same wave in the OTHER layout (single-device
        submesh, or the full mesh when the active layout already is the
        submesh) and compare bitwise — the live every-run evidence behind
        the 'layout changes, decisions do not' contract."""
        other = self.submesh if active_mesh is not self.submesh else self.mesh
        try:
            res, t = self._time_layout(other, inp, pol, gangs)
        except Exception as e:  # noqa: BLE001 — a probe must never kill a wave
            _log.warning("mesh parity probe failed to run: %s", e)
            return
        self.parity_checks += 1
        self._m.parity_checks.inc()
        self._m.single_probe_s.observe(t)
        if not (np.array_equal(res[0], out[0])
                and np.array_equal(res[1], out[1])):
            self.parity_divergent += 1
            self._m.parity_divergent.inc()
            _log.error("mesh parity probe DIVERGED: %s vs %s layout",
                       active_mesh.shape, other.shape)

    def _pad_vals(self, name: str, vals: np.ndarray, pad: int) -> np.ndarray:
        """Row-delta values padded to the resident (mesh-padded) row
        width. Only planes whose node axis is NOT axis 0 need this: their
        delta rows span the full padded row. Fills match
        pad_inputs_for_mesh exactly (zone pads unlabeled, counts pad
        zero)."""
        if pad == 0:
            return vals
        if name == "zone_idx":          # [k, N] -> [k, N+pad], unlabeled
            return np.pad(vals, ((0, 0), (0, pad)), constant_values=-1)
        if name == "group_counts":      # [k, N+1] -> [k, N+1+pad], empty
            return np.pad(vals, ((0, 0), (0, pad)), constant_values=0)
        return vals

    def memory_report(self, inp) -> dict:
        """shard_memory_report under the full mesh, surfaced to the
        ``solverd_mesh_shard_bytes_per_device`` gauge by the service."""
        rep = self._pm.shard_memory_report(inp, self.mesh)
        self._m.shard_bytes_per_device.set(rep["total_bytes_per_device"])
        return rep
