"""kube-solverd — the shared solver daemon with wave coalescing.

Why this process exists: the multi-process churn topology runs N scheduler
workers, and each one solved its waves **in-process on CPU** (solve p50
854 ms/wave at full shape, CHURN_MP_r05_fullshape.json) because worker
processes cannot share the one accelerator-grade solver runtime — while a
device that clears bigger waves in ~122 ms sat attached to the same host.
This daemon owns that runtime and serves every worker over a local socket
(the same topology move kube-store made for the cluster store).

**Wave coalescing.** Requests arriving within a short gather window are
merged into ONE padded batched device call and fanned back out
per-requester:

- each request's SolverInputs is padded (per axis, pow-2 bucketed — the
  same compile-bounding trick models/incremental.py uses for the pod
  axis) to the group's target shape. Padding is decision-invariant by
  construction: pad nodes carry ``node_extra_ok=False`` (never feasible,
  advertise nothing, zero capacity), pad pods pin to host index -2 with
  zero requests (never placeable, commit nothing), pad vocabulary/zone
  columns are all-zero (no conflicts, no violations, zero scores), and
  the group-counts off-list slot moves with the node axis;
- requests sharing a solver-config fingerprint (policy + gangs + resource
  dtype) stack on a new leading batch axis and run through one
  ``jit(vmap(solve_jit))`` program — every arithmetic op the per-request
  scan performs is exact (integer, or float32 pinned to HIGHEST
  precision), so batched results are bit-identical to solo runs;
- the batch axis itself is pow-2 bucketed by replicating the first
  request, so the daemon compiles O(log) programs per family, not one
  per gather-window occupancy.

**Backpressure.** The request queue is bounded: when ``max_queue`` waves
are already waiting, new requests get an immediate BUSY reply (the
apiserver's 429 analog) instead of unbounded queueing latency — the
client falls back to its in-process path for that wave, so a wedged or
overloaded daemon degrades to exactly the pre-solverd behavior.

**Delta wire (protocol v2).** The daemon keeps a resident plane cache
keyed by (worker id, shape bucket): a client that already shipped a full
frame for a bucket thereafter ships only the changed rows of the
node/group/zone planes (``protocol.DELTA_FIELDS``) plus the per-wave pod
planes. Reconstruction is copy-on-write — an applied delta produces NEW
arrays, never mutating planes a queued earlier wave still references —
and the cache entry is only installed when the wave is actually
enqueued, so a BUSY bounce leaves client and daemon views consistent.
Any mismatch (no entry after a restart or eviction, epoch skew, shape
drift) is answered with ``{"resync": reason}`` before any solve work;
the client re-sends the wave as a full frame. Solves stay bit-identical:
the daemon either reconstructs byte-identical inputs or refuses.
"""

from __future__ import annotations

import functools
import logging
import os
import socket
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Tuple

import numpy as np

from kubernetes_tpu.models.policy import BatchPolicy
from kubernetes_tpu.models.snapshot import _pow2_pad
from kubernetes_tpu.solver import protocol
from kubernetes_tpu.solver.prewarm import PrewarmController, pow2_ladder
from kubernetes_tpu.util import metrics, tracing

__all__ = ["SolverService"]

_log = logging.getLogger("kubernetes_tpu.solver.service")

# SolverInputs field -> (axis names, pad fill). Axis names resolve against
# the per-group target dims; fills are the decision-invariant values the
# module docstring argues for. N1 (group_counts' node axis) is special:
# its last column is the off-list slot and must stay last after padding.
_PAD_SPEC = {
    "cap":             (("N", "R"), 0),
    "advertises":      (("N", "R"), False),
    "fit_used":        (("N", "R"), 0),
    "fit_exceeded":    (("N",), False),
    "score_used":      (("N", "R"), 0),
    "node_ports":      (("N", "Wp"), 0),
    "node_sel":        (("N", "Ks"), False),
    "node_pds":        (("N", "Wd"), 0),
    "node_extra_ok":   (("N",), False),
    "req":             (("P", "R"), 0),
    "pod_ports":       (("P", "Wp"), 0),
    "pod_sel":         (("P", "Ks"), False),
    "pod_pds":         (("P", "Wd"), 0),
    "pod_host_idx":    (("P",), -2),
    "tie_hi":          (("P",), 0),
    "tie_lo":          (("P",), 0),
    "pod_gid":         (("P",), -1),
    "pod_group_member": (("P", "G"), False),
    "group_counts":    (("G", "N1"), 0),
    "gang_start":      (("P",), True),
    "score_static":    (("N",), 0),
    "node_aff_vals":   (("N", "L"), -1),
    "pod_aff_static":  (("P", "L"), -2),
    "anchor_vals0":    (("G", "L"), 0),
    "has_anchor0":     (("G",), False),
    "zone_idx":        (("A", "N"), -1),   # pad nodes are unlabeled
    "zone_counts0":    (("A", "G", "V"), 0),  # phantom zones hold no peers
    # kube-preempt: pad pods carry priority 0 and can never preempt; pad
    # bands are BAND_EMPTY (never strictly below any priority); pad nodes
    # hold no evictable pods
    "pod_prio":        (("P",), 0),
    "pod_can_preempt": (("P",), False),
    "band_prio":       (("B",), 2**31 - 1),
    "evict_cap":       (("N", "B", "R"), 0),
    "evict_cnt":       (("N", "B"), 0),
}


def _dims_of(inp) -> Dict[str, int]:
    return {
        "N": inp.cap.shape[0], "R": inp.cap.shape[1],
        "Wp": inp.node_ports.shape[1], "Ks": inp.node_sel.shape[1],
        "Wd": inp.node_pds.shape[1], "P": inp.req.shape[0],
        "G": inp.group_counts.shape[0], "L": inp.node_aff_vals.shape[1],
        "A": inp.zone_idx.shape[0], "V": inp.zone_counts0.shape[2],
        "B": inp.band_prio.shape[0],
    }


def _target_dims(all_dims: List[Dict[str, int]]) -> Dict[str, int]:
    """Group target: pow-2 bucket of the per-axis max. L and A are fixed by
    the (shared) policy, so bucketing them is a no-op; everything else
    genuinely varies wave to wave."""
    t: Dict[str, int] = {}
    for k in all_dims[0]:
        m = max(d[k] for d in all_dims)
        if k in ("L", "A"):
            t[k] = m
        elif k == "B":
            # B == 0 must STAY 0: padding a band axis into a legacy wave
            # would compile the preemption sub-program for it
            t[k] = 0 if m == 0 else _pow2_pad(m, minimum=2)
        elif k == "G":
            t[k] = _pow2_pad(m, minimum=8)
        else:
            t[k] = _pow2_pad(m, minimum=1)
    t["N1"] = t["N"] + 1
    return t


def _pad_inputs(inp, target: Dict[str, int]):
    """Pad one request's SolverInputs to the group target shape with the
    decision-invariant fills; returns the same NamedTuple type."""
    out = []
    for name, arr in zip(inp._fields, inp):
        axes, fill = _PAD_SPEC[name]
        want = tuple(target[a] for a in axes)
        if arr.shape == want:
            out.append(arr)
            continue
        if name == "group_counts":
            # off-list slot is the LAST column at every size: move it
            g, n1 = arr.shape
            grown = np.zeros(want, arr.dtype)
            grown[:g, :n1 - 1] = arr[:, :n1 - 1]
            grown[:g, want[1] - 1] = arr[:, n1 - 1]
            out.append(grown)
            continue
        grown = np.full(want, fill, arr.dtype)
        grown[tuple(slice(0, s) for s in arr.shape)] = arr
        out.append(grown)
    return type(inp)(*out)


@functools.lru_cache(maxsize=64)
def _batched_solver(pol: BatchPolicy, gangs: bool):
    """One compiled program family per (policy, gangs): vmap of the XLA
    sequential-commit scan over a leading batch axis. solve_jit's per-item
    semantics are preserved exactly under vmap (all decision arithmetic is
    integer or HIGHEST-precision f32 — see models/batch_solver.py)."""
    import jax

    from kubernetes_tpu.models.batch_solver import solve_jit

    return jax.jit(jax.vmap(functools.partial(solve_jit, pol=pol,
                                              gangs=gangs)))


class _SolverdMetrics:
    _singleton = None

    def __init__(self):
        reg = metrics.default_registry()
        self.queue_depth = reg.gauge(
            "solverd_queue_depth", "Waves waiting for the gather window")
        self.requests = reg.counter(
            "solverd_requests_total", "Solve requests by outcome",
            ("outcome",))
        self.waves = reg.counter(
            "solverd_coalesced_waves_total",
            "Waves folded into batched device solves")
        self.solves = reg.counter(
            "solverd_device_solves_total",
            "Batched device solve calls (coalesce factor = waves/solves)")
        self.batch = reg.histogram(
            "solverd_batch_waves", "Waves per batched solve",
            buckets=(1, 2, 4, 8, 16, 32))
        self.occupancy = reg.histogram(
            "solverd_gather_occupancy",
            "Gather-window fill fraction (waves gathered / max_batch)",
            buckets=(0.0625, 0.125, 0.25, 0.5, 0.75, 1.0))
        self.solve_s = reg.histogram(
            "solverd_solve_seconds", "Batched solve wall time",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5))


def _solverd_metrics() -> _SolverdMetrics:
    if _SolverdMetrics._singleton is None:
        _SolverdMetrics._singleton = _SolverdMetrics()
    return _SolverdMetrics._singleton


class _Req:
    __slots__ = ("inp", "pol", "gangs", "p", "conn", "send_lock",
                 "cache_key", "delta", "trace", "t_enq")

    def __init__(self, inp, pol, gangs, p, conn, send_lock,
                 cache_key=None, delta=None, trace=None):
        self.inp = inp          # host-side SolverInputs (numpy)
        self.pol = pol
        self.gangs = gangs
        self.p = p              # requester's pod-axis length (reply slice)
        self.conn = conn
        self.send_lock = send_lock
        # delta-wire residency handles for the mesh executor: the cache
        # entry this wave belongs to and, per changed plane, the
        # (base, rows, vals) triple whose device twin can be applied as
        # an on-device scatter instead of a full re-transfer
        self.cache_key = cache_key
        self.delta = delta
        # v3 trace context of the requesting wave (protocol.parse_trace)
        # + enqueue instant: the daemon's queue-wait and solve spans
        # attach to the wave's trace in the merged per-run artifact
        self.trace = trace
        self.t_enq = time.monotonic_ns()


class SolverService:
    """The kube-solverd daemon loop. One thread per connection reads and
    enqueues requests (replying BUSY itself when the queue is full); ONE
    solver thread gathers, coalesces, solves, and writes replies."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 gather_window_s: float = 0.003, max_batch: int = 16,
                 max_queue: int = 64, cache_entries: int = 64,
                 mesh: str = "auto", pods_axis: int = 1,
                 mesh_min_nodes=None, mesh_dispatch: str = "auto",
                 mesh_probe: str = "first", prewarm: bool = False,
                 prewarm_nodes: int = 0, prewarm_pods: int = 1024,
                 prewarm_batch: int = 1):
        from kubernetes_tpu.models.batch_solver import ensure_x64
        ensure_x64()  # spread_score's exact-rounding emulation needs x64
        self.gather_window_s = gather_window_s
        self.max_batch = max_batch
        self.max_queue = max_queue
        # device-mesh production dispatch (solver/mesh_exec.py): auto-on
        # when more than one device is attached; single-wave groups above
        # the node floor then solve from device-resident sharded planes
        self._mesh_exec = None
        import jax

        from kubernetes_tpu.parallel.mesh import maybe_mesh
        if maybe_mesh(mesh, pods_axis) is not None:
            from kubernetes_tpu.solver.mesh_exec import MeshExecutor
            self._mesh_exec = MeshExecutor(
                pods_axis=pods_axis, min_nodes=mesh_min_nodes,
                dispatch=mesh_dispatch, probe=mesh_probe,
                cache_entries=cache_entries)
            _log.info("mesh dispatch enabled: %d devices, pods_axis=%d, "
                      "node_shards=%d, min_nodes=%d, dispatch=%s",
                      jax.device_count(), pods_axis,
                      self._mesh_exec.node_shards,
                      self._mesh_exec.min_nodes, mesh_dispatch)
        # delta-wire resident plane cache: (wid, bucket) -> {"epoch": n,
        # "planes": {field: np.ndarray}} — arrays are immutable by
        # convention (copy-on-write on delta apply), LRU-bounded
        self.cache_entries = cache_entries
        self._plane_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        self._cache_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self._stopped = threading.Event()
        self._cond = threading.Condition()
        # ktpu-vet: ok thread-discipline — bounded by the BUSY backpressure
        # check (len >= max_queue under _cond) before every append
        self._pending: deque = deque()
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._m = _solverd_metrics()
        self._dm = metrics.solverd_delta_metrics()
        self._sx = metrics.slipstream_metrics()
        # device-call / wave counters, exposed for tests and /metrics alike
        self.solve_calls = 0
        self.waves_served = 0
        self.delta_waves = 0
        self.resync_replies = 0
        # kube-slipstream prewarm (solver/prewarm.py): the daemon's fill
        # trigger watches every padded group's true occupancy against the
        # pow-2 bucket it solved in (BATCH = the vmap batch axis) and
        # compiles the next bucket off the solve loop; --prewarm boot
        # mode seeds the bucket set implied by the declared cluster size
        self._prewarm = None
        self._prewarm_exemplar = None    # (SolverInputs, pol, gangs)
        self._boot_hints = (int(prewarm_nodes), int(prewarm_pods),
                            int(prewarm_batch)) if prewarm else None
        if os.environ.get("KTPU_PREWARM", "auto") != "off":
            self._prewarm = PrewarmController(self._prewarm_compile,
                                              name="solverd-prewarm")
        elif prewarm:
            # boot mode explicitly requested but the compile thread is
            # env-disabled: report ready so nothing gates on us
            self._sx.prewarm_ready.set(1)
        # worker-reported encoder resync accounting (the "enc" header
        # field each solve frame piggybacks): latest [replay, full]
        # totals per worker, exposed as fleet-sum gauges on /metrics
        self._enc_reported: Dict[str, Tuple[int, int]] = {}
        self._enc_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    @property
    def address(self) -> str:
        host, port = self._sock.getsockname()[:2]
        return f"{host}:{port}"

    def _start_prewarm(self) -> None:
        if self._prewarm is None:
            return
        self._prewarm.start()
        if self._boot_hints is not None:
            threading.Thread(target=self._prewarm_boot, daemon=True,
                             name="solverd-prewarm-boot").start()

    def start(self) -> "SolverService":
        self._start_prewarm()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="solverd-accept")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._solve_loop, daemon=True,
                             name="solverd-solve")
        t.start()
        self._threads.append(t)
        return self

    def serve_forever(self) -> None:
        self._start_prewarm()
        t = threading.Thread(target=self._solve_loop, daemon=True,
                             name="solverd-solve")
        t.start()
        self._threads.append(t)
        self._accept_loop()

    def stop(self) -> None:
        self._stopped.set()
        if self._prewarm is not None:
            self._prewarm.stop()
        with self._cond:
            self._cond.notify_all()
        try:
            # shutdown BEFORE close: close() alone does not wake a thread
            # blocked in accept(), and while that syscall blocks the
            # kernel keeps the socket in LISTEN — a restarted daemon then
            # can't rebind the port
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # close accepted connections too: their threads are blocked in
        # recv, and a lingering child socket keeps the port unbindable
        # for a restarted daemon
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # -- connection side ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # accepted sockets do NOT inherit the listener's SO_REUSEADDR;
            # without it their FIN_WAIT remnants block a restarted daemon
            # from rebinding the port
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            # Bounded SEND only (not settimeout, which would also kill
            # idle keep-alive recv): replies are written by the ONE solver
            # thread, so a stalled client with a full receive buffer would
            # otherwise wedge every queued wave daemon-wide. On timeout
            # sendall raises (caught as OSError) and the reply is dropped
            # — the wedged requester's problem, not the fleet's.
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            struct.pack("ll", 30, 0))
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="solverd-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # the reply to an accepted solve is written by the solver thread;
        # BUSY/error/ping replies by this thread. A client sends one
        # request at a time per connection, but the lock keeps even a
        # misbehaving client's frames whole.
        send_lock = threading.Lock()
        try:
            while not self._stopped.is_set():
                msg = protocol.recv_msg(conn)
                if msg is None:
                    return
                header, arrays = msg
                op = header.get("op", "")
                if op == "ping":
                    with send_lock:
                        protocol.send_msg(conn, {
                            "ok": True, "v": protocol.PROTOCOL_VERSION,
                            "solves": self.solve_calls,
                            "waves": self.waves_served})
                    continue
                if op != "solve":
                    with send_lock:
                        protocol.send_msg(conn, {
                            "err": "SolverProtocolError",
                            "msg": f"unknown op {op!r}"})
                    continue
                self._enqueue_solve(header, arrays, conn, send_lock)
        except (OSError, protocol.SolverProtocolError, ValueError) as e:
            _log.debug("solverd connection dropped: %s", e)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _enqueue_solve(self, header: dict, arrays: List[np.ndarray],
                       conn: socket.socket,
                       send_lock: threading.Lock) -> None:
        from kubernetes_tpu.models.batch_solver import SolverInputs

        def reject(err: str, msg: str) -> None:
            self._m.requests.inc("error")
            with send_lock:
                protocol.send_msg(conn, {"err": err, "msg": msg})

        def resync(reason: str) -> None:
            # NOT an error: the designed cold-cache/skew answer. The client
            # re-sends the same wave as a full frame.
            self.resync_replies += 1
            self._dm.resyncs.inc(reason)
            with send_lock:
                protocol.send_msg(conn, {"resync": reason})

        v = header.get("v")
        if not (isinstance(v, int) and protocol.MIN_PROTOCOL_VERSION
                <= v <= protocol.PROTOCOL_VERSION):
            reject("SolverProtocolError",
                   f"protocol version skew: daemon speaks "
                   f"{protocol.MIN_PROTOCOL_VERSION}.."
                   f"{protocol.PROTOCOL_VERSION}, request is {v!r}")
            return
        try:
            pol = protocol.policy_from_wire(header["policy"])
        except (KeyError, TypeError, ValueError) as e:
            reject("SolverProtocolError", f"bad policy: {e}")
            return
        gangs = bool(header.get("gangs", False))
        # a v1 client computed its fingerprint with v=1 — derive likewise
        fp = protocol.solver_fingerprint(pol, gangs, version=v)
        if header.get("fp") not in (None, fp):
            reject("SolverProtocolError",
                   f"fingerprint mismatch: request {header.get('fp')!r}, "
                   f"daemon derives {fp!r}")
            return

        # kube-slipstream: schedulers piggyback their encoder resync
        # counters (replay_total, full_total) on the solve header so the
        # daemon's /metrics shows cluster-wide resync health without a
        # second scrape target. Per-scheduler last-seen values, summed.
        enc = header.get("enc")
        if isinstance(enc, (list, tuple)) and len(enc) == 2:
            ch = header.get("cache")
            wid = ch.get("wid") if isinstance(ch, dict) else None
            key = str(wid) if wid is not None else f"conn{id(conn)}"
            try:
                pair = (int(enc[0]), int(enc[1]))
            except (TypeError, ValueError):
                pair = None
            if pair is not None:
                with self._enc_lock:
                    self._enc_reported[key] = pair
                    rep = sum(p[0] for p in self._enc_reported.values())
                    ful = sum(p[1] for p in self._enc_reported.values())
                self._sx.replay_reported.set(rep)
                self._sx.full_reported.set(ful)

        fields = SolverInputs._fields
        planes = header.get("planes")
        cache_hdr = header.get("cache")
        shipped = sum(a.nbytes for a in arrays)
        cache_key = epoch = None
        new_planes: Dict[str, np.ndarray] = {}
        delta_updates: Dict[str, tuple] = {}
        is_delta = False
        if planes is None:
            # v1-style full frame: every field present, nothing cached
            if len(arrays) != len(fields):
                reject("SolverProtocolError",
                       f"expected {len(fields)} arrays, got {len(arrays)}")
                return
            cols = list(arrays)
        else:
            if len(planes) != len(fields):
                reject("SolverProtocolError",
                       f"expected {len(fields)} plane entries, "
                       f"got {len(planes)}")
                return
            is_delta = any(p != "F" for p in planes)
            entry = None
            if cache_hdr is not None:
                try:
                    cache_key = (str(cache_hdr["wid"]),
                                 str(cache_hdr["bucket"]))
                    epoch = int(cache_hdr.get("epoch", 0))
                except (KeyError, TypeError, ValueError) as e:
                    reject("SolverProtocolError", f"bad cache header: {e}")
                    return
            if is_delta:
                if cache_key is None:
                    reject("SolverProtocolError",
                           "delta planes without a cache header")
                    return
                with self._cache_lock:
                    entry = self._plane_cache.get(cache_key)
                if entry is None:
                    resync("no_cache")
                    return
                if entry["epoch"] != epoch:
                    resync("epoch")
                    return
            it = iter(arrays)
            cols = []
            try:
                for name, p in zip(fields, planes):
                    if p == "F":
                        arr = next(it)
                        if cache_key is not None and \
                                name in protocol.DELTA_FIELDS:
                            # own buffer: cached planes must not pin the
                            # whole receive frame nor alias its reuse
                            arr = np.array(arr, copy=True)
                            new_planes[name] = arr
                        cols.append(arr)
                    elif p == "S":
                        cols.append(entry["planes"][name])
                    elif isinstance(p, list) and len(p) == 2 \
                            and p[0] == "D":
                        rows = next(it)
                        vals = next(it)
                        base = entry["planes"][name]
                        if (rows.ndim != 1 or vals.shape[:1] != rows.shape
                                or vals.shape[1:] != base.shape[1:]
                                or vals.dtype != base.dtype
                                or (rows.size and
                                    (int(rows.max()) >= base.shape[0]
                                     or int(rows.min()) < 0))):
                            resync("shape")
                            return
                        # copy-on-write: queued earlier waves may still
                        # reference the base plane
                        arr = base.copy()
                        arr[rows.astype(np.int64)] = vals
                        new_planes[name] = arr
                        # the mesh executor can replay this as an
                        # on-device scatter when its resident buffer
                        # still matches `base` by identity
                        delta_updates[name] = (base, rows, vals)
                        cols.append(arr)
                    else:
                        reject("SolverProtocolError",
                               f"bad plane entry {p!r} for {name}")
                        return
            except KeyError:
                resync("missing_plane")
                return
            except StopIteration:
                reject("SolverProtocolError", "truncated delta frame")
                return
            if next(it, None) is not None:
                reject("SolverProtocolError", "trailing arrays in frame")
                return
        inp = SolverInputs(*cols)
        req = _Req(inp, pol, gangs, int(inp.req.shape[0]), conn, send_lock,
                   cache_key=cache_key, delta=delta_updates or None,
                   trace=protocol.parse_trace(header))
        with self._cond:
            if len(self._pending) >= self.max_queue:
                busy = True
            else:
                busy = False
                self._pending.append(req)
                self._m.queue_depth.set(len(self._pending))
                self._cond.notify()
        if busy:
            # cache deliberately untouched: the client will not advance
            # its mirror for a bounced wave, so both sides stay at the
            # pre-frame epoch
            self._m.requests.inc("busy")
            with send_lock:
                protocol.send_msg(conn, {"busy": True})
            return
        self._dm.bytes_shipped.inc(by=shipped)
        self._dm.bytes_saved.inc(
            by=max(0, sum(c.nbytes for c in cols) - shipped))
        if is_delta:
            self.delta_waves += 1
            self._dm.hits.inc()
        else:
            self._dm.full_frames.inc()
        if cache_key is not None:
            with self._cache_lock:
                prev = self._plane_cache.pop(cache_key, None)
                merged = dict(prev["planes"]) if prev else {}
                merged.update(new_planes)
                self._plane_cache[cache_key] = {
                    "epoch": (epoch or 0) + 1, "planes": merged}
                while len(self._plane_cache) > self.cache_entries:
                    self._plane_cache.popitem(last=False)
                self._dm.cache_entries.set(len(self._plane_cache))

    # -- solver side -------------------------------------------------------
    def _gather(self) -> List[_Req]:
        """Block for the first request, then keep gathering until the
        window closes or the batch is full."""
        with self._cond:
            while not self._pending and not self._stopped.is_set():
                self._cond.wait(0.1)
            if self._stopped.is_set():
                return []
            batch = [self._pending.popleft()]
        deadline = time.monotonic() + self.gather_window_s
        while len(batch) < self.max_batch:
            with self._cond:
                while self._pending and len(batch) < self.max_batch:
                    batch.append(self._pending.popleft())
                if len(batch) >= self.max_batch:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopped.is_set():
                    break
                self._cond.wait(remaining)
        with self._cond:
            self._m.queue_depth.set(len(self._pending))
        return batch

    def _solve_loop(self) -> None:
        while not self._stopped.is_set():
            batch = self._gather()
            if not batch:
                continue
            self._m.occupancy.observe(len(batch) / self.max_batch)
            groups: Dict[tuple, List[_Req]] = {}
            for r in batch:
                key = (r.pol, r.gangs, str(r.inp.cap.dtype),
                       r.inp.node_aff_vals.shape[1],
                       r.inp.zone_idx.shape[0])
                groups.setdefault(key, []).append(r)
            for reqs in groups.values():
                try:
                    self._solve_group(reqs)
                except Exception as e:  # noqa: BLE001 — must answer anyway
                    _log.exception("batched solve failed (%d waves)",
                                   len(reqs))
                    self._m.requests.inc("error")
                    for r in reqs:
                        try:
                            with r.send_lock:
                                protocol.send_msg(r.conn, {
                                    "err": type(e).__name__, "msg": str(e)})
                        except OSError:
                            pass

    def _device_solve(self, stacked, pol: BatchPolicy, gangs: bool
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """One batched device call. Overridable seam (tests inject slow or
        counting fakes to drive backpressure deterministically)."""
        import jax.numpy as jnp

        fn = _batched_solver(pol, gangs)
        chosen, scores = fn(stacked)
        # one readback for both planes, like batch_solver.solve
        both = np.asarray(jnp.stack([chosen, scores]))
        return both[0], both[1]

    # -- kube-slipstream prewarm (solver/prewarm.py) ------------------------
    def _prewarm_compile(self, target: Dict[str, int]) -> None:
        """Prewarm-thread compile of one batched bucket: pad the latest
        exemplar wave to the target axis dims, replicate it across the
        target batch axis, and run it through the SAME jit(vmap) program
        cache (_batched_solver) the solve loop hits. _device_solve reads
        the result back, so the executable is complete — and persisted
        via util/warmstart.py — before any live wave can need it.
        Elementwise max against the exemplar's own dims keeps the pad
        grow-only when live shapes moved between queue and compile."""
        ex = self._prewarm_exemplar
        if ex is None:
            raise RuntimeError("no exemplar wave to pad from")
        inp, pol, gangs = ex
        t = dict(target)
        batch = max(1, int(t.pop("BATCH", 1)))
        dims = _dims_of(inp)
        t = {k: max(int(v), dims.get(k, 0)) for k, v in t.items()}
        for k, v in dims.items():
            t.setdefault(k, v)
        t["N1"] = t["N"] + 1
        padded = _pad_inputs(inp, t)
        stacked = type(padded)(*(np.stack([c] * batch) for c in padded))
        self._device_solve(stacked, pol, gangs)

    def _prewarm_boot(self) -> None:
        """--prewarm boot mode: compile the bucket set implied by the
        declared cluster size (--prewarm-nodes/-pods/-batch) before the
        first request arrives, from a synthetic exemplar wave shaped
        like the churn harness's cluster (64cpu/256Gi nodes, 100m/128Mi
        pods, default policy). A live wave whose policy or resource
        dtype differs simply misses these entries and compiles as today
        — the fill trigger covers it from then on. The boot set arms
        the compile_prewarm_ready gauge the harness load window gates
        on."""
        nodes_hint, pods_hint, batch_hint = self._boot_hints
        try:
            from kubernetes_tpu.api import types as api
            from kubernetes_tpu.api.quantity import Quantity
            from kubernetes_tpu.models.batch_solver import \
                snapshot_to_host_inputs
            from kubernetes_tpu.models.snapshot import encode_snapshot
            floor = min(64, max(1, pods_hint))
            node = api.Node(
                metadata=api.ObjectMeta(name="prewarm-node"),
                spec=api.NodeSpec(capacity={
                    "cpu": Quantity("64"), "memory": Quantity("256Gi")}))
            res = api.ResourceRequirements(limits={
                "cpu": Quantity("100m"), "memory": Quantity("128Mi")})
            pods = [api.Pod(
                metadata=api.ObjectMeta(name=f"prewarm-{i}",
                                        namespace="default"),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="img", resources=res)]))
                for i in range(floor)]
            snap = encode_snapshot([node], [], pods, [],
                                   policy=BatchPolicy())
            host = snapshot_to_host_inputs(snap)
        except Exception:
            _log.exception("prewarm boot: synthetic exemplar failed")
            self._prewarm.boot_set([])
            return
        if self._prewarm_exemplar is None:
            self._prewarm_exemplar = (host, BatchPolicy(), False)
        dims = _dims_of(host)
        n_target = _pow2_pad(max(int(nodes_hint), dims["N"]), minimum=1)
        batches = sorted({1, _pow2_pad(max(1, int(batch_hint)),
                                       minimum=1)})
        targets = []
        for p in pow2_ladder(pods_hint, floor=256) or [dims["P"]]:
            for b in batches:
                t = dict(dims)
                t["N"] = n_target
                t["N1"] = n_target + 1
                t["P"] = max(p, dims["P"])
                t["BATCH"] = b
                targets.append(t)
        self._prewarm.boot_set(targets)

    @staticmethod
    def _trace_group(reqs: List[_Req], t0_ns: int, end_ns: int,
                     mesh: bool) -> None:
        """Attach the daemon's per-wave spans (queue wait + batched
        solve) to each requesting wave's trace — the cross-process leg
        of the wave timeline. No-op unless the daemon runs with --trace
        AND the frame carried a v3 trace context."""
        if not tracing.enabled():
            return
        for r in reqs:
            if r.trace is None:
                continue
            tracing.record("solverd.queue", r.t_enq, t0_ns, parent=r.trace)
            tracing.record("solverd.solve", t0_ns, end_ns, parent=r.trace,
                           coalesced=len(reqs), mesh=mesh, pods=r.p,
                           nodes=int(r.inp.cap.shape[0]))

    def _solve_group(self, reqs: List[_Req]) -> None:
        pol, gangs = reqs[0].pol, reqs[0].gangs
        # kernel-vs-mesh-vs-single dispatch (docs/design/solver.md): a
        # single-wave group above the mesh executor's node floor solves
        # from device-resident sharded planes at its EXACT resident shape
        # (no pow-2 node pad, pod planes donated, deltas applied on
        # device); coalesced multi-wave groups and small waves keep the
        # padded jit(vmap) path below, whose pow-2 bucketing exists for
        # exactly those heterogeneous batches.
        me = self._mesh_exec
        if me is not None and len(reqs) == 1 \
                and me.eligible(reqs[0].inp, pol, gangs):
            r = reqs[0]
            t0 = time.perf_counter()
            t0_ns = time.monotonic_ns()
            if r.trace is not None and tracing.enabled():
                # ambient install so MeshExecutor's plane/device sub-spans
                # attach to this wave's trace
                with tracing.span("solverd.mesh", parent=r.trace):
                    chosen, scores = me.solve(r.inp, pol, gangs,
                                              cache_key=r.cache_key,
                                              delta=r.delta)
            else:
                chosen, scores = me.solve(r.inp, pol, gangs,
                                          cache_key=r.cache_key,
                                          delta=r.delta)
            dt = time.perf_counter() - t0
            self._trace_group(reqs, t0_ns, time.monotonic_ns(), mesh=True)
            self.solve_calls += 1
            self.waves_served += 1
            self._m.solves.inc()
            self._m.waves.inc()
            self._m.batch.observe(1)
            self._m.solve_s.observe(dt)
            self._m.requests.inc("ok")
            try:
                with r.send_lock:
                    protocol.send_msg(
                        r.conn, {"ok": True, "coalesced": 1},
                        (np.ascontiguousarray(chosen[:r.p]),
                         np.ascontiguousarray(scores[:r.p])))
            except OSError:
                _log.debug("requester went away before its reply")
            return
        all_dims = [_dims_of(r.inp) for r in reqs]
        target = _target_dims(all_dims)
        padded = [_pad_inputs(r.inp, target) for r in reqs]
        B = _pow2_pad(len(padded), minimum=1)
        if self._prewarm is not None:
            # kube-slipstream fill trigger: report this group's TRUE
            # occupancy against the bucket it is about to solve in (plus
            # the vmap batch axis), so the next bucket compiles off this
            # loop before growth crosses the boundary
            self._prewarm_exemplar = (reqs[0].inp, pol, gangs)
            actual = {k: max(d[k] for d in all_dims) for k in all_dims[0]}
            actual["BATCH"] = len(reqs)
            bucket = dict(target)
            bucket["BATCH"] = B
            frozen = ("R", "L", "A")
            if B >= _pow2_pad(self.max_batch, minimum=1):
                frozen += ("BATCH",)  # gather never fills past max_batch
            self._prewarm.observe(actual, bucket, frozen=frozen)
        # replicate the first wave to fill the pow-2 batch bucket: bounded
        # wasted lanes instead of one compile per occupancy
        padded += [padded[0]] * (B - len(padded))
        stacked = type(padded[0])(*(np.stack(cols)
                                    for cols in zip(*padded)))
        t0 = time.perf_counter()
        t0_ns = time.monotonic_ns()
        chosen, scores = self._device_solve(stacked, pol, gangs)
        dt = time.perf_counter() - t0
        self._trace_group(reqs, t0_ns, time.monotonic_ns(), mesh=False)
        self.solve_calls += 1
        self.waves_served += len(reqs)
        self._m.solves.inc()
        self._m.waves.inc(by=len(reqs))
        self._m.batch.observe(len(reqs))
        self._m.solve_s.observe(dt)
        for i, r in enumerate(reqs):
            self._m.requests.inc("ok")
            try:
                with r.send_lock:
                    protocol.send_msg(
                        r.conn,
                        {"ok": True, "coalesced": len(reqs)},
                        (np.ascontiguousarray(chosen[i, :r.p]),
                         np.ascontiguousarray(scores[i, :r.p])))
            except OSError:
                _log.debug("requester went away before its reply")
