"""kube-solverd wire protocol — versioned solve request/response frames.

The framing style is the one the kube-store process proved out
(storage/remote.py): length-prefixed frames over a local TCP socket. A
store frame is small JSON, but a solve request carries a wave's encoded
tensors (~27 numpy arrays, up to a few MB at full shape), so the payload
here is a JSON *header* followed by the arrays' raw bytes:

    frame   := u32 total_len | u32 header_len | header_json | array_bytes
    header  := {"v": 1, "op": ..., ...request/response fields...,
                "arrays": [[dtype_str, shape, nbytes], ...]}

Array bytes are concatenated in header order, C-contiguous, no alignment
padding (the receiver copies into fresh numpy buffers anyway). dtype
strings are numpy's (``"int32"``, ``"uint32"``, ``"bool"``, ...).

Ops:

- ``solve``: header carries ``policy`` (a BatchPolicy in wire form),
  ``gangs`` (bool), and ``fp`` — the solver-config fingerprint binding the
  request to (protocol version, policy, gangs). The arrays are the
  SolverInputs fields in ``SolverInputs._fields`` order, host-side
  (numpy), exactly what ``batch_solver.snapshot_to_host_inputs`` emits.
  Response: ``{"ok": true, "coalesced": k}`` + two arrays
  (chosen[P] i32, scores[P] i32), or ``{"busy": true}`` (queue full —
  the 429 analog; the client falls back or retries later), or
  ``{"err": ..., "msg": ...}``.
- ``ping``: health/handshake. Response carries the daemon's protocol
  version and solve statistics, so a client can refuse a version-skewed
  daemon before shipping any tensors.

**Delta frames (v2).** A full-shape wave ships several MB of planes, but
between consecutive waves of one scheduler worker only O(changed) node
rows differ — the incremental encoder keeps the node-side planes
resident, so the wire should too. A v2 ``solve`` may carry:

- ``cache``: ``{"wid": worker-id, "bucket": shape-bucket, "epoch": n}`` —
  the daemon keys a resident plane cache by (wid, bucket); ``bucket``
  digests every field's (dtype, shape), so any vocabulary growth or
  dtype flip lands in a fresh bucket and forces a full frame;
- ``planes``: one entry per SolverInputs field, in field order:
  ``"F"`` (full array follows), ``"S"`` (unchanged — daemon reuses its
  cached plane, nothing on the wire), or ``["D", k]`` (row delta: a
  ``[k] i32`` row-index array followed by a ``[k, ...]`` values array).
  Only ``DELTA_FIELDS`` (the node/group/zone resident planes) may be
  ``"S"``/``"D"``; pod-axis planes are always ``"F"``.

Epoch rule: a full frame (all-``F`` + ``cache``) installs the cache entry
at epoch ``epoch+1``; each applied delta requires the entry to be at the
request's ``epoch`` exactly and advances it by one. Any mismatch — no
entry (daemon restarted, LRU-evicted), epoch skew (a lost reply
desynced the pair), row out of range — is answered with
``{"resync": reason}`` WITHOUT solving; the client re-sends the wave as
a full frame. Solves are bit-identical by construction: the daemon
reconstructs byte-identical arrays or refuses.

A v1 client (no ``cache``/``planes``) against a v2 daemon keeps working:
the daemon treats its frames as full-plane requests and fingerprints
them with the request's own version. The fingerprint exists so the
daemon can group compatible requests for coalescing (same compiled
program family) and reject requests from a scheduler built against an
incompatible protocol revision without decoding the tensor payload.

**Trace context (v3).** A v3 ``solve`` header may carry
``"trace": [trace_id, parent_span_id]`` — the kube-trace span context
(util/tracing.py) of the scheduler wave that shipped the frame. The
daemon attaches its queue-wait and solve spans to that trace so the
merged per-run artifact shows the wave's full causal path across the
process boundary. The field is OPTIONAL and advisory: it never affects
solving, is ignored by tracing-disabled daemons, and v1/v2 clients that
omit it are served exactly as before (untraced). It deliberately rides
the JSON header, not the fingerprint — two waves differing only in
trace context must still coalesce into one compiled program family.
"""

from __future__ import annotations

import hashlib
import json
import socket
import struct
from dataclasses import asdict
from typing import List, Optional, Tuple

import numpy as np

from kubernetes_tpu.models.policy import BatchPolicy

__all__ = ["PROTOCOL_VERSION", "MIN_PROTOCOL_VERSION", "MAX_FRAME",
           "DELTA_FIELDS", "SolverProtocolError",
           "send_msg", "recv_msg", "policy_to_wire", "policy_from_wire",
           "solver_fingerprint", "shape_bucket", "parse_trace"]

PROTOCOL_VERSION = 3      # v3: optional trace context on solve frames
MIN_PROTOCOL_VERSION = 1  # v1 full-plane / v2 delta clients still served


def parse_trace(header: dict):
    """The solve header's optional trace context -> (trace_id,
    parent_span_id) tuple, or None when absent/malformed. Tolerant by
    design: a bad trace field must never fail a solve."""
    tr = header.get("trace")
    if (isinstance(tr, (list, tuple)) and len(tr) == 2
            and all(isinstance(x, str) and 0 < len(x) <= 64 for x in tr)):
        return (tr[0], tr[1])
    return None

# SolverInputs fields the daemon may cache between waves and the client
# may ship as row deltas: everything keyed on the node/group/zone axes
# (resident in models/incremental.IncrementalEncoder). Pod-axis planes
# are new every wave and always ship full.
DELTA_FIELDS = frozenset((
    "cap", "advertises", "fit_used", "fit_exceeded", "score_used",
    "node_ports", "node_sel", "node_pds", "node_extra_ok",
    "group_counts", "score_static", "node_aff_vals",
    "zone_idx", "zone_counts0",
    # kube-preempt: the evictable-band planes are node-resident like every
    # other plane above (band_prio rides along — [B] rows delta like any
    # axis-0 plane); pod_prio/pod_can_preempt are pod-axis, always full
    "evict_cap", "evict_cnt", "band_prio",
))

# A full-shape wave (10k pods x 10k nodes) encodes to a few hundred MB in
# the worst padded case; 1 GiB bounds a corrupt length word, not real use.
MAX_FRAME = 1 << 30


class SolverProtocolError(Exception):
    """Malformed frame / version skew / connection failure mid-frame."""


# -- policy (de)serialization ------------------------------------------------
# BatchPolicy is a frozen dataclass of ints/bools/nested tuples; JSON turns
# tuples into lists, so the decoder re-tuples the nested fields to restore
# hashability (the policy is a jit-static argument on the daemon side).

def policy_to_wire(pol: BatchPolicy) -> dict:
    return asdict(pol)


def policy_from_wire(d: dict) -> BatchPolicy:
    return BatchPolicy(
        use_ports=bool(d["use_ports"]),
        use_resources=bool(d["use_resources"]),
        use_disk=bool(d["use_disk"]),
        use_selector=bool(d["use_selector"]),
        use_host=bool(d["use_host"]),
        label_presence=tuple((tuple(labels), bool(presence))
                             for labels, presence in d["label_presence"]),
        affinity_labels=tuple(d["affinity_labels"]),
        w_lr=int(d["w_lr"]),
        w_spread=int(d["w_spread"]),
        w_equal=int(d["w_equal"]),
        label_prefs=tuple((label, bool(presence), int(w))
                          for label, presence, w in d["label_prefs"]),
        anti_affinity=tuple((label, int(w))
                            for label, w in d["anti_affinity"]),
        all_infeasible=bool(d["all_infeasible"]),
    )


def solver_fingerprint(pol: BatchPolicy, gangs: bool,
                       version: int = PROTOCOL_VERSION) -> str:
    """Canonical digest of (protocol version, policy, gangs) — the compiled
    program family a request belongs to. Requests sharing a fingerprint may
    be coalesced into one batched solve. ``version`` is the REQUEST's
    protocol version: a v2 daemon verifying a v1 frame must derive the
    digest the v1 client computed."""
    blob = json.dumps({"v": int(version), "policy": policy_to_wire(pol),
                       "gangs": bool(gangs)}, sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def shape_bucket(arrays) -> str:
    """Digest of every array's (dtype, shape) in order — the delta cache
    key's shape component. Any growth of a vocabulary axis, a pow-2 pod
    bucket change, or an i32/i64 dtype flip produces a new bucket, so a
    delta can never be applied across incompatible layouts."""
    blob = ";".join(f"{a.dtype.str}{tuple(a.shape)}" for a in arrays)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


# -- framing -----------------------------------------------------------------

def _recv_exact_into(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` from the socket; False on EOF before it filled."""
    while view:
        n = sock.recv_into(view)
        if n == 0:
            return False
        view = view[n:]
    return True


def send_msg(sock: socket.socket, header: dict,
             arrays: Tuple[np.ndarray, ...] = ()) -> None:
    """Serialize and send one frame. ``header["arrays"]`` is filled in from
    ``arrays`` (dtype/shape/nbytes per array, in order). Array payloads go
    out as zero-copy memoryviews — a full-shape wave is hundreds of MB,
    so tobytes()+join would add two transient full-payload copies to the
    hot path."""
    meta = []
    views: List[memoryview] = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        meta.append([str(a.dtype), list(a.shape), a.nbytes])
        if a.nbytes:  # zero-size planes carry no payload (and can't cast)
            views.append(memoryview(a).cast("B"))
    header = dict(header, arrays=meta)
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body_len = 4 + len(hjson) + sum(v.nbytes for v in views)
    if body_len > MAX_FRAME:
        raise SolverProtocolError(f"frame too large: {body_len} bytes")
    sock.sendall(struct.pack(">II", body_len, len(hjson)) + hjson)
    for v in views:
        sock.sendall(v)


def recv_msg(sock: socket.socket
             ) -> Optional[Tuple[dict, List[np.ndarray]]]:
    """Receive one frame -> (header, arrays), or None on clean EOF.
    Arrays are writable zero-copy views over ONE receive buffer (a
    bytearray is a writable buffer, so np.frombuffer over it is too);
    the buffer lives as long as any returned array does."""
    head = bytearray(4)
    if not _recv_exact_into(sock, memoryview(head)):
        return None
    (total,) = struct.unpack(">I", head)
    if total > MAX_FRAME or total < 4:
        raise SolverProtocolError(f"bad frame length {total}")
    body = bytearray(total)
    if not _recv_exact_into(sock, memoryview(body)):
        raise SolverProtocolError("connection closed mid-frame")
    (hlen,) = struct.unpack(">I", body[:4])
    if hlen > total - 4:
        raise SolverProtocolError(f"bad header length {hlen}")
    try:
        header = json.loads(bytes(body[4:4 + hlen]))
    except ValueError as e:
        raise SolverProtocolError(f"bad header json: {e}")
    arrays: List[np.ndarray] = []
    off = 4 + hlen
    for dtype_str, shape, nbytes in header.get("arrays", ()):
        if off + nbytes > total:
            raise SolverProtocolError("truncated array payload")
        dt = np.dtype(dtype_str)
        arr = np.frombuffer(body, dtype=dt, count=nbytes // dt.itemsize,
                            offset=off).reshape(shape)
        arrays.append(arr)
        off += nbytes
    return header, arrays
