"""kube-slipstream ahead-of-time shape-bucket prewarm.

Pow-2 bucketing (models/incremental.py vocab caps, solver/service.py
``_target_dims``) bounds how MANY programs the solver compiles, but not
WHEN: the first wave to cross a bucket boundary pays the XLA compile
inline — seconds of stall parked squarely on the wave loop, which is why
the r18 planet record ran 70/s instead of its structural rate and why the
churn harness needed a ``max(180, nodes * 0.05)`` warmup heuristic.

The PrewarmController moves that compile OFF the wave loop:

- **fill trigger** — every wave reports its true (unpadded) axis
  occupancy against the pow-2 bucket it ran in (``observe``); when an
  axis reaches ``fill_fraction`` of its bucket, the NEXT bucket's target
  shape is queued and a background thread compiles it through the exact
  entry point live waves use (``models/batch_solver.warm_compile`` in
  process, the daemon's batched vmap program in solverd). By the time
  growth actually crosses the boundary, the program is already in the
  jit cache — the bucket swap is a dict hit, not a compile;
- **boot set** — ``boot_set(targets)`` seeds the queue with the bucket
  set implied by the known cluster size (``--prewarm`` on cmd/solverd
  and cmd/scheduler) and the ``compile_prewarm_ready`` gauge flips to 1
  when it drains, which is the readiness signal hack/churn_mp.py gates
  its load window on (replacing the node-count heuristic, kept only as
  a hard timeout).

The swap is double-buffered by construction: a prewarm compile inserts
into the SAME program cache (jax's jit cache + util/warmstart.py's
persistent store) that live dispatch reads, and the insertion happens
only when the executable is complete — a live wave arriving mid-compile
never observes a half-built program, it either misses (and compiles as
today) or hits the finished entry. Compiled work is read back to host
before being discarded so the backend cannot elide it.

Thread model: ``observe``/``submit`` are cheap and thread-safe (called
from wave/solve threads); one daemon thread runs the compiles serially
so prewarm never competes with itself for the device.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, Iterable, Optional, Sequence

from kubernetes_tpu.util import metrics

__all__ = ["PrewarmController", "pow2_ladder"]

_log = logging.getLogger("kubernetes_tpu.solver.prewarm")


def pow2_ladder(top: int, floor: int = 64) -> list:
    """Descending pow-2 bucket ladder from the bucket containing ``top``
    down to ``floor`` — the boot set for an axis whose live value ramps
    up through every bucket (the churn harness's pod axis)."""
    if top <= 0:
        return []
    b = 1
    while b < top:
        b <<= 1
    out = []
    while b >= max(1, floor):
        out.append(b)
        b >>= 1
    return out


class PrewarmController:
    """Queue + background compile thread over opaque shape targets.

    ``compile_fn(target)`` receives one target dict (axis letter ->
    length, e.g. ``{"N": 65536, "P": 1024, ...}``; solverd adds a
    ``"BATCH"`` key for the vmap batch axis) and must compile AND read
    back the corresponding program. Targets are deduplicated for the
    controller's lifetime — a bucket is compiled at most once.
    """

    def __init__(self, compile_fn, *, fill_fraction: float = 0.75,
                 name: str = "prewarm"):
        if not (0.0 < fill_fraction <= 1.0):
            raise ValueError(f"fill_fraction {fill_fraction} not in (0, 1]")
        self._compile = compile_fn
        self.fill_fraction = fill_fraction
        self.name = name
        self._sx = metrics.slipstream_metrics()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._queue: deque = deque()  # ktpu-vet: ok thread-discipline — lifetime-deduplicated (each pow-2 bucket queued at most once, _done/_queued guard), so the queue is bounded by the distinct-bucket count
        self._queued: set = set()      # keys queued or compiling
        self._done: set = set()        # keys compiled (or failed — no retry)
        self._boot: set = set()        # boot keys not yet compiled
        self._boot_armed = False
        self._thread: Optional[threading.Thread] = None
        # plain counters for tests/introspection (metrics are the
        # cross-process surface)
        self.compiled = 0
        self.errors = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "PrewarmController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=f"{self.name}-compile")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    # -- intake -------------------------------------------------------------
    @staticmethod
    def _key(target: Dict[str, int]) -> tuple:
        return tuple(sorted(target.items()))

    def submit(self, target: Dict[str, int], boot: bool = False) -> bool:
        """Queue one target unless it was already queued or compiled.
        Returns True when newly queued."""
        key = self._key(target)
        with self._lock:
            if key in self._done:
                return False
            if boot:
                self._boot.add(key)
            if key in self._queued:
                self._refresh_gauges()
                return False
            self._queued.add(key)
            self._queue.append(dict(target))
            self._refresh_gauges()
        self._wake.set()
        return True

    def boot_set(self, targets: Iterable[Dict[str, int]]) -> int:
        """Arm the readiness gate over ``targets`` (the --prewarm boot
        set). ``compile_prewarm_ready`` goes 0 until every one compiled;
        an empty/already-compiled set reports ready immediately."""
        n = 0
        with self._lock:
            self._boot_armed = True
        for t in targets:
            if self.submit(t, boot=True):
                n += 1
        with self._lock:
            self._refresh_gauges()
        return n

    def observe(self, actual: Dict[str, int], bucket: Dict[str, int],
                frozen: Sequence[str] = ()) -> None:
        """Hot-path fill check: for every axis whose true occupancy
        ``actual[k]`` reached ``fill_fraction`` of its current bucket,
        queue the single-axis-advanced next bucket. Axes absent from
        ``actual`` or listed in ``frozen`` never trigger."""
        f = self.fill_fraction
        for k, cur in bucket.items():
            if k in frozen or k == "N1":
                continue
            cur = int(cur)
            a = actual.get(k)
            if cur <= 0 or a is None or int(a) < f * cur:
                continue
            nxt = {ax: int(v) for ax, v in bucket.items()}
            nxt[k] = cur * 2
            if "N1" in nxt:
                nxt["N1"] = nxt["N"] + 1
            self.submit(nxt)

    # -- state --------------------------------------------------------------
    def ready(self) -> bool:
        with self._lock:
            return self._boot_armed and not self._boot

    def pending(self) -> int:
        with self._lock:
            return len(self._queued)

    def _refresh_gauges(self) -> None:
        # caller holds self._lock
        self._sx.prewarm_pending.set(len(self._queued))
        if self._boot_armed:
            self._sx.prewarm_ready.set(0 if self._boot else 1)

    # -- compile thread -----------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                target = self._queue.popleft() if self._queue else None
            if target is None:
                self._wake.wait(0.25)
                self._wake.clear()
                continue
            t0 = time.perf_counter()
            ok = True
            try:
                self._compile(target)
            except Exception:  # noqa: BLE001 — a failed prewarm must
                # never take the thread down; the live wave path simply
                # compiles on demand as it would have without prewarm
                ok = False
                self.errors += 1
                _log.exception("%s: bucket compile failed for %s",
                               self.name, target)
            dt = time.perf_counter() - t0
            key = self._key(target)
            with self._lock:
                self._queued.discard(key)
                self._done.add(key)  # no retry loop either way
                self._boot.discard(key)
                self._refresh_gauges()
            if ok:
                self.compiled += 1
                self._sx.prewarm_total.inc()
                self._sx.prewarm_s.observe(dt)
                _log.info("%s: compiled bucket %s in %.2fs", self.name,
                          {k: v for k, v in sorted(target.items())}, dt)
