"""RemoteSolver — a kube-solverd client with graceful in-process fallback.

Drop-in for the in-process solve path: ``RemoteSolver.solve(snap)``
returns exactly what ``models.batch_solver.solve(snap)`` returns (chosen
node indices + winning scores, gang post-pass applied), so the
BatchScheduler's wave loop cannot tell which solver ran — except by the
wave latency. Recovery discipline mirrors the store client
(storage/remote.RemoteStore): one pooled connection per thread; a failure
the daemon never saw the frame for (refused connect, send error, any
death of a REUSED pooled connection) retries once on a fresh connection,
while a post-send failure on a fresh connection raises — the daemon may
be mid-solve, and re-sending would double its load exactly when it is
slow (see _call).

Degradation ladder, worst case first:

- daemon replies BUSY (bounded queue full): solve this wave in-process,
  do NOT mark the daemon unhealthy — backpressure is it working as
  designed;
- connection refused / timed out / died twice: solve in-process and mark
  the daemon unhealthy for ``cooldown_s`` so a dead daemon costs one
  connect attempt per cooldown, not per wave;
- protocol/version errors: same as above (a version-skewed daemon will
  never start working mid-run).

With ``fallback=False`` the failures raise instead (tests, and deploys
that would rather crash than silently run N CPU solvers again).
"""

from __future__ import annotations

import socket
import threading
import time
import uuid
from typing import Dict, Optional, Tuple

import numpy as np

from kubernetes_tpu.models.policy import BatchPolicy
from kubernetes_tpu.solver import protocol
from kubernetes_tpu.util import metrics, tracing
from kubernetes_tpu.util.retry import Backoff

__all__ = ["RemoteSolver", "SolverBusy", "SolverUnavailable"]


class _Mirror:
    """Client-side copy of the resident planes the daemon holds for one
    (worker-thread, shape-bucket) cache entry. The arrays are OWNED
    copies: encoder-resident planes can mutate in place between waves, so
    diffing against a reference we also hold by reference would see
    nothing change. ``epoch`` counts applied frames and must stay in
    lockstep with the daemon's entry — any skew surfaces as a resync."""

    __slots__ = ("epoch", "planes")

    def __init__(self, epoch: int, planes: Dict[str, np.ndarray]):
        self.epoch = epoch
        self.planes = planes


class SolverUnavailable(Exception):
    """No healthy kube-solverd behind the configured address."""


class SolverBusy(Exception):
    """The daemon's bounded queue is full (the 429 analog)."""


class RemoteSolver:
    # the reply deadline must clear a COLD solve: the daemon's first wave
    # of a new shape bucket pays an XLA compile (seconds on CPU, tens of
    # seconds over a TPU tunnel), and treating that as a dead connection
    # would re-send the wave and solve it twice
    def __init__(self, address: str, timeout_s: float = 180.0,
                 connect_timeout_s: float = 2.0, fallback: bool = True,
                 cooldown_s: float = 5.0, delta: bool = True):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._timeout_s = timeout_s
        self._connect_timeout_s = connect_timeout_s
        self.fallback = fallback
        self.cooldown_s = cooldown_s
        # delta wire (protocol v2): ship O(changed-rows) plane deltas
        # against a daemon-side resident cache; False pins full frames
        self.delta = delta
        # device mesh for the IN-PROCESS fallback path (the daemon runs
        # its own MeshExecutor); set by the scheduler from its --mesh flag
        self.fallback_mesh = None
        self._wid = uuid.uuid4().hex[:12]
        self._local = threading.local()
        self._lock = threading.Lock()
        self._unhealthy_until = 0.0
        # exponential cooldown: a daemon mid-respawn costs a retry after
        # ~cooldown_s/8, doubling (jittered) to the cooldown_s cap while
        # it stays dead — reconnecting within seconds of a kube-chaos
        # respawn instead of always paying the full fixed cooldown,
        # while a permanently-dead daemon still costs one connect per
        # cap. Reset on the first successful remote wave.
        self._cooldown = Backoff(base=max(0.25, cooldown_s / 8.0),
                                 cap=max(0.25, cooldown_s))
        # visible in tests and the scheduler's /metrics narrative
        self.remote_waves = 0
        self.fallback_waves = 0
        self.busy_waves = 0
        self.delta_waves = 0
        self.full_waves = 0
        self.resync_waves = 0
        self.resync_reasons: Dict[str, int] = {}
        self.delta_bytes_shipped = 0
        self.delta_bytes_full = 0

    # -- plumbing ----------------------------------------------------------
    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self._timeout_s)
        return sock

    def _call(self, header: dict, arrays=()):
        """Request/response on the pooled per-thread connection. Retry-once
        covers failures the daemon never saw the frame for: a refused
        connect, a send error, or any failure on a REUSED pooled
        connection (a daemon restart between waves half-closes the pool;
        the send "succeeds" into the dead socket and the recv gets EOF).
        A failure after a send on a FRESH connection does NOT retry: the
        daemon very likely has the frame and may be solving it, and a
        retry after a merely-slow reply would make it solve the same wave
        twice — exactly when it is most loaded. (Pure solves keep the
        caller's fallback safe either way, just not free.)"""
        last_err: Optional[Exception] = None
        for attempt in (0, 1):
            sock = getattr(self._local, "sock", None)
            reused = sock is not None
            sent = False
            try:
                if sock is None:
                    sock = self._local.sock = self._connect()
                protocol.send_msg(sock, header, arrays)
                sent = True
                resp = protocol.recv_msg(sock)
                if resp is None:
                    raise protocol.SolverProtocolError(
                        "daemon closed the connection mid-call")
                return resp
            except (OSError, protocol.SolverProtocolError) as e:
                last_err = e
                self._local.sock = None
                try:
                    if sock is not None:
                        sock.close()
                except OSError:
                    pass
                if sent and not reused:
                    break
        raise SolverUnavailable(
            f"kube-solverd at {self._addr[0]}:{self._addr[1]} "
            f"unreachable: {last_err}")

    # -- health ------------------------------------------------------------
    def _in_cooldown(self) -> bool:
        with self._lock:
            return time.monotonic() < self._unhealthy_until

    def _mark_unhealthy(self) -> None:
        with self._lock:
            self._unhealthy_until = time.monotonic() + self._cooldown.next()

    def _mark_healthy(self) -> None:
        with self._lock:
            self._unhealthy_until = 0.0
            self._cooldown.reset()

    def ping(self) -> dict:
        """Daemon health + version handshake; raises SolverUnavailable."""
        header, _ = self._call({"op": "ping", "v": protocol.PROTOCOL_VERSION})
        if "err" in header:
            raise SolverUnavailable(header.get("msg", header["err"]))
        if header.get("v") != protocol.PROTOCOL_VERSION:
            raise SolverUnavailable(
                f"daemon protocol v{header.get('v')} != "
                f"client v{protocol.PROTOCOL_VERSION}")
        return header

    # -- the solve seam ----------------------------------------------------
    @staticmethod
    def _parse_solve_reply(resp_header, arrays
                           ) -> Tuple[np.ndarray, np.ndarray]:
        if resp_header.get("busy"):
            raise SolverBusy("kube-solverd queue full")
        if "err" in resp_header:
            raise protocol.SolverProtocolError(
                f"{resp_header['err']}: {resp_header.get('msg', '')}")
        if len(arrays) != 2:
            raise protocol.SolverProtocolError(
                f"solve reply carried {len(arrays)} arrays, expected 2")
        return arrays[0], arrays[1]

    def _mirrors(self) -> Dict[str, _Mirror]:
        m = getattr(self._local, "mirrors", None)
        if m is None:
            m = self._local.mirrors = {}
        return m

    _MAX_MIRRORS = 16  # pow-2 bucketing keeps live shapes well below this

    def _delta_plan(self, host_inputs, mir: _Mirror):
        """Diff the wave's planes against the mirror of what the daemon
        holds -> (wire plane list, arrays to ship, mirror commit list).
        The row compare is a vectorized memcmp over the resident planes
        (~MBs/ms); the bytes SHIPPED are O(changed rows). A plane whose
        delta would not beat re-sending it ships full."""
        plan: list = []
        arrays: list = []
        commits: list = []
        for name, cur in zip(host_inputs._fields, host_inputs):
            cur = np.ascontiguousarray(cur)
            if name not in protocol.DELTA_FIELDS:
                plan.append("F")
                arrays.append(cur)
                continue
            prev = mir.planes[name]
            diff = prev != cur  # same shape/dtype: the bucket key pins them
            changed = diff.any(axis=tuple(range(1, diff.ndim))) \
                if diff.ndim > 1 else diff
            rows = np.nonzero(changed)[0].astype(np.int32)
            if rows.size == 0:
                plan.append("S")
                continue
            row_nbytes = cur.nbytes // max(1, cur.shape[0])
            if rows.size * (row_nbytes + 4) >= cur.nbytes:
                plan.append("F")
                arrays.append(cur)
                commits.append((name, None, cur))
            else:
                vals = np.ascontiguousarray(cur[rows])
                plan.append(["D", int(rows.size)])
                arrays.extend((rows, vals))
                commits.append((name, rows, vals))
        return plan, tuple(arrays), commits

    def solve_remote(self, host_inputs, pol: BatchPolicy, gangs: bool
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Ship one wave's host-side SolverInputs; returns (chosen, scores)
        for the shipped pod axis. Raises SolverBusy / SolverUnavailable /
        SolverProtocolError — no fallback at this layer.

        With ``delta`` on (default), consecutive waves of one thread ship
        O(changed-rows) plane deltas against the daemon's resident cache;
        a ``resync`` answer (daemon restarted, entry evicted, epoch skew)
        degrades that one wave to a full frame and re-establishes the
        pair. The mirror only advances after a successful solve reply, so
        BUSY bounces and daemon-side failures can never desync it
        silently — at worst the next delta resyncs."""
        sx = metrics.slipstream_metrics()
        base = {
            "op": "solve", "v": protocol.PROTOCOL_VERSION,
            "fp": protocol.solver_fingerprint(pol, gangs),
            "policy": protocol.policy_to_wire(pol),
            "gangs": bool(gangs),
            # kube-slipstream: piggyback this scheduler's encoder resync
            # counters so solverd's /metrics mirrors cluster resync health
            "enc": [int(sx.resync_replay.total()),
                    int(sx.resync_full.total())],
        }
        # v3 trace context: the wave's ambient span rides the header so
        # the daemon's queue/solve spans join this trace (advisory only
        # — see protocol.parse_trace; absent when tracing is off)
        ctx = tracing.current()
        if ctx is not None:
            base["trace"] = [ctx[0], ctx[1]]
        if not self.delta:
            resp_header, arrays = self._call(base, tuple(host_inputs))
            return self._parse_solve_reply(resp_header, arrays)
        bucket = protocol.shape_bucket(host_inputs)
        wid = f"{self._wid}.{threading.get_ident()}"
        mirrors = self._mirrors()
        mir = mirrors.get(bucket)
        if mir is not None:
            plan, arrays, commits = self._delta_plan(host_inputs, mir)
            header = dict(base, cache={"wid": wid, "bucket": bucket,
                                       "epoch": mir.epoch}, planes=plan)
            resp_header, rarrs = self._call(header, arrays)
            if not resp_header.get("resync"):
                out = self._parse_solve_reply(resp_header, rarrs)
                mir.epoch += 1
                for name, rows, vals in commits:
                    if rows is None:
                        mir.planes[name] = np.array(vals, copy=True)
                    else:
                        mir.planes[name][rows] = vals
                self.delta_waves += 1
                self.delta_bytes_shipped += sum(a.nbytes for a in arrays)
                self.delta_bytes_full += sum(
                    a.nbytes for a in host_inputs)
                return out
            self.resync_waves += 1
            reason = str(resp_header.get("resync"))
            self.resync_reasons[reason] = (
                self.resync_reasons.get(reason, 0) + 1)
            mirrors.pop(bucket, None)
        # full frame: establish (or resync) the daemon's cache entry
        header = dict(base,
                      cache={"wid": wid, "bucket": bucket, "epoch": 0},
                      planes=["F"] * len(host_inputs))
        resp_header, rarrs = self._call(header, tuple(host_inputs))
        if resp_header.get("resync"):
            raise protocol.SolverProtocolError(
                f"daemon demanded resync of a full frame: "
                f"{resp_header['resync']!r}")
        out = self._parse_solve_reply(resp_header, rarrs)
        self.full_waves += 1
        if len(mirrors) >= self._MAX_MIRRORS:
            mirrors.pop(next(iter(mirrors)))
        mirrors[bucket] = _Mirror(1, {
            name: np.array(arr, copy=True)
            for name, arr in zip(host_inputs._fields, host_inputs)
            if name in protocol.DELTA_FIELDS})
        return out

    def solve(self, snap) -> Tuple[np.ndarray, np.ndarray]:
        """The batch_solver.solve twin over the wire: encode-side inputs
        from ``snap``, remote solve, gang post-pass — falling back to the
        full in-process path whenever the daemon can't take the wave."""
        from kubernetes_tpu.models import gang
        from kubernetes_tpu.models.batch_solver import (
            NEG,
            snapshot_to_host_inputs,
            solve as solve_in_process,
        )

        if self._in_cooldown():
            if not self.fallback:
                raise SolverUnavailable("kube-solverd in unhealthy cooldown")
            self.fallback_waves += 1
            return solve_in_process(snap, mesh=self.fallback_mesh)
        pol = snap.policy or BatchPolicy()
        gangs = snap.has_gangs
        host = snapshot_to_host_inputs(snap)
        try:
            chosen, scores = self.solve_remote(host, pol, gangs)
        except SolverBusy:
            # BUSY is the designed overload response: reuse the encode the
            # wave already paid instead of re-deriving it while saturated
            self.busy_waves += 1
            if not self.fallback:
                raise
            return solve_in_process(snap, host=host,
                                    mesh=self.fallback_mesh)
        except (SolverUnavailable, protocol.SolverProtocolError):
            self._mark_unhealthy()
            if not self.fallback:
                raise
            self.fallback_waves += 1
            return solve_in_process(snap, host=host,
                                    mesh=self.fallback_mesh)
        self.remote_waves += 1
        self._mark_healthy()  # the daemon answered: cooldown resets
        if gangs:
            chosen = gang.apply_all_or_nothing(snap.pod_rid, chosen)
            scores = np.where(chosen < 0, np.int32(NEG), scores)
        return chosen, scores
