"""kube-fairshed: flow-classified priority & fairness admission.

The r11-r14 records pin the overload failure mode: offered ~994/s
against ~490/s sustained turns into 37 s of *invisible* e2e backlog,
and under that pressure the control plane sheds blindly — the only 429
in the tree was the read-only port's token bucket, no client honored
Retry-After, and the scheduler's own reflector traffic queued behind
feeder create floods on the same GIL. This module is the API
priority-and-fairness layer (ref: the successor codebases' APF,
KEP-1040, borrowed shape; "Priority Matters", PAPERS.md, for the
band idea): every request is classified into a FLOW by
credential/user-agent/path, each flow gets an isolated max-inflight
budget and a bounded FIFO with a queue-wait deadline, and excess is
answered ``429 + Retry-After`` computed from the flow's MEASURED drain
rate — never a constant.

Flows (docs/design/apiserver-hotpath.md has the full table):

- ``system`` — the control plane's own traffic: scheduler binds
  (``bindings`` / ``bindings:batch``), component reflector list/watch
  (user-agent ``kube-scheduler``/``kubelet``/``kube-controller-manager``),
  and the unversioned observability endpoints (healthz, metrics,
  debug, version, validate). Structurally isolated: a system request
  only ever waits on other system requests — it is NEVER queued behind
  a lower band, which is the starvation-freedom invariant
  (``fairshed_system_shed_total`` must stay 0; the
  ``system_flow_shed_zero`` SLO rule watches it live).
- ``workload`` — user workload mutations: pod/resource writes from
  non-system clients (the churn feeders). The optional BACKLOG
  GOVERNOR lives here: when ``backlog_limit`` is set, pod creates past
  ``created - bound >= backlog_limit`` shed with a Retry-After derived
  from the measured bind drain rate, so the created-but-unbound queue
  — the 37 s invisible backlog — becomes a bounded, disclosed number.
- ``best-effort`` — observers, kubectl reads, event posts: the first
  band to shed, the last to matter.

Deterministic twins: the in-process seams
(``util/chaos.delay_if_armed("apiserver.dispatch.<flow>")`` in the
HTTP dispatch path) let tier-1 hold a band's inflight slots occupied
for an exact duration and prove system-flow starvation-freedom without
a live multi-process stack (tests/test_fairshed.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence, Tuple

from kubernetes_tpu.util import metrics as metrics_pkg

__all__ = ["SYSTEM", "WORKLOAD", "BEST_EFFORT", "FLOWS", "FlowConfig",
           "Shed", "FairShed", "classify", "route_info"]

SYSTEM = "system"
WORKLOAD = "workload"
BEST_EFFORT = "best-effort"
FLOWS = (SYSTEM, WORKLOAD, BEST_EFFORT)

# user-agent prefixes whose traffic IS the control plane: their
# reflector list/watches and status writes ride the system band
_SYSTEM_COMPONENTS = ("kube-scheduler", "kubelet", "kube-controller-manager",
                      "kube-proxy")
# unversioned endpoints that must survive overload: health probing and
# the observability pull paths (flightrec /debug/vars, /metrics, trace
# drains) are exactly what diagnoses a gray-failing server
_SYSTEM_HEADS = ("healthz", "version", "metrics", "validate", "debug")

_WRITE_METHODS = ("POST", "PUT", "PATCH", "DELETE")


def route_info(parts: Sequence[str]) -> Tuple[str, str, str]:
    """``(head, resource, subresource)`` from split path parts, by the
    same normalization the dispatcher applies (namespace scoping, the
    ``watch`` prefix, the ``bindings:batch`` verb suffix) — but without
    touching the registry: classification must stay O(path)."""
    head = parts[0] if parts else ""
    if head != "api" or len(parts) < 3:
        return head, "", ""
    rest = [("bindings" if seg == "bindings:batch" else seg)
            for seg in parts[2:]]
    if rest and rest[0] == "watch":
        rest = rest[1:]
    if rest and rest[0] == "namespaces" and len(rest) >= 3:
        rest = rest[2:]
    resource = rest[0] if rest else ""
    subresource = rest[2] if len(rest) > 2 else ""
    return head, resource, subresource


def classify(method: str, parts: Sequence[str],
             user_agent: Optional[str]) -> str:
    """Flow of one request, by path/credential/user-agent. Order:
    observability heads and the bind path are system no matter who
    asks; events are best-effort no matter who posts (diagnostics,
    not state — the async recorder already treats them as sheddable);
    component user-agents are system; remaining writes are workload;
    remaining reads are best-effort."""
    head, resource, subresource = route_info(parts)
    if head in _SYSTEM_HEADS:
        return SYSTEM
    if resource == "bindings" or subresource == "binding":
        return SYSTEM
    if resource == "events":
        return BEST_EFFORT
    ua = (user_agent or "").partition("/")[0]
    if ua in _SYSTEM_COMPONENTS:
        return SYSTEM
    if method in _WRITE_METHODS:
        return WORKLOAD
    return BEST_EFFORT


class FlowConfig:
    """One flow's budget: concurrent dispatches, queued waiters past
    that, and how long a waiter may park before it sheds."""

    __slots__ = ("max_inflight", "queue_limit", "queue_deadline_s")

    def __init__(self, max_inflight: int, queue_limit: int,
                 queue_deadline_s: float):
        assert max_inflight >= 1 and queue_limit >= 0
        self.max_inflight = max_inflight
        self.queue_limit = queue_limit
        self.queue_deadline_s = queue_deadline_s


# Defaults sized for the churn topology: the scheduler holds a handful
# of reflector streams + one commit leg (system), each feeder is one
# pipelined connection = one handler thread (workload), observers and
# kubectl are occasional (best-effort). Budgets are per PROCESS — an
# SO_REUSEPORT worker fleet multiplies them.
DEFAULT_FLOWS: Dict[str, FlowConfig] = {
    SYSTEM: FlowConfig(max_inflight=32, queue_limit=256,
                       queue_deadline_s=5.0),
    WORKLOAD: FlowConfig(max_inflight=16, queue_limit=128,
                         queue_deadline_s=1.0),
    BEST_EFFORT: FlowConfig(max_inflight=8, queue_limit=64,
                            queue_deadline_s=1.0),
}


class Shed(Exception):
    """Admission refused this request: the HTTP layer answers
    ``429 + Retry-After: <ceil(retry_after_s)>`` with the hint also in
    the Status's ``details.retryAfterSeconds`` so JSON clients see it."""

    def __init__(self, flow: str, reason: str, retry_after_s: float):
        super().__init__(f"{flow} flow shed ({reason}); "
                         f"retry after {retry_after_s:.1f}s")
        self.flow = flow
        self.reason = reason
        self.retry_after_s = retry_after_s


class _Waiter:
    __slots__ = ("event", "admitted", "t_enq")

    def __init__(self, t_enq: float):
        self.event = threading.Event()
        self.admitted = False
        self.t_enq = t_enq


class _Ticket:
    """One admitted request's slot; release is idempotent (the watch
    handler releases EARLY, at stream start, so a long-lived stream
    never pins an inflight slot; the route's finally releases again)."""

    __slots__ = ("_shed", "flow", "_released")

    def __init__(self, shed: "FairShed", flow: str):
        self._shed = shed
        self.flow = flow
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._shed._release(self.flow)

    def __enter__(self) -> "_Ticket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# drain-rate measurement window: completions older than this no longer
# shape Retry-After hints (a stale burst must not promise a fast drain)
_DRAIN_WINDOW_S = 10.0
_DRAIN_SAMPLES = 2048
# Retry-After clamp: at least 1 s (an HTTP header carries whole
# seconds; 0 would be the constant-"1" non-answer this layer replaces),
# at most 30 s (past that the client should re-plan, not park)
_HINT_MIN_S = 1.0
_HINT_MAX_S = 30.0
_HINT_FALLBACK_S = 2.0   # no drain measured yet (cold server)


class FairShed:
    """Per-flow admission: isolated inflight budgets + bounded FIFO
    queues + measured-drain Retry-After, plus the optional workload
    backlog governor. One instance per APIServer; thread-safe."""

    def __init__(self, flows: Optional[Dict[str, FlowConfig]] = None,
                 backlog_limit: int = 0,
                 clock=time.monotonic, ledger=None):
        self._clock = clock
        self._lock = threading.Lock()
        self.flows: Dict[str, FlowConfig] = dict(DEFAULT_FLOWS)
        if flows:
            self.flows.update(flows)
        self._inflight: Dict[str, int] = {f: 0 for f in self.flows}
        self._queues: Dict[str, deque] = {
            # length is checked against queue_limit before append, so
            # maxlen (the thread-discipline bound) never silently evicts
            f: deque(maxlen=max(1, cfg.queue_limit))
            for f, cfg in self.flows.items()}
        # per-flow completion timestamps -> measured drain rate
        self._done: Dict[str, deque] = {
            f: deque(maxlen=_DRAIN_SAMPLES) for f in self.flows}
        # the workload backlog governor: pods created minus pods bound,
        # maintained by the write paths (note_pod_created /
        # note_pods_bound / note_pod_deleted). A single worker's local
        # counters are exact when that worker serves both creates and
        # binds; an SO_REUSEPORT fleet passes ``ledger`` (a
        # share.SharedLedger) — the cross-worker drain feed — so the
        # governor and the measured Retry-After hints stay exact at
        # ``--apiservers N`` (docs/design/apiserver-hotpath.md
        # §cross-worker).
        self.backlog_limit = int(backlog_limit)
        self._created = 0
        self._bound = 0
        self._bind_done: deque = deque(maxlen=_DRAIN_SAMPLES)
        self._ledger = ledger
        self._mx = metrics_pkg.fairshed_metrics()
        self._lmx = metrics_pkg.fairshed_ledger_metrics() \
            if ledger is not None else None
        if self._lmx is not None:
            self._lmx.workers.set(ledger.seg.nworkers)

    # -- accounting seams (the HTTP write paths call these) ---------------

    def note_pod_created(self) -> None:
        if self._ledger is not None:
            self._ledger.note_created()
            self._lmx.creates.inc()
        with self._lock:
            self._created += 1
            self._mx.backlog.set(self._backlog_locked())

    def note_pods_bound(self, n: int) -> None:
        if n <= 0:
            return
        if self._ledger is not None:
            self._ledger.note_bound(n)
            self._lmx.binds.inc(by=n)
        now = self._clock()
        with self._lock:
            self._bound += n
            for _ in range(min(n, _DRAIN_SAMPLES)):
                self._bind_done.append(now)
            self._mx.backlog.set(self._backlog_locked())

    def note_pod_deleted(self) -> None:
        """A deleted pod leaves the ledger. If it was still pending the
        decrement is exact; if it was bound this UNDER-counts the
        backlog (sheds later than truth — the availability-safe
        direction) instead of wedging a long-lived server at a phantom
        ceiling."""
        if self._ledger is not None:
            self._ledger.note_deleted()
            self._lmx.deletes.inc()
        with self._lock:
            self._created = max(self._bound, self._created - 1)
            self._mx.backlog.set(self._backlog_locked())

    def _backlog_locked(self) -> int:
        if self._ledger is not None:
            depth = self._ledger.backlog()
            self._lmx.backlog.set(depth)
            return depth
        return max(0, self._created - self._bound)

    @property
    def backlog(self) -> int:
        with self._lock:
            return self._backlog_locked()

    # -- drain rates ------------------------------------------------------

    @staticmethod
    def _rate(done: deque, now: float) -> float:
        """Completions/second over the trailing window; 0.0 = no data."""
        if len(done) < 2:
            return 0.0
        lo = now - _DRAIN_WINDOW_S
        # deque is time-ordered; count the in-window tail
        n = 0
        oldest = now
        for t in reversed(done):
            if t < lo:
                break
            n += 1
            oldest = t
        if n < 2:
            return 0.0
        span = max(1e-3, now - oldest)
        return n / span

    def drain_rate(self, flow: str) -> float:
        with self._lock:
            return self._rate(self._done[flow], self._clock())

    def bind_rate(self) -> float:
        if self._ledger is not None:
            return self._ledger.bind_rate(self._clock())
        with self._lock:
            return self._rate(self._bind_done, self._clock())

    def _hint(self, pending: float, rate: float) -> float:
        """Retry-After from a measured drain rate: time for ``pending``
        completions at ``rate``, clamped. A cold server (no rate yet)
        answers the fallback — still a number picked for the deployment,
        not the constant '1' the old sites hardcoded."""
        if rate <= 0.0:
            return _HINT_FALLBACK_S
        return min(_HINT_MAX_S, max(_HINT_MIN_S, pending / rate))

    # -- admission --------------------------------------------------------

    def admit(self, flow: str, pod_create: bool = False) -> _Ticket:
        """Admit or raise ``Shed``. Flows are fully isolated: a request
        waits only on ITS flow's inflight budget and FIFO position —
        system is structurally never queued behind lower bands."""
        cfg = self.flows[flow]
        now = self._clock()
        with self._lock:
            if pod_create and flow == WORKLOAD and self.backlog_limit:
                backlog = self._backlog_locked()
                if backlog >= self.backlog_limit:
                    if self._ledger is not None:
                        rate = self._ledger.bind_rate(now)
                    else:
                        rate = self._rate(self._bind_done, now)
                    hint = self._hint(backlog - self.backlog_limit + 1,
                                      rate)
                    self._shed_locked(flow, "backlog", hint)
                    raise Shed(flow, "backlog", hint)
            if self._inflight[flow] < cfg.max_inflight:
                self._inflight[flow] += 1
                self._mx.inflight.set(self._inflight[flow], flow)
                self._mx.admitted.inc(flow)
                self._mx.queue_wait.observe(0.0, flow)
                return _Ticket(self, flow)
            q = self._queues[flow]
            if len(q) >= cfg.queue_limit:
                hint = self._hint(len(q) + 1,
                                  self._rate(self._done[flow], now))
                self._shed_locked(flow, "queue_full", hint)
                raise Shed(flow, "queue_full", hint)
            w = _Waiter(now)
            q.append(w)
            self._mx.queued.set(len(q), flow)
        ok = w.event.wait(cfg.queue_deadline_s)
        with self._lock:
            if w.admitted:
                # released slot was handed to us (possibly racing the
                # deadline — a handed slot is always taken, never leaked)
                wait_s = self._clock() - w.t_enq
                self._mx.queue_wait.observe(wait_s, flow)
                self._mx.admitted.inc(flow)
                return _Ticket(self, flow)
            try:
                self._queues[flow].remove(w)
            except ValueError:
                pass
            self._mx.queued.set(len(self._queues[flow]), flow)
            hint = self._hint(len(self._queues[flow]) + 1,
                              self._rate(self._done[flow], self._clock()))
            self._shed_locked(flow, "timeout", hint)
        assert not ok or w.admitted  # event set implies a handoff
        raise Shed(flow, "timeout", hint)

    def _shed_locked(self, flow: str, reason: str, hint: float) -> None:
        self._mx.shed.inc(flow, reason)
        self._mx.retry_after.observe(hint, flow)
        if flow == SYSTEM:
            # the starvation-freedom invariant counter: any non-zero
            # value here is an isolation bug, and the overload record
            # contract requires it to read 0
            self._mx.system_shed.inc()

    def _release(self, flow: str) -> None:
        now = self._clock()
        with self._lock:
            self._done[flow].append(now)
            q = self._queues[flow]
            while q:
                w = q.popleft()
                self._mx.queued.set(len(q), flow)
                if not w.admitted:
                    # hand the slot over: inflight count is unchanged,
                    # the waiter owns it from here
                    w.admitted = True
                    w.event.set()
                    return
            self._inflight[flow] = max(0, self._inflight[flow] - 1)
            self._mx.inflight.set(self._inflight[flow], flow)

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            now = self._clock()
            out = {}
            for f in self.flows:
                out[f] = {"inflight": self._inflight[f],
                          "queued": len(self._queues[f]),
                          "drain_rate": self._rate(self._done[f], now)}
            out["backlog"] = {"depth": self._backlog_locked(),
                              "limit": self.backlog_limit,
                              "bind_rate":
                                  self._ledger.bind_rate(now)
                                  if self._ledger is not None
                                  else self._rate(self._bind_done, now)}
            return out
