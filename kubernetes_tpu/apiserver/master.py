"""Master — constructs every registry and serves the verb dispatch.

Rebuild of ``pkg/master/master.go:350-490`` + the generic REST handlers
(``pkg/apiserver/resthandler.go``): one Config builds the store, the typed
helper, all per-resource registries and sub-resources, the admission chain,
and exposes ``dispatch`` — the single seam shared by the in-process client
and the HTTP layer, mirroring the reference invariant that every component
talks only through the API surface (DESIGN.md:40).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from kubernetes_tpu import admission as admission_pkg
# ktpu-vet: ok unused — side-effect import: registers admission plugin factories
from kubernetes_tpu.admission import plugins as admission_plugins  # noqa: F401
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.fields import parse_field_selector
from kubernetes_tpu.api.labels import parse_selector
from kubernetes_tpu.api.latest import scheme as default_scheme
from kubernetes_tpu.api.meta import default_rest_mapper
from kubernetes_tpu.registry import resources as reg
from kubernetes_tpu.registry.generic import Context
from kubernetes_tpu.storage.helper import StoreHelper
from kubernetes_tpu.storage.memstore import MemStore

__all__ = ["Master", "MasterConfig"]

DEFAULT_ADMISSION = ("NamespaceAutoProvision", "NamespaceLifecycle",
                     "LimitRanger", "ResourceQuota", "PriorityDefault")


@dataclass
class MasterConfig:
    """ref: master.Config (master.go:112-160)."""

    store: Optional[MemStore] = None
    scheme: Any = None
    admission_control: tuple = DEFAULT_ADMISSION
    authorizer: Any = None          # .authorize(user, attrs) raising Forbidden
    portal_net: str = "10.0.0.0/24"
    event_ttl_seconds: float = 3600.0
    cloud: Any = None               # cloudprovider.Interface (ref: master.go Cloud)


class Master:
    def __init__(self, config: Optional[MasterConfig] = None):
        c = config or MasterConfig()
        self.store = c.store or MemStore()
        self.scheme = c.scheme or default_scheme
        self.helper = StoreHelper(self.store, self.scheme)
        self.mapper = default_rest_mapper()
        self.authorizer = c.authorizer

        # registries (ref: master.go:350-396 init)
        self.pods = reg.make_pod_registry(self.helper)
        self.controllers = reg.make_rc_registry(self.helper)
        self.nodes = reg.make_node_registry(self.helper)
        self.services = reg.make_service_registry(
            self.helper, reg.IPAllocator(c.portal_net), cloud=c.cloud,
            node_lister=lambda: [n.metadata.name for n in
                                 self.nodes.list(Context()).items])
        self.endpoints = reg.make_endpoints_registry(self.helper)
        self.events = reg.make_event_registry(self.helper, c.event_ttl_seconds)
        self.namespaces = reg.make_namespace_registry(self.helper)
        self.secrets = reg.make_secret_registry(self.helper)
        self.limitranges = reg.make_limitrange_registry(self.helper)
        self.resourcequotas = reg.make_resourcequota_registry(self.helper)
        self.priorityclasses = reg.make_priorityclass_registry(self.helper)

        # sub/special resources
        self.bindings = reg.BindingREST(self.pods)
        self.pod_status = reg.PodStatusREST(self.pods)
        self.ns_finalize = reg.NamespaceFinalizeREST(self.namespaces)
        self.quota_status = reg.ResourceQuotaStatusREST(self.resourcequotas)

        # the storage map (ref: master.go:350 "storage" map[string]RESTStorage)
        self.storage: Dict[str, Any] = {
            "pods": self.pods,
            "replicationcontrollers": self.controllers,
            "services": self.services,
            "endpoints": self.endpoints,
            "nodes": self.nodes,
            "bindings": self.bindings,
            "events": self.events,
            "namespaces": self.namespaces,
            "secrets": self.secrets,
            "limitranges": self.limitranges,
            "resourcequotas": self.resourcequotas,
            "priorityclasses": self.priorityclasses,
        }
        self.subresources: Dict[tuple, Any] = {
            ("pods", "binding"): self.bindings,
            ("pods", "status"): self.pod_status,
            ("namespaces", "finalize"): self.ns_finalize,
            ("resourcequotas", "status"): self.quota_status,
        }

        # decode-time selfLink stamping: with the store's shared-read
        # contract (storage/helper.py), cached objects must be born
        # complete — a post-read stamp would make watch frames and list
        # responses order-dependent on whether a GET ran first
        for res_name, registry in self.storage.items():
            prefix = getattr(registry, "prefix", None)
            if prefix is None:
                continue  # subresource REST (bindings): no storage of its own
            self.helper.register_linker(
                prefix, self._make_linker(res_name, registry))

        self.admission = admission_pkg.new_from_plugins(
            list(c.admission_control),
            namespaces=self.namespaces,
            limitranges=self.limitranges,
            resourcequotas=self.resourcequotas,
            priorityclasses=self.priorityclasses,
        )

        # bootstrap: the default namespace always exists (the reference
        # auto-provisions "default" via admission; we seed it eagerly too)
        try:
            self.namespaces.create(
                Context(), api.Namespace(metadata=api.ObjectMeta(name=api.NamespaceDefault)))
        except errors.StatusError as e:
            if not errors.is_already_exists(e):
                raise

    # ------------------------------------------------------------------
    def _make_linker(self, resource: str, registry):
        def link(obj) -> None:
            m = getattr(obj, "metadata", None)
            if isinstance(m, api.ObjectMeta):
                m.self_link = self._self_link(resource, obj)
        return link

    def _self_link(self, resource: str, obj) -> str:
        """ref: resthandler.go setSelfLink — /api/<v>/namespaces/<ns>/<res>/<name>
        for namespaced resources, /api/<v>/<res>/<name> cluster-scoped."""
        m = getattr(obj, "metadata", None)
        if m is None:
            return ""
        version = getattr(self.scheme, "version", "v1")
        if self.mapper.is_namespaced(resource) and m.namespace:
            return f"/api/{version}/namespaces/{m.namespace}/{resource}/{m.name}"
        return f"/api/{version}/{resource}/{m.name}"

    def _stamp_self_links(self, resource: str, obj, namespace: str = ""):
        if obj is None:
            return obj
        items = getattr(obj, "items", None)
        if items is not None:
            for item in items:
                # result kinds (e.g. BindingResult) carry no ObjectMeta;
                # storage reads arrive pre-stamped by the decode-time
                # linker — never re-write a shared cached object here
                m = getattr(item, "metadata", None)
                if isinstance(m, api.ObjectMeta) and not m.self_link:
                    m.self_link = self._self_link(resource, item)
            version = getattr(self.scheme, "version", "v1")
            if self.mapper.is_namespaced(resource) and namespace:
                obj.metadata.self_link = \
                    f"/api/{version}/namespaces/{namespace}/{resource}"
            else:
                obj.metadata.self_link = f"/api/{version}/{resource}"
        elif hasattr(obj, "metadata") and isinstance(obj.metadata, api.ObjectMeta):
            if not obj.metadata.self_link:
                obj.metadata.self_link = self._self_link(resource, obj)
        return obj

    def _registry(self, resource: str):
        resource = self.mapper.resource_for(self.mapper.kind_for(resource)) \
            if self.mapper.has_resource(resource) else resource
        r = self.storage.get(resource)
        if r is None:
            raise errors.new_not_found("resource", resource)
        return resource, r

    def _authorize(self, user, attrs: admission_pkg.Attributes) -> None:
        if self.authorizer is not None:
            self.authorizer.authorize(user, attrs)

    def bind_batch(self, namespace: str, bindings: api.BindingList,
                   user: Any = None,
                   on_bound: Optional[Any] = None) -> api.BindingResultList:
        """POST /api/{v}/ns/{ns}/bindings:batch — one wave of CAS binds in
        one request. Authorization and admission run ONCE against the
        request namespace (the same checks the per-pod bind path runs per
        binding — every item is namespace-pinned to the request by
        BindingREST.create_many, so nothing escapes the single check);
        per-item CAS semantics and partial success are preserved by
        create_many/atomic_update_many."""
        ctx = Context(namespace=namespace, user=user)
        attrs = admission_pkg.Attributes(
            operation=admission_pkg.CREATE, resource="bindings",
            namespace=namespace, obj=bindings, user=user)
        self._authorize(user, attrs)
        self.admission.admit(attrs)
        self._authorize_victims(user, namespace, bindings.items)
        return self.bindings.create_many(ctx, bindings, on_bound=on_bound)

    def _authorize_victims(self, user, namespace: str, bindings) -> None:
        """kube-preempt: an evict+bind item deletes pods, so EVERY
        distinct victim namespace (the request's own included — binding
        create rights are not pod delete rights) gets its own DELETE
        authorization + admission pass. Shared by bind_batch and the
        per-pod binding subresource, so neither form widens what the
        plain delete verb allows."""
        victim_ns = {v.namespace or namespace
                     for b in bindings for v in getattr(b, "victims", ())}
        for ns in sorted(victim_ns):
            vattrs = admission_pkg.Attributes(
                operation=admission_pkg.DELETE, resource="pods",
                namespace=ns, user=user)
            self._authorize(user, vattrs)
            self.admission.admit(vattrs)

    def dispatch(self, verb: str, resource: str, *, namespace: str = "",
                 name: str = "", body: Any = None, subresource: str = "",
                 label_selector: str = "", field_selector: str = "",
                 resource_version: str = "", user: Any = None,
                 lag_limit: Optional[int] = None) -> Any:
        """The generic REST entry (ref: resthandler.go Get/List/Create/Update/
        Delete/Watch Resource). Verbs: get, list, create, update, delete,
        watch. Returns API objects, or a watch.Watcher for watch."""
        canonical, registry = self._registry(resource)
        ctx = Context(namespace=namespace, user=user)
        attrs = admission_pkg.Attributes(
            operation="", resource=canonical, namespace=namespace, name=name,
            obj=body, user=user, subresource=subresource)

        if subresource:
            sub = self.subresources.get((canonical, subresource))
            if sub is None:
                raise errors.new_not_found("resource", f"{canonical}/{subresource}")
            if verb == "create":
                attrs.operation = admission_pkg.CREATE
                self._authorize(user, attrs)
                self.admission.admit(attrs)
                if canonical == "pods" and subresource == "binding":
                    # a single evict+bind binding deletes pods too: same
                    # per-victim-namespace DELETE authz as bind_batch
                    items = list(getattr(body, "items", None) or [body])
                    if any(getattr(b, "victims", None) for b in items):
                        self._authorize_victims(user, namespace, items)
                return sub.create(ctx, body)
            if verb == "update":
                attrs.operation = admission_pkg.UPDATE
                self._authorize(user, attrs)
                self.admission.admit(attrs)
                return sub.update(ctx, body)
            raise errors.new_method_not_supported(canonical, verb)

        if verb == "get":
            self._authorize(user, attrs)
            return self._stamp_self_links(canonical, registry.get(ctx, name))
        if verb == "list":
            self._authorize(user, attrs)
            return self._stamp_self_links(
                canonical, registry.list(ctx, parse_selector(label_selector),
                                         parse_field_selector(field_selector)),
                namespace=namespace)
        if verb == "watch":
            self._authorize(user, attrs)
            return registry.watch(ctx, parse_selector(label_selector),
                                  parse_field_selector(field_selector),
                                  resource_version=resource_version)
        if verb == "watch_raw":
            # the HTTP fan-out path (apiserver/http._stream_watch): raw
            # store events + a translate callable, driven by the
            # connection's own thread — see GenericRegistry.watch_raw
            self._authorize(user, attrs)
            raw_fn = getattr(registry, "watch_raw", None)
            if raw_fn is None:
                # non-generic storage (e.g. bindings): the plain watch verb
                # carries the 405/behavior contract; identity-translate
                w = registry.watch(ctx, parse_selector(label_selector),
                                   parse_field_selector(field_selector),
                                   resource_version=resource_version)
                return w, (lambda ev: ev)
            return raw_fn(ctx, parse_selector(label_selector),
                          parse_field_selector(field_selector),
                          resource_version=resource_version,
                          lag_limit=lag_limit)
        if verb == "create":
            attrs.operation = admission_pkg.CREATE
            attrs.name = getattr(getattr(body, "metadata", None), "name", name)
            self._authorize(user, attrs)
            self.admission.admit(attrs)
            return self._stamp_self_links(canonical, registry.create(ctx, body))
        if verb == "update":
            attrs.operation = admission_pkg.UPDATE
            self._authorize(user, attrs)
            self.admission.admit(attrs)
            return self._stamp_self_links(canonical, registry.update(ctx, body))
        if verb == "delete":
            attrs.operation = admission_pkg.DELETE
            self._authorize(user, attrs)
            self.admission.admit(attrs)
            return registry.delete(ctx, name)
        raise errors.new_method_not_supported(canonical, verb)
