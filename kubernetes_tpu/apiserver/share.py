"""kube-share: the cross-worker apiserver side channel (shared segment).

An SO_REUSEPORT worker fleet (``--apiservers N``) splits two things the
single-worker hot path kept exact by construction:

1. **the encode-once frame cache** — frames are keyed ``(rv, version)``
   and the store's modified_index is globally unique per revision, so a
   frame built by the worker that COMMITTED the write is byte-valid for
   every sibling's watch fan-out. Without sharing, each worker of an
   N-fleet re-encodes every revision it fans out (N× the encode CPU the
   cache exists to avoid).
2. **the fairshed backlog ledger** — ``created - bound`` is exact only
   when one process sees both sides; the kernel load-balances creates
   and binds to DIFFERENT workers, so each worker's local ledger is a
   random share of the truth and the governor / Retry-After hints go
   blind (the former ``--overload`` ⇒ ``--apiservers 1`` restriction).

Both feeds ride ONE mmap-backed file (tmpfs in the harness): a fixed
header, then per-worker blocks of cache-line-aligned monotonic counters
plus a frame ring. The discipline that keeps it lock-free ACROSS
processes:

- **single-writer blocks** — worker *i* writes only block *i*; every
  other worker only reads it. In-process, a ``threading.Lock`` covers
  the handler threads of the owning worker.
- **publish-then-bump** — a ring record's bytes are fully written
  before the head counter moves, and heads/counters are aligned 8-byte
  slots (single-store on every platform this runs on), so a reader
  never observes a half-written record through a bumped head.
- **reader-validates** — heads are monotonic byte counts; a reader
  whose cursor lags by more than the ring size lost records (counted,
  ``apiserver_cache_seed_ring_drops_total``) and re-anchors at the
  head. After copying a batch it re-reads the head: if the writer
  lapped it mid-copy the batch is discarded, not imported.

Frame sharing is an OPTIMISATION feed (a lost record means a sibling
re-encodes once — correctness unaffected); the ledger counters are the
EXACT feed (never ring-buffered, never dropped: cumulative u64s summed
on read). docs/design/apiserver-hotpath.md §cross-worker has the full
design argument.
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

__all__ = ["ShareSegment", "SharedLedger", "DEFAULT_RING_BYTES"]

_MAGIC = b"KTPUSHR1"
_HEADER_FMT = "<8sII48x"            # magic, nworkers, ring_bytes -> 64 B
_HEADER_SIZE = struct.calcsize(_HEADER_FMT)
assert _HEADER_SIZE == 64

# per-worker counter block: one cache line of aligned u64 slots
_CTR_CREATED = 0      # pods created (fairshed ledger)
_CTR_BOUND = 1        # pods bound
_CTR_DELETED = 2      # pending deletes (post-clamp, see SharedLedger)
_CTR_HEAD = 3         # frame ring head (monotonic bytes, pads included)
_CTR_PUBLISHED = 4    # frame records published
_CTR_SLOTS = 8
_CTR_BYTES = _CTR_SLOTS * 8

# ring record: total_len(u32) rv_len(u16) ver_len(u16) then rv|ver|json.
# A 0xFFFFFFFF total_len is the wrap pad: skip to the next ring start.
_REC_FMT = "<IHH"
_REC_HEADER = struct.calcsize(_REC_FMT)
_WRAP_PAD = 0xFFFFFFFF

DEFAULT_RING_BYTES = 4 * 1024 * 1024


class ShareSegment:
    """One worker's attachment to the shared segment file. Create once
    (the harness / parent process), attach per worker with that
    worker's index; ``worker_index=-1`` attaches read-only (probes)."""

    def __init__(self, path: str, worker_index: int = -1):
        self.path = path
        self.worker_index = worker_index
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, nworkers, ring_bytes = struct.unpack_from(_HEADER_FMT,
                                                         self._mm, 0)
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a kube-share segment")
        self.nworkers = nworkers
        self.ring_bytes = ring_bytes
        if not (-1 <= worker_index < nworkers):
            raise ValueError(f"worker_index {worker_index} out of range "
                             f"(segment has {nworkers} workers)")
        # guards THIS process's writes into its own block; cross-process
        # isolation is structural (single-writer blocks)
        self._wlock = threading.Lock()
        # per-sibling ring cursors (monotonic byte counts)
        self._cursors = [0] * nworkers
        self.ring_drops = 0

    @classmethod
    def create(cls, path: str, nworkers: int,
               ring_bytes: int = DEFAULT_RING_BYTES,
               worker_index: int = -1) -> "ShareSegment":
        """Create (or truncate) the segment file and attach to it."""
        assert nworkers >= 1 and ring_bytes >= 4096
        size = _HEADER_SIZE + nworkers * (_CTR_BYTES + ring_bytes)
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, size)
        finally:
            os.close(fd)
        with open(path, "r+b") as f:
            f.write(struct.pack(_HEADER_FMT, _MAGIC, nworkers, ring_bytes))
        return cls(path, worker_index=worker_index)

    # -- layout -----------------------------------------------------------

    def _ctr_off(self, worker: int) -> int:
        return _HEADER_SIZE + worker * (_CTR_BYTES + self.ring_bytes)

    def _ring_off(self, worker: int) -> int:
        return self._ctr_off(worker) + _CTR_BYTES

    def _ctr_get(self, worker: int, slot: int) -> int:
        return struct.unpack_from("<Q", self._mm,
                                  self._ctr_off(worker) + slot * 8)[0]

    def _ctr_set(self, worker: int, slot: int, value: int) -> None:
        struct.pack_into("<Q", self._mm,
                         self._ctr_off(worker) + slot * 8, value)

    def _ctr_add(self, slot: int, n: int = 1) -> None:
        """Bump one of OUR counter slots (single-writer: only the in-
        process lock is needed)."""
        w = self.worker_index
        with self._wlock:
            self._ctr_set(w, slot, self._ctr_get(w, slot) + n)

    def counter_totals(self, slot: int) -> int:
        return sum(self._ctr_get(w, slot) for w in range(self.nworkers))

    def worker_counters(self, worker: int) -> dict:
        """One worker's published counters (harness disclosure)."""
        return {"created": self._ctr_get(worker, _CTR_CREATED),
                "bound": self._ctr_get(worker, _CTR_BOUND),
                "deleted": self._ctr_get(worker, _CTR_DELETED),
                "published": self._ctr_get(worker, _CTR_PUBLISHED)}

    # -- frame ring (publish side) ----------------------------------------

    def publish_frame(self, rv: str, version: str, wire_json: str) -> bool:
        """Publish one seeded encoding into our ring. Returns False if
        the record is too large to ever fit (never published)."""
        if self.worker_index < 0:
            return False
        rv_b = rv.encode("utf-8")
        ver_b = version.encode("utf-8")
        json_b = wire_json.encode("utf-8")
        total = _REC_HEADER + len(rv_b) + len(ver_b) + len(json_b)
        if total > self.ring_bytes // 2:
            return False
        w = self.worker_index
        base = self._ring_off(w)
        with self._wlock:
            head = self._ctr_get(w, _CTR_HEAD)
            pos = head % self.ring_bytes
            room = self.ring_bytes - pos
            if total > room:
                # wrap pad: mark (if a marker fits) and skip to ring start
                if room >= 4:
                    struct.pack_into("<I", self._mm, base + pos, _WRAP_PAD)
                head += room
                pos = 0
            off = base + pos
            struct.pack_into(_REC_FMT, self._mm, off, total,
                             len(rv_b), len(ver_b))
            off += _REC_HEADER
            self._mm[off:off + len(rv_b)] = rv_b
            off += len(rv_b)
            self._mm[off:off + len(ver_b)] = ver_b
            off += len(ver_b)
            self._mm[off:off + len(json_b)] = json_b
            # bump-last: the record is fully resident before readers can
            # see it through the head
            self._ctr_set(w, _CTR_HEAD, head + total)
            self._ctr_set(w, _CTR_PUBLISHED,
                          self._ctr_get(w, _CTR_PUBLISHED) + 1)
        return True

    # -- frame ring (consume side) ----------------------------------------

    def drain_frames(self, limit: int = 4096) \
            -> List[Tuple[str, str, str]]:
        """Import every sibling's new records: ``[(rv, version, json)]``.
        Loss-tolerant by contract — a lapped reader re-anchors and
        counts ``ring_drops`` (the consumer re-encodes those revisions,
        nothing breaks)."""
        out: List[Tuple[str, str, str]] = []
        for w in range(self.nworkers):
            if w == self.worker_index:
                continue
            out.extend(self._drain_one(w, limit))
        return out

    def _drain_one(self, w: int, limit: int) -> List[Tuple[str, str, str]]:
        head = self._ctr_get(w, _CTR_HEAD)
        cur = self._cursors[w]
        if head == cur:
            return []
        if head - cur > self.ring_bytes:
            # lapped before we started: everything between is gone
            self.ring_drops += 1
            cur = head
            self._cursors[w] = cur
            return []
        base = self._ring_off(w)
        batch: List[Tuple[str, str, str]] = []
        while cur < head and len(batch) < limit:
            pos = cur % self.ring_bytes
            room = self.ring_bytes - pos
            if room < _REC_HEADER:
                cur += room
                continue
            total, rv_len, ver_len = struct.unpack_from(_REC_FMT, self._mm,
                                                        base + pos)
            if total == _WRAP_PAD:
                cur += room
                continue
            if total < _REC_HEADER or total > room:
                # torn/lapped read — re-anchor at head
                self.ring_drops += 1
                cur = head
                break
            off = base + pos + _REC_HEADER
            rv = bytes(self._mm[off:off + rv_len])
            off += rv_len
            ver = bytes(self._mm[off:off + ver_len])
            off += ver_len
            json_len = total - _REC_HEADER - rv_len - ver_len
            payload = bytes(self._mm[off:off + json_len])
            cur += total
            batch.append((rv.decode("utf-8", "replace"),
                          ver.decode("utf-8", "replace"),
                          payload.decode("utf-8", "replace")))
        # lap check: if the writer overwrote what we just copied, the
        # bytes above may interleave two records — discard, re-anchor
        if self._ctr_get(w, _CTR_HEAD) - self._cursors[w] > self.ring_bytes:
            self.ring_drops += 1
            self._cursors[w] = self._ctr_get(w, _CTR_HEAD)
            return []
        self._cursors[w] = cur
        return batch

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


class SharedLedger:
    """The cross-worker fairshed drain feed: exact global
    created/bound/deleted from the segment's per-worker cumulative
    counters, plus a measured GLOBAL bind rate.

    The rate is sampled locally: every query appends ``(now, Σbound)``
    to a trailing-window deque — admission traffic IS the sampler, so
    under the load that makes hints matter the window is dense. The
    delete clamp mirrors the local ledger's availability-safe rule: a
    delete only counts while the global backlog is positive (deleting a
    BOUND pod must not open phantom governor headroom)."""

    _WINDOW_S = 10.0
    _SAMPLES = 2048

    def __init__(self, seg: ShareSegment, clock=None):
        self.seg = seg
        self._clock = clock or time.monotonic
        self._samples: deque = deque(maxlen=self._SAMPLES)
        self._lock = threading.Lock()

    def note_created(self) -> None:
        self.seg._ctr_add(_CTR_CREATED)

    def note_bound(self, n: int) -> None:
        self.seg._ctr_add(_CTR_BOUND, n)
        self._sample()

    def note_deleted(self) -> None:
        if self.backlog() > 0:
            self.seg._ctr_add(_CTR_DELETED)

    def backlog(self) -> int:
        s = self.seg
        return max(0, s.counter_totals(_CTR_CREATED)
                   - s.counter_totals(_CTR_BOUND)
                   - s.counter_totals(_CTR_DELETED))

    def _sample(self) -> None:
        now = self._clock()
        total = self.seg.counter_totals(_CTR_BOUND)
        with self._lock:
            self._samples.append((now, total))

    def bind_rate(self, now: Optional[float] = None) -> float:
        """Global binds/second over the trailing window (0.0 = no
        data). Samples on every call, so admission-time queries keep
        the window fresh without a background thread."""
        self._sample()
        if now is None:
            now = self._clock()
        lo = now - self._WINDOW_S
        with self._lock:
            window = [(t, v) for t, v in self._samples if t >= lo]
        if len(window) < 2:
            return 0.0
        (t0, v0), (t1, v1) = window[0], window[-1]
        if v1 <= v0:
            return 0.0
        return (v1 - v0) / max(1e-3, t1 - t0)
