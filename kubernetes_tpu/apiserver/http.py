"""HTTP REST layer over Master.dispatch.

Rebuild of the reference's API serving stack: route installation
(ref: pkg/apiserver/api_installer.go:194-239), generic REST handlers
(ref: pkg/apiserver/resthandler.go), watch streaming as chunked JSON frames
(ref: pkg/apiserver/watch.go:62-142), JSON merge PATCH
(ref: resthandler.go:205 PatchResource), proxy/redirect
(ref: pkg/apiserver/{proxy,redirect}.go), request logging
(ref: pkg/httplog/log.go), Prometheus request metrics
(ref: pkg/apiserver/apiserver.go:40-87), plus the unversioned endpoints
/healthz (ref: pkg/healthz), /version (ref: pkg/version), /validate
(ref: pkg/master/master.go:516-551) and /metrics.

Paths, both namespaced-in-path (v1-style, ref v1beta3) and
namespace-as-query-param (legacy v1beta1 style):

    /api                                   -> {"versions": [...]}
    /api/{v}/namespaces/{ns}/{res}[/{name}[/{sub}]]
    /api/{v}/{res}[/{name}]?namespace=ns
    /api/{v}/watch/...        or ?watch=true  -> chunked watch stream
    /api/{v}/proxy/{res}/{name}/{path...}     -> subrequest relay
    /api/{v}/redirect/{res}/{name}            -> 307 Location
"""

from __future__ import annotations

import json
import logging
import os
import re
import socket
import threading
import time
import urllib.parse
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from kubernetes_tpu import version as version_pkg
from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import fairshed as fairshed_mod
from kubernetes_tpu.auth import AuthRequest
from kubernetes_tpu.util import chaos
from kubernetes_tpu.util import metrics as metrics_pkg
from kubernetes_tpu.util import tracing

_httplog = logging.getLogger("kubernetes_tpu.apiserver.httplog")

__all__ = ["APIServer"]


def _convert_field_selector(apisrv, version: str, resource: str,
                            sel: str) -> str:
    """Rewrite a field selector from the request version's label vocabulary
    to the internal one (ref: pkg/api/v1beta1/conversion.go field-label
    conversion funcs; registered per kind in api/latest.py)."""
    from kubernetes_tpu.api.fields import FieldSelector, parse_field_selector

    try:
        _, registry = apisrv.master._registry(resource)
        obj_type = registry.obj_type
        kind = getattr(obj_type, "kind", "") or obj_type.__name__
    except Exception:
        return sel
    try:
        fs = parse_field_selector(sel)
    except ValueError:
        return sel  # the registry layer surfaces the parse error uniformly
    out = []
    for f, op, v in fs.requirements:
        nf, nv = apisrv.scheme.convert_field_label(version, kind, f, v)
        out.append((nf, op, nv))
    return str(FieldSelector(out))


def _merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge patch (ref: resthandler.go:205 PatchResource)."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out


class _FastHeaders:
    """Case-insensitive header mapping with the small API surface the
    handlers use (.get/.items/in). Replaces the stdlib email-parser
    message object, which costs ~0.2ms per request at churn rates."""

    __slots__ = ("_h",)

    def __init__(self, lower_to_pairs: dict):
        self._h = lower_to_pairs  # lower-name -> (original name, value)

    def get(self, name, default=None):
        pair = self._h.get(name.lower())
        return pair[1] if pair is not None else default

    def __contains__(self, name) -> bool:
        return name.lower() in self._h

    def __getitem__(self, name):
        return self._h[name.lower()][1]

    def items(self):
        return [(n, v) for n, v in self._h.values()]

    def keys(self):
        return [n for n, _ in self._h.values()]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # keep-alive clients see headers and body as separate writes; without
    # NODELAY, Nagle + the client's delayed ACK makes every kept-alive
    # request a ~40ms round trip
    disable_nagle_algorithm = True
    server_version = "kubernetes-tpu-apiserver"

    def parse_request(self) -> bool:
        """Lean replacement for the stdlib parse (same observable
        behavior for HTTP/1.0-1.1 clients: keep-alive semantics, Expect:
        100-continue, 431 on oversized headers). The stdlib path builds
        an email.message.Message per request via feedparser — measurably
        the single biggest fixed cost per request under churn."""
        self.command = None
        self.request_version = "HTTP/0.9"
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if len(words) == 3:
            command, path, version = words
            if not version.startswith("HTTP/"):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
        elif len(words) == 2:
            command, path = words
            version = "HTTP/0.9"
            if command != "GET":
                self.send_error(400,
                                f"Bad HTTP/0.9 request type ({command!r})")
                return False
        else:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path, self.request_version = command, path, version

        headers: dict = {}
        n_lines = 0
        while True:
            line = self.rfile.readline(65537)
            if len(line) > 65536:
                self.send_error(431, "Header line too long")
                return False
            if line in (b"\r\n", b"\n", b""):
                break
            n_lines += 1
            if n_lines > 200:  # bound header LINES, not dict entries —
                self.send_error(431, "Too many headers")  # joins don't grow it
                return False
            name, sep, value = line.decode("iso-8859-1").partition(":")
            if not sep:
                self.send_error(400, "Malformed header line")
                return False
            name = name.strip()
            lname = name.lower()
            prev = headers.get(lname)
            if prev is None:
                headers[lname] = (name, value.strip())
            elif lname == "content-length":
                # RFC 7230 §3.3.2: repeats must be identical; a joined value
                # would fail int() later, so reject differing repeats here
                if value.strip() != prev[1]:
                    self.send_error(400, "Conflicting Content-Length")
                    return False
            else:  # RFC 7230 §3.2.2: join repeats with ", "
                headers[lname] = (prev[0], prev[1] + ", " + value.strip())
        self.headers = _FastHeaders(headers)

        # bodies are framed by Content-Length only; a chunked body would be
        # left unread in rfile and desync the kept-alive stream (CL.TE
        # smuggling, RFC 7230 §3.3.3) — refuse rather than desync
        te = headers.get("transfer-encoding")
        if te is not None and te[1].strip().lower() not in ("", "identity"):
            self.send_error(501, "Transfer-Encoding not supported")
            return False

        conntokens = [t.strip() for t in
                      (self.headers.get("Connection") or "").lower().split(",")]
        if "close" in conntokens:
            self.close_connection = True
        elif version >= "HTTP/1.1" or ("keep-alive" in conntokens
                                       and self.protocol_version >= "HTTP/1.1"):
            self.close_connection = False
        expect = [t.strip() for t in
                  self.headers.get("Expect", "").lower().split(",")]
        if ("100-continue" in expect
                and self.protocol_version >= "HTTP/1.1"
                and version >= "HTTP/1.1"):
            if not self.handle_expect_100():
                return False
        return True

    # ----- plumbing -------------------------------------------------------

    def log_message(self, fmt, *args):  # ref: pkg/httplog — route to hook
        log = self.server.api.request_log  # type: ignore[attr-defined]
        if log is not None:
            log("%s %s" % (self.address_string(), fmt % args))

    def _send_json(self, code: int, payload: str, extra_headers=()):
        body = payload.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str, ctype="text/plain; charset=utf-8"):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_status_error(self, e: errors.StatusError, version: str,
                           extra_headers=()):
        apisrv = self.server.api  # type: ignore[attr-defined]
        try:
            payload = apisrv.scheme.encode(e.status, version)
        except Exception:
            payload = json.dumps({"kind": "Status", "status": "Failure",
                                  "message": str(e), "code": e.code})
        self._send_json(e.code, payload, extra_headers=extra_headers)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    # ----- verb entry points ---------------------------------------------

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_PUT(self):
        self._route("PUT")

    def do_PATCH(self):
        self._route("PATCH")

    def do_DELETE(self):
        self._route("DELETE")

    def do_OPTIONS(self):
        # CORS preflight (ref: handlers.go:140-144): an allowed origin gets
        # its headers and stops at 204; anything else keeps the pre-CORS
        # behavior — a plain 501 Unsupported method, never dispatched
        apisrv = self.server.api  # type: ignore[attr-defined]
        started = time.monotonic()
        resource = ([p for p in self.path.split("/") if p] + ["", "", ""])[2]
        self._read_body()  # keep-alive hygiene, like _route
        rl = apisrv.rate_limiter
        if self._cors_check():
            # allowed-origin preflight: answered WITHOUT consuming a
            # rate-limit token. A preflight is browser-generated, touches
            # no store state, and costs one header block — metering it
            # would let anonymous OPTIONS bursts starve the throttled
            # port's reads of tokens, while refusing it (on the read-only
            # port) would break the non-simple GETs (Authorization,
            # X-Requested-With, ...) whose headers this server itself
            # advertises in _CORS_HEADERS
            code = 204
            self.send_response(code)
            self.send_header("Content-Length", "0")
            self.end_headers()
        elif apisrv.read_only:
            # ReadOnly(RateLimit(handler)) nesting for everything else: a
            # non-preflight OPTIONS is a write-shaped method and the
            # GET-only gate rejects it BEFORE the limiter, so it can never
            # drain tokens legitimate reads need
            code = 403
            self._send_status_error(
                errors.new_forbidden("", "", "this is a read-only endpoint"),
                apisrv.default_version)
        elif rl is not None and not rl.can_accept():
            code = 429
            hint = apisrv.retry_after_hint()
            self._send_status_error(
                errors.new_too_many_requests(retry_after_s=hint),
                apisrv.default_version,
                extra_headers=(("Retry-After", str(hint)),))
        else:
            code = 501
            self.send_error(code, "Unsupported method ('OPTIONS')")
        # preflights are real traffic: browsers send one before every
        # non-simple request — record them like every other response
        apisrv.metric_requests.inc("options", resource,
                                   self.client_address[0], str(code))
        apisrv.metric_latency.observe(time.monotonic() - started,
                                      "options", resource)
        _httplog.log(logging.DEBUG, "OPTIONS %s -> %d from %s",
                     self.path, code, self.client_address[0])

    # ----- CORS (ref: pkg/apiserver/handlers.go CORS) ---------------------

    _CORS_METHODS = "POST, GET, OPTIONS, PUT, DELETE"
    _CORS_HEADERS = ("Content-Type, Content-Length, Accept-Encoding, "
                     "X-CSRF-Token, Authorization, X-Requested-With, "
                     "If-Modified-Since")

    def _cors_check(self) -> bool:
        """Remember the request Origin when it matches the allow-list; the
        end_headers hook then stamps the CORS headers on whatever response
        the handler writes."""
        self._cors_origin = None
        patterns = self.server.api.cors_patterns  # type: ignore[attr-defined]
        self._cors_enabled = bool(patterns)
        if not patterns:
            return False
        origin = self.headers.get("Origin") or ""
        # fullmatch, not search: these responses carry Allow-Credentials,
        # and an unanchored pattern like "https://example.com" would also
        # grant a lookalike origin ("https://example.com.evil.net") the
        # browser's credentialed trust. Patterns are anchored at both ends;
        # authors who want subdomains say so explicitly (".*\.example\.com")
        if origin and any(p.fullmatch(origin) for p in patterns):
            self._cors_origin = origin
            return True
        return False

    def end_headers(self):
        if getattr(self, "_cors_enabled", False):
            # responses differ by Origin whenever CORS is on (headers
            # present vs absent, and the reflected origin value): caches
            # must key on it or one origin's variant poisons another's
            self.send_header("Vary", "Origin")
            self._cors_enabled = False
        origin = getattr(self, "_cors_origin", None)
        if origin:
            self.send_header("Access-Control-Allow-Origin", origin)
            self.send_header("Access-Control-Allow-Methods", self._CORS_METHODS)
            self.send_header("Access-Control-Allow-Headers", self._CORS_HEADERS)
            self.send_header("Access-Control-Allow-Credentials", "true")
            self._cors_origin = None  # once per response
        super().end_headers()

    # ----- routing --------------------------------------------------------

    def _route(self, method: str):
        apisrv = self.server.api  # type: ignore[attr-defined]
        started = time.monotonic()
        parsed = urllib.parse.urlsplit(self.path)
        # handlers use the single-value view; the node/pod proxy forwards
        # the raw pairs so repeated params (exec argv) survive
        self._raw_query_pairs = urllib.parse.parse_qsl(parsed.query)
        # first-value view from the pairs already parsed (the stdlib
        # parse_qs would re-parse the query string a second time)
        query: dict = {}
        for k, v in self._raw_query_pairs:
            if k not in query:
                query[k] = v
        parts = [p for p in parsed.path.split("/") if p]
        self._cors_check()   # stamps headers on the response if allowed
        code = 200
        self._fs_ticket = None   # per-request (keep-alive reuses self)
        verb_label = method.lower()
        self._metric_resource = (parts + ["", "", ""])[2]
        # Always drain the body up front: unread bytes would desync the
        # keep-alive connection (next request parses them as a request line).
        raw_body = self._read_body()
        # kube-trace: a request carrying X-KTPU-Trace joins its caller's
        # trace (the scheduler wave's commit leg, a client's list). Only
        # traced requests record spans — untraced churn traffic must not
        # fill the ring. One header lookup when tracing is on; zero cost
        # when off.
        self._trace_ctx = tracing.parse(
            self.headers.get(tracing.HEADER)) if tracing.enabled() else None
        try:
            # read-only / rate-limit serving modes. The reference nests
            # ReadOnly(RateLimit(handler)) (handlers.go, wired by
            # cmd/kube-apiserver onto the ro port), so the GET-only check
            # runs FIRST: a rejected write must not consume a token that a
            # legitimate read could have used.
            if apisrv.read_only and method != "GET":
                raise errors.new_forbidden(
                    "", "", "this is a read-only endpoint")
            rl = apisrv.rate_limiter
            if rl is not None and not rl.can_accept():
                code = 429
                hint = apisrv.retry_after_hint()
                self._send_status_error(
                    errors.new_too_many_requests(retry_after_s=hint),
                    self._version_of(parts),
                    extra_headers=(("Retry-After", str(hint)),))
                return
            # kube-fairshed flow-classified admission (docs/design/
            # apiserver-hotpath.md): classify by path/user-agent, take
            # (or wait for) an inflight slot in the request's OWN flow,
            # shed with 429 + a measured-drain Retry-After when the
            # flow's queue or the workload backlog governor says no.
            # System traffic never waits on lower bands — isolation is
            # per-flow by construction.
            fs = apisrv.fairshed
            flow = ""
            if fs is not None:
                flow = fairshed_mod.classify(
                    method, parts, self.headers.get("User-Agent"))
                _head, res, sub = fairshed_mod.route_info(parts)
                try:
                    self._fs_ticket = fs.admit(
                        flow, pod_create=(method == "POST"
                                          and res == "pods" and not sub))
                except fairshed_mod.Shed as e:
                    code = 429
                    hint = max(1, int(-(-e.retry_after_s // 1)))
                    self._send_status_error(
                        errors.new_too_many_requests(
                            f"{e.flow} flow over capacity "
                            f"({e.reason}); retry in {hint}s",
                            retry_after_s=hint),
                        self._version_of(parts),
                        extra_headers=(("Retry-After", str(hint)),))
                    return
            user = self._authenticate(apisrv)
            # kube-chaos gray-latency twins: the harness's
            # component@T:delay=MS schedule pauses a live process; these
            # seams inject the same stall in-process so tier-1 proves
            # flow isolation under slowness without process churn
            chaos.delay_if_armed("apiserver.dispatch")
            if flow:
                chaos.delay_if_armed("apiserver.dispatch." + flow)
            if self._trace_ctx is not None:
                with tracing.span("http." + verb_label,
                                  parent=self._trace_ctx,
                                  path=parsed.path):
                    code = self._dispatch_path(method, parts, query, user,
                                               raw_body)
            else:
                code = self._dispatch_path(method, parts, query, user,
                                           raw_body)
        except errors.StatusError as e:
            code = e.code
            self._send_status_error(e, self._version_of(parts))
        except (BrokenPipeError, ConnectionResetError):
            code = 499
        except Exception as e:  # ref: util.HandleCrash — 500, keep serving
            code = 500
            try:
                self._send_status_error(errors.new_internal_error(repr(e)),
                                        self._version_of(parts))
            except Exception:
                pass
        finally:
            ticket = self._fs_ticket
            if ticket is not None:
                ticket.release()   # idempotent: watches released early
            apisrv.metric_requests.inc(verb_label, self._metric_resource,
                                       self.client_address[0], str(code))
            elapsed = time.monotonic() - started
            apisrv.metric_latency.observe(elapsed, verb_label,
                                          self._metric_resource)
            # request log (ref: pkg/httplog/log.go — method, path, status,
            # latency per request; DEBUG so production defaults stay quiet
            # like glog's v-levels, errors at INFO)
            _httplog.log(
                logging.INFO if code >= 500 else logging.DEBUG,
                "%s %s -> %d (%.1fms) from %s", method, self.path, code,
                elapsed * 1000.0, self.client_address[0])

    def _version_of(self, parts) -> str:
        apisrv = self.server.api  # type: ignore[attr-defined]
        if len(parts) >= 2 and parts[0] == "api" and parts[1] in apisrv.versions:
            return parts[1]
        return apisrv.default_version

    def _authenticate(self, apisrv):
        authn = apisrv.authenticator
        if authn is None:
            return None
        peer_cert = None
        if hasattr(self.connection, "getpeercert"):
            try:
                peer_cert = self.connection.getpeercert()
            except Exception:
                peer_cert = None
        req = AuthRequest(headers=dict(self.headers.items()), peer_cert=peer_cert)
        info, ok = authn.authenticate(req)
        if not ok:
            raise errors.new_unauthorized()
        return info

    def _dispatch_path(self, method: str, parts, query: Dict[str, str], user,
                       raw_body: bytes = b"") -> int:
        apisrv = self.server.api  # type: ignore[attr-defined]

        if not parts:
            self._send_json(200, json.dumps(
                {"paths": ["/api", "/healthz", "/metrics", "/ui/",
                           "/validate", "/version"]}))
            return 200
        head = parts[0]
        if head in ("ui", "static"):  # ref: pkg/ui served at /static/
            if method != "GET":
                raise errors.new_method_not_supported("asset", method)
            from kubernetes_tpu.ui import asset
            found = asset("/".join(parts[1:]))
            if found is None:
                raise errors.new_not_found("asset", "/".join(parts[1:]))
            body, ctype = found
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return 200
        if head == "healthz":
            return self._handle_healthz(parts[1:])
        if head == "version":
            self._send_json(200, json.dumps(version_pkg.get().as_dict()))
            return 200
        if head == "metrics":
            payload = apisrv.metrics_registry.render_text()
            # the process-wide default registry carries the watch-package
            # loss counters (watch_events_dropped/coalesced, lag resyncs)
            # — surface them alongside the per-server families
            default_reg = metrics_pkg.default_registry()
            if default_reg is not apisrv.metrics_registry:
                payload += default_reg.render_text()
            self._send_text(200, payload,
                            ctype="text/plain; version=0.0.4; charset=utf-8")
            return 200
        if head == "validate":
            payload, ok = apisrv.validate_components()
            self._send_json(200 if ok else 500, json.dumps(payload))
            return 200 if ok else 500
        if head == "debug" and len(parts) >= 2 and parts[1] == "pprof":
            return self._handle_pprof(parts[2:], query)
        if head == "debug" and len(parts) >= 2 and parts[1] == "vars":
            # kube-flightrec shard: this process's metric time-series
            # rings, incremental past the caller's ?since=<ns> cursor.
            # The first pull ARMS the recorder (lazily, like the span
            # ring) so aggregator discovery is also activation.
            if method != "GET":
                raise errors.new_method_not_supported("vars", method)
            try:
                since = int(query.get("since", "0") or "0")
            except ValueError:
                since = 0
            self._send_json(200, json.dumps(self.server.api.flightrec_vars(
                since)))
            return 200
        if head == "debug" and len(parts) >= 2 and parts[1] == "trace":
            # drain this process's span ring (kube-trace shard); the churn
            # harness merges every process's shard into one Perfetto file.
            # ?peek=1 reads without resetting the drain cursor.
            if method != "GET":
                raise errors.new_method_not_supported("trace", method)
            self._send_json(200, json.dumps(tracing.drain(
                reset=query.get("peek") not in ("1", "true"))))
            return 200
        if head != "api":
            raise errors.new_not_found("path", "/" + "/".join(parts))
        if len(parts) == 1:
            self._send_json(200, json.dumps({"versions": list(apisrv.versions)}))
            return 200

        version = parts[1]
        if version not in apisrv.versions:
            raise errors.new_not_found("apiVersion", version)
        rest = parts[2:]

        watching = query.get("watch") in ("true", "1")
        if rest and rest[0] == "watch":  # /api/{v}/watch/... prefix form
            watching = True
            rest = rest[1:]
        if rest and rest[0] in ("proxy", "redirect"):
            return self._handle_proxy_redirect(rest[0], version, rest[1:],
                                               query, user, method, raw_body)

        # the batch-bind verb-suffix route: "bindings:batch" is one path
        # segment; normalize it to the bindings resource before namespace
        # scoping so both path-ns and query-ns forms resolve
        batch_bind = "bindings:batch" in rest
        if batch_bind:
            rest = ["bindings" if seg == "bindings:batch" else seg
                    for seg in rest]

        # namespace from path (v1-style) or query param (v1beta1-style).
        # /namespaces/{name}[/finalize] stays the namespaces resource itself;
        # /namespaces/{ns}/{known-resource}/... scopes the request.
        namespace = query.get("namespace", "")
        if rest and rest[0] == "namespaces" and len(rest) >= 3 \
                and apisrv.is_resource(rest[2]):
            namespace, rest = rest[1], rest[2:]
        if not rest:
            raise errors.new_bad_request("no resource in path")
        resource = rest[0]
        self._metric_resource = resource
        name = rest[1] if len(rest) > 1 else ""
        subresource = rest[2] if len(rest) > 2 else ""

        if batch_bind:
            if resource != "bindings" or name or watching:
                raise errors.new_bad_request(
                    "the :batch suffix applies to POST .../bindings:batch")
            self._metric_resource = "bindings:batch"
            if method != "POST":
                raise errors.new_method_not_supported("bindings:batch",
                                                      method)
            return self._handle_batch_bind(version, namespace, raw_body,
                                           user)

        label_sel = query.get("labelSelector", query.get("labels", ""))
        field_sel = query.get("fieldSelector", query.get("fields", ""))
        rv = query.get("resourceVersion", "")
        if field_sel:
            # field labels are a per-version vocabulary (v1beta1
            # "DesiredState.Host" == internal "spec.host"; ref:
            # pkg/api/v1beta1/conversion.go field-label funcs)
            field_sel = _convert_field_selector(apisrv, version, resource,
                                                field_sel)

        if watching:
            if method != "GET":
                raise errors.new_bad_request("watch requires GET")
            if name:  # single-object watch scopes by name
                field_sel = f"metadata.name={name}"
            watcher, translate = apisrv.master.dispatch(
                "watch_raw", resource, namespace=namespace,
                label_selector=label_sel, field_selector=field_sel,
                resource_version=rv, user=user,
                lag_limit=apisrv.watch_lag_limit)
            self._stream_watch(watcher, translate, version,
                               gate_tag=query.get("chaosGate", ""))
            return 200

        body_obj = None
        if method in ("POST", "PUT", "PATCH"):
            if method == "PATCH":
                return self._handle_patch(version, resource, namespace, name,
                                          subresource, raw_body, user)
            if raw_body:
                try:
                    body_obj = apisrv.scheme.decode(
                        raw_body, default_version=version)
                except Exception as e:
                    raise errors.new_bad_request(f"cannot decode body: {e}")

        verb = {"GET": "get" if name else "list", "POST": "create",
                "PUT": "update", "DELETE": "delete"}[method]
        out = apisrv.master.dispatch(
            verb, resource, namespace=namespace, name=name, body=body_obj,
            subresource=subresource, label_selector=label_sel,
            field_selector=field_sel, user=user)
        code = 201 if verb == "create" else 200
        fs = apisrv.fairshed
        if fs is not None and resource == "pods":
            # workload backlog governor ledger: pods entering the
            # pending set, pods bound (the per-pod binding subresource;
            # the batch endpoint counts its own), pods leaving
            if verb == "create" and not subresource:
                fs.note_pod_created()
            elif verb == "create" and subresource == "binding":
                fs.note_pods_bound(1)
            elif verb == "delete" and not subresource:
                fs.note_pod_deleted()
        if out is None:
            ok = api.Status(status=api.StatusSuccess, code=code)
            self._send_json(code, apisrv.scheme.encode(ok, version))
        else:
            # encode_response seeds the watch frame cache with this very
            # payload: the fan-out of the store event this write produced
            # then copies bytes instead of encoding again
            self._send_json(code, apisrv.encode_response(out, version))
        return code

    def _handle_batch_bind(self, version: str, namespace: str,
                           raw_body: bytes, user) -> int:
        """POST .../bindings:batch — one scheduler wave of CAS binds in
        ONE keep-alive request (the bind_many seam's wire form). Body:
        BindingList; response: 200 BindingResultList with per-item
        status/code — partial success, per-pod CAS semantics identical
        to POST pods/{name}/binding."""
        apisrv = self.server.api  # type: ignore[attr-defined]
        started = time.monotonic()
        if not raw_body:
            raise errors.new_bad_request(
                "bindings:batch requires a BindingList body")
        try:
            body = apisrv.scheme.decode(raw_body, default_version=version)
        except Exception as e:
            raise errors.new_bad_request(f"cannot decode body: {e}")
        if isinstance(body, api.Binding):
            body = api.BindingList(items=[body])
        if not isinstance(body, api.BindingList):
            raise errors.new_bad_request(
                "bindings:batch body must be a BindingList")
        out = apisrv.master.bind_batch(
            namespace or api.NamespaceDefault, body, user=user,
            # encode-once at commit: each bound pod's new revision is
            # serialized here, where the write lands, so the watch fan-out
            # of its CAS event is a byte copy for every watcher
            on_bound=lambda pod: apisrv.seed_frame(pod, version))
        payload = apisrv.scheme.encode(out, version)
        if apisrv.fairshed is not None:
            bound = sum(1 for item in out.items if not item.error)
            apisrv.fairshed.note_pods_bound(bound)
        apisrv.metric_batch_bind_size.observe(len(body.items))
        apisrv.metric_batch_bind_seconds.observe(time.monotonic() - started)
        self._send_json(200, payload)
        return 200

    def _handle_patch(self, version, resource, namespace, name, subresource,
                      raw: bytes, user) -> int:
        """JSON merge patch: read-modify-write through the codec
        (ref: resthandler.go PatchResource:205)."""
        apisrv = self.server.api  # type: ignore[attr-defined]
        if not name:
            raise errors.new_bad_request("PATCH requires a resource name")
        try:
            patch = json.loads(raw.decode("utf-8"))
        except Exception as e:
            raise errors.new_bad_request(f"cannot decode patch: {e}")
        current = apisrv.master.dispatch("get", resource, namespace=namespace,
                                         name=name, user=user)
        wire = json.loads(apisrv.scheme.encode(current, version))
        merged = _merge_patch(wire, patch)
        try:
            obj = apisrv.scheme.decode(json.dumps(merged), default_version=version)
        except Exception as e:
            raise errors.new_bad_request(f"patched object invalid: {e}")
        out = apisrv.master.dispatch("update", resource, namespace=namespace,
                                     name=name, body=obj,
                                     subresource=subresource, user=user)
        self._send_json(200, apisrv.scheme.encode(out, version))
        return 200

    def _handle_healthz(self, subpath) -> int:
        """Deep health (ref: pkg/healthz grown toward ComponentStatus):
        /healthz probes the components this server actually depends on —
        store reachability and watch-hub liveness — and answers 503 with
        the per-component verdicts when any fails. /healthz/ping stays
        the unconditional liveness answer (process up, serving)."""
        if subpath and subpath[0] == "ping":
            self._send_text(200, "ok")
            return 200
        payload, ok = self.server.api.health_components()
        code = 200 if ok else 503
        self._send_json(code, json.dumps(payload))
        return code

    # ----- watch streaming (ref: pkg/apiserver/watch.go:62-142) ----------

    def _write_chunk(self, data: bytes):
        self.wfile.write(f"{len(data):x}\r\n".encode("ascii"))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()

    def _handle_pprof(self, rest, query) -> int:
        """ref: pprof endpoints every reference binary exposes
        (pkg/master/master.go:431-435)."""
        from kubernetes_tpu.util import pprof

        which = rest[0] if rest else ""
        body = pprof.handle(which, query.get("seconds", ""),
                            query.get("format", ""))
        if body is None:
            raise errors.new_not_found("pprof", which)
        self._send_text(200, body)
        return 200

    def _translate_batch(self, batch, translate, version, ws_frames: bool):
        """Map one drained batch of raw store events to wire byte parts.
        Returns (parts, lagged): ``lagged`` means the bounded-lag resync
        marker was hit — its 410 ERROR frame is the last part and the
        stream must end. The encode (if any) happens here exactly once
        per (revision, version); every other watcher of the same event
        copies cached bytes."""
        apisrv = self.server.api  # type: ignore[attr-defined]
        idx = 2 if ws_frames else 1
        parts = []
        for ev in batch:
            if ev.type == watchpkg.ERROR and ev.object is None:
                # bounded-lag drop-to-resync marker from the store layer
                parts.append(apisrv.lag_resync_entry(version)[idx])
                apisrv.metric_watch_lag_drops.inc()
                return parts, True
            try:
                tev = translate(ev)
                if tev is None:
                    continue
                if isinstance(tev, tuple):  # fast path: (type, rv, thunk)
                    ev_type, rv, thunk = tev
                    parts.append(
                        apisrv.frame_entry(ev_type, thunk, version,
                                           rv=rv)[idx])
                else:
                    parts.append(apisrv.frame_entry(tev.type, tev.object,
                                                    version)[idx])
            except Exception as e:  # undecodable payload: surface, keep going
                parts.append(apisrv.frame_entry(
                    watchpkg.ERROR,
                    errors.new_internal_error(str(e)).status, version)[idx])
        return parts, False

    def _stream_watch(self, watcher: watchpkg.Watcher, translate,
                      version: str, gate_tag: str = ""):
        """Chunked-JSON watch stream as a byte WRITER: this connection's
        thread drains raw store events in batches, maps them through the
        shared frame-bytes cache, and writes each batch with ONE send —
        no per-watcher pump thread, no per-watcher encode, one syscall
        per batch instead of four per event
        (ref: pkg/apiserver/watch.go:62-142).

        ``gate_tag`` (the ``chaosGate`` query param) names an optional
        chaos gate this writer parks on before draining: a test can hold
        ONE watcher's consumer still — deterministically growing the
        producer-side queue past lag_limit — while siblings stream
        freely. Untagged watchers never touch the seam."""
        from kubernetes_tpu.util import websocket as ws

        if ws.wants_websocket(self.headers):
            return self._stream_watch_websocket(watcher, translate, version)
        apisrv = self.server.api  # type: ignore[attr-defined]
        apisrv.track_watcher(watcher)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        if getattr(self, "_trace_ctx", None) is not None:
            # echo the stream's trace context so the client can stamp
            # frame-observation spans onto the same trace
            self.send_header(tracing.HEADER, tracing.wire(self._trace_ctx))
        self.end_headers()
        # fairshed: the admission slot covered the watch SETUP; the
        # long-lived stream itself must not pin an inflight slot (the
        # scheduler's reflectors live for the whole run — they would
        # permanently exhaust the system budget)
        ticket = getattr(self, "_fs_ticket", None)
        if ticket is not None:
            ticket.release()
        try:
            lagged = False
            while not lagged:
                if gate_tag:
                    chaos.gate_if_armed("apiserver.watch.write." + gate_tag)
                batch = watcher.next_batch(
                    linger=apisrv.watch_write_linger)
                if batch is None:
                    break
                t0 = time.monotonic()
                parts, lagged = self._translate_batch(batch, translate,
                                                      version, ws_frames=False)
                if parts:
                    apisrv.metric_fanout_frames.observe(len(parts))
                    self.wfile.write(b"".join(parts))
                    self.wfile.flush()
                    apisrv.metric_fanout_seconds.observe(
                        time.monotonic() - t0)
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            pass
        finally:
            watcher.stop()
            apisrv.untrack_watcher(watcher)
            self.close_connection = True

    def _stream_watch_websocket(self, watcher: watchpkg.Watcher, translate,
                                version: str):
        """Watch events as WebSocket text frames, one event per message,
        batches of cached frame bytes per send like the chunked variant
        (ref: pkg/apiserver/watch.go:62-126 — the websocket variant the
        reference serves alongside chunked JSON, negotiated by Upgrade)."""
        from kubernetes_tpu.util import websocket as ws

        apisrv = self.server.api  # type: ignore[attr-defined]
        apisrv.track_watcher(watcher)
        self.send_response_only(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", ws.accept_key(
            self.headers.get("Sec-WebSocket-Key", "")))
        if getattr(self, "_trace_ctx", None) is not None:
            self.send_header(tracing.HEADER, tracing.wire(self._trace_ctx))
        self.end_headers()
        # fairshed: release the admission slot at stream start, like the
        # chunked variant — a long-lived stream never pins inflight
        ticket = getattr(self, "_fs_ticket", None)
        if ticket is not None:
            ticket.release()

        # one writer lock: PONGs from the reader thread and event frames
        # from this thread interleave bytes otherwise (sendall is not
        # atomic once the TCP send buffer fills)
        wlock = threading.Lock()

        # client frames: PING -> PONG, CLOSE (or EOF) -> stop the watcher
        def reader():
            try:
                while True:
                    frame = ws.read_frame(self.rfile)
                    if frame is None or frame[0] == ws.OP_CLOSE:
                        break
                    if frame[0] == ws.OP_PING:
                        with wlock:
                            ws.send_pong(self.wfile, frame[1])
            except OSError:
                pass
            finally:
                watcher.stop()

        threading.Thread(target=reader, daemon=True,
                         name="ws-watch-reader").start()
        try:
            lagged = False
            while not lagged:
                batch = watcher.next_batch(
                    linger=apisrv.watch_write_linger)
                if batch is None:
                    break
                t0 = time.monotonic()
                parts, lagged = self._translate_batch(batch, translate,
                                                      version, ws_frames=True)
                if parts:
                    apisrv.metric_fanout_frames.observe(len(parts))
                    with wlock:
                        self.wfile.write(b"".join(parts))
                        self.wfile.flush()
                    apisrv.metric_fanout_seconds.observe(
                        time.monotonic() - t0)
            with wlock:
                ws.send_close(self.wfile)
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            pass
        finally:
            watcher.stop()
            apisrv.untrack_watcher(watcher)
            self.close_connection = True
        return 101

    # ----- proxy / redirect (ref: pkg/apiserver/{proxy,redirect}.go) -----

    def _handle_proxy_redirect(self, mode: str, version: str, rest, query,
                               user, method: str = "GET",
                               raw_body: bytes = b"") -> int:
        apisrv = self.server.api  # type: ignore[attr-defined]
        namespace = query.get("namespace", "")
        if rest and rest[0] == "namespaces" and len(rest) >= 3:
            namespace, rest = rest[1], rest[2:]
        if len(rest) < 2:
            raise errors.new_bad_request(f"{mode} needs /{{resource}}/{{name}}")
        resource, name, tail = rest[0], rest[1], rest[2:]
        location = apisrv.resource_location(resource, namespace, name, user)
        if location is None:
            raise errors.new_not_found(resource, name)
        target = f"http://{location}/" + "/".join(tail)
        # forward the original query pairs (ref: proxy.go) — repeated keys
        # (e.g. exec's cmd= argv) must survive verbatim
        fwd_pairs = [(k, v) for k, v in getattr(self, "_raw_query_pairs", [])
                     if k != "namespace"]
        if fwd_pairs:
            target += "?" + urllib.parse.urlencode(fwd_pairs)
        if mode == "redirect":
            self.send_response(307)
            self.send_header("Location", target)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return 307
        try:
            # forward the incoming method and body verbatim (ref: proxy.go
            # ServeHTTP builds the backend request from the original) — a bare
            # urlopen(target) would turn every proxied POST into a GET
            fwd = urllib.request.Request(
                target, data=raw_body if raw_body else None, method=method)
            ctype = self.headers.get("Content-Type")
            if ctype and raw_body:
                fwd.add_header("Content-Type", ctype)
            resp = urllib.request.urlopen(fwd, timeout=10)
        except urllib.error.HTTPError as e:
            resp = e  # backend errors relay verbatim (exec exit!=0 is a 500)
        except Exception as e:
            raise errors.new_internal_error(f"proxy to {target} failed: {e}")
        with resp:
            body = resp.read()
            status = resp.status if hasattr(resp, "status") else resp.code
            self.send_response(status)
            self.send_header("Content-Type",
                             resp.headers.get("Content-Type", "text/plain"))
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return status


class APIServer:
    """The serving front half of the master (ref: master.go:398-490 route
    installation + cmd/kube-apiserver). Wraps a Master with HTTP."""

    def __init__(self, master, host: str = "127.0.0.1", port: int = 0,
                 authenticator=None, request_log=None, ssl_context=None,
                 metrics_registry: Optional[metrics_pkg.Registry] = None,
                 node_locator=None, kubelet_port: int = 10250,
                 reuse_port: bool = False, cors_allowed_origins=(),
                 read_only: bool = False, rate_limiter=None,
                 watch_lag_limit: int = 65536, fairshed=None, share=None):
        self.master = master
        # kube-share cross-worker side channel (apiserver/share.py;
        # None on single-worker servers — zero cost): the write path
        # publishes every seeded encoding into this worker's ring, and
        # the fan-out's wire-cache misses drain sibling rings before
        # falling back to a local encode.
        self.share = share
        # kube-fairshed flow-classified admission (apiserver/fairshed.py;
        # None disables — zero cost on the request path). The binary
        # enables it by default; the overload harness adds the workload
        # backlog governor on top.
        self.fairshed = fairshed
        # per-HTTP-watcher queue bound: past it, modify events coalesce and
        # anything uncoalescible drops the watcher to resync (410 ERROR
        # frame + end-of-stream; the client re-lists). 0/None disables.
        # The queue holds shared StoreEvent references (bytes are only
        # rendered at write time), so the default is sized as a
        # stuck-watcher safety valve, NOT burst shedding: a commit wave
        # fanning thousands of events at a busy-but-draining consumer
        # (the scheduler's own reflectors) must ride the queue, while a
        # watcher minutes behind gets the 410 and re-lists.
        self.watch_lag_limit = watch_lag_limit or None
        # fan-out write linger: accumulate this long after a batch's
        # first event before draining+writing, so a steady event stream
        # costs each watcher one wakeup and one syscall per BATCH, not
        # per event (see Watcher.next_batch)
        self.watch_write_linger = 0.004
        # CORS origin allow-list, each entry a regex (ref: handlers.go CORS
        # + --cors_allowed_origins; empty list = CORS disabled)
        self.cors_patterns = [re.compile(p) for p in cors_allowed_origins]
        # the kubernetes-ro serving mode (ref: handlers.go ReadOnly +
        # RateLimit; wired by cmd/kube-apiserver onto --read_only_port):
        # GETs only, optionally throttled by a token bucket
        self.read_only = read_only
        self.rate_limiter = rate_limiter
        self.node_locator = node_locator
        self.kubelet_port = kubelet_port
        self.scheme = master.scheme
        self.versions = tuple(master.scheme.versions())
        self.default_version = master.scheme.default_version
        self.authenticator = authenticator
        self.request_log = request_log
        self.metrics_registry = metrics_registry or metrics_pkg.Registry()
        # ref: apiserver.go:40-61 request count + latency instrumentation
        self.metric_requests = self.metrics_registry.counter(
            "apiserver_request_count", "Counter of apiserver requests",
            ("verb", "resource", "client", "code"))
        self.metric_latency = self.metrics_registry.histogram(
            "apiserver_request_latencies_seconds", "Request latency",
            ("verb", "resource"), buckets=metrics_pkg.APISERVER_BUCKETS)
        # the apiserver hot-path family (docs/design/apiserver-hotpath.md):
        # frame-cache effectiveness, fan-out write batching, lag drops,
        # and the batch-bind endpoint's size/latency envelope
        self.metric_frame_hits = self.metrics_registry.counter(
            "apiserver_watch_frame_cache_hits_total",
            "Watch frame deliveries served from cached bytes "
            "(no object encode)")
        self.metric_frame_misses = self.metrics_registry.counter(
            "apiserver_watch_frame_cache_misses_total",
            "Watch frame deliveries that had to encode the object")
        self.metric_frame_seeds = self.metrics_registry.counter(
            "apiserver_watch_frame_seeds_total",
            "Frame-cache entries seeded by the write path "
            "(encode-once at commit)")
        self.metric_watch_lag_drops = self.metrics_registry.counter(
            "apiserver_watch_lag_drops_total",
            "Watch streams dropped to resync (410 ERROR frame) after "
            "exceeding the lag bound")
        self.metric_fanout_seconds = self.metrics_registry.histogram(
            "apiserver_watch_fanout_seconds",
            "Translate+write time per fan-out batch to one watcher",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                     0.025, 0.05, 0.1, 0.25, 1.0))
        self.metric_fanout_frames = self.metrics_registry.histogram(
            "apiserver_watch_write_frames",
            "Frames per fan-out write (write-coalescing depth)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128))
        self.metric_batch_bind_size = self.metrics_registry.histogram(
            "apiserver_batch_bind_size",
            "Bindings per bindings:batch request",
            buckets=(1, 4, 16, 64, 256, 1024, 4096))
        self.metric_batch_bind_seconds = self.metrics_registry.histogram(
            "apiserver_batch_bind_seconds",
            "bindings:batch handler latency",
            buckets=metrics_pkg.DEFAULT_BUCKETS)
        # cross-process cache seeding (apiserver/share.py): frames this
        # worker published for siblings, sibling frames imported into
        # the local wire cache, fan-out deliveries those imports saved
        # from encoding, and ring laps (lost optimisation records)
        self.metric_seed_published = self.metrics_registry.counter(
            "apiserver_cache_seed_published_total",
            "Seeded encodings published into this worker's share ring")
        self.metric_seed_imported = self.metrics_registry.counter(
            "apiserver_cache_seed_imported_total",
            "Sibling-published encodings imported into the wire cache")
        self.metric_seed_hits = self.metrics_registry.counter(
            "apiserver_cache_seed_hits_total",
            "Wire-cache misses resolved by draining sibling rings "
            "(an encode avoided by the cross-process feed)")
        self.metric_seed_ring_drops = self.metrics_registry.counter(
            "apiserver_cache_seed_ring_drops_total",
            "Ring records lost to reader lap (the sibling re-encodes; "
            "correctness unaffected)")
        # worker identity for SO_REUSEPORT fleet scrapes: a /metrics GET
        # lands on an arbitrary worker, so the harness keys its
        # per-worker disclosure on these two gauges
        self.metric_worker_pid = self.metrics_registry.gauge(
            "apiserver_worker_pid", "This worker process's pid")
        self.metric_worker_pid.set(float(os.getpid()))
        self.metric_worker_index = self.metrics_registry.gauge(
            "apiserver_worker_index",
            "Share-segment block index of this worker (-1 = standalone)")
        self.metric_worker_index.set(
            float(share.worker_index) if share is not None else -1.0)
        self._watchers: set = set()
        self._watch_lock = threading.Lock()
        # Encode-once fan-out caches (one lock guards both):
        #  _wire_cache:  (resourceVersion, wire version) -> the object's
        #      wire JSON string. The store's modified_index is globally
        #      unique per revision (and list responses never seed or
        #      fetch), making it a safe fan-out-wide key — the encode
        #      analog of StoreHelper's decode cache. Seeded by the write
        #      path (create/update responses, batch-bind commits) so the
        #      fan-out usually never encodes at all.
        #  _frame_cache: (resourceVersion, event type, wire version) ->
        #      (frame json str, chunked-transfer bytes, websocket frame
        #      bytes) assembled from the wire JSON — every watcher of any
        #      transport writes the same bytes. Both bounded FIFO.
        self._wire_cache: "OrderedDict" = OrderedDict()
        self._frame_cache: "OrderedDict" = OrderedDict()
        self._frame_lock = threading.Lock()
        # serializes sibling-ring drains (the per-process mmap cursors)
        self._share_drain_lock = threading.Lock()
        # (rv, version) -> Event: one fan-out thread encodes a revision,
        # concurrent watchers of the same event wait for its bytes
        # instead of burning the GIL on duplicate encodes
        self._encode_inflight: Dict[tuple, threading.Event] = {}
        self._httpd = ThreadingHTTPServer((host, port), _Handler,
                                          bind_and_activate=False)
        self._httpd.daemon_threads = True
        if reuse_port:
            # several worker processes share one listen port; the kernel
            # load-balances accepts (the multi-worker topology kube-store
            # exists for)
            self._httpd.socket.setsockopt(socket.SOL_SOCKET,
                                          socket.SO_REUSEPORT, 1)
        try:
            self._httpd.server_bind()
            self._httpd.server_activate()
        except BaseException:
            self._httpd.server_close()
            raise
        if ssl_context is not None:
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True)
        self._httpd.api = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def base_url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True, name="apiserver-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._watch_lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def retry_after_hint(self) -> int:
        """Whole-seconds Retry-After for the token-bucket 429 sites
        (read-only port): the limiter's own measured refill delay,
        clamped to [1, 30] — the hardcoded '1' these sites used to ship
        told a dry-bucket client to hammer a throttled port once per
        second forever."""
        rl = self.rate_limiter
        s = 1.0
        if rl is not None and hasattr(rl, "retry_after_s"):
            s = rl.retry_after_s()
        return max(1, min(30, int(-(-s // 1))))

    def is_resource(self, name: str) -> bool:
        try:
            self.master._registry(name)
            return True
        except Exception:
            return False

    # Sized for the lag depth the watch queues allow, not just the event
    # rate: a watcher thousands of events behind must still find the
    # bytes of the revisions it is draining, or every lagging stream
    # re-encodes history (an 8192-entry first cut churned exactly that
    # way at full shape). Entries are shared strings/bytes, ~1-3 KB each.
    _FRAME_CACHE_MAX = 32768
    _WIRE_CACHE_MAX = 65536

    @staticmethod
    def _rv_of(obj) -> str:
        from kubernetes_tpu.api.meta import accessor

        kind = getattr(obj, "kind", "") or type(obj).__name__
        if kind.endswith("List"):
            # a list's resourceVersion is a store INDEX, which an object's
            # modified_index can equal — lists never seed or fetch
            return ""
        try:
            return accessor.resource_version(obj)
        except Exception:
            return ""

    def seed_frame(self, obj, version: str, wire_json: str = "") -> None:
        """Seed the wire cache with one object's encoding — called by the
        WRITE path (create/update responses, batch-bind commits), where
        the bytes are being produced anyway, so the watch fan-out of the
        resulting store event is a pure byte copy (the 'serialize exactly
        once per (resourceVersion, api version)' contract)."""
        rv = self._rv_of(obj)
        if not rv:
            return
        key = (rv, version)
        with self._frame_lock:
            if key in self._wire_cache:
                return
        if not wire_json:
            try:
                wire_json = self.scheme.encode(obj, version)
            except Exception:
                return
        self.metric_frame_seeds.inc()
        with self._frame_lock:
            self._wire_cache[key] = wire_json
            while len(self._wire_cache) > self._WIRE_CACHE_MAX:
                self._wire_cache.popitem(last=False)
            waiter = self._encode_inflight.pop(key, None)
        if waiter is not None:
            waiter.set()  # wake fan-out threads parked on this revision
        if self.share is not None \
                and self.share.publish_frame(rv, version, wire_json):
            # the cross-process analog of the local seed: siblings'
            # fan-outs import these bytes instead of re-encoding
            self.metric_seed_published.inc()

    def encode_response(self, obj, version: str) -> str:
        """Encode a dispatch result for its HTTP response AND seed the
        frame cache with it (single objects only — see seed_frame)."""
        payload = self.scheme.encode(obj, version)
        self.seed_frame(obj, version, wire_json=payload)
        return payload

    @staticmethod
    def _assemble(ev_type: str, obj_json: str):
        """(frame json, chunked bytes, ws frame bytes) for one event —
        pure string/byte assembly, no codec work."""
        from kubernetes_tpu.util import websocket as ws

        frame = '{"type": "%s", "object": %s}' % (ev_type, obj_json)
        payload = frame.encode("utf-8")
        body = payload + b"\n"
        chunk = ("%x\r\n" % len(body)).encode("ascii") + body + b"\r\n"
        return frame, chunk, ws.text_frame(payload)

    _ENCODE_FALLBACK = ('{"kind": "Status", "status": "Failure", '
                        '"message": "encode error"}')

    def frame_entry(self, ev_type: str, obj, version: str,
                    rv: Optional[str] = None):
        """(frame json, chunked bytes, ws frame bytes) for one watch
        event, encoded at most once per (object revision, wire version)
        across every watcher and transport (ref: the reference encodes
        per watch connection, pkg/apiserver/watch.go:66 — here the encode
        is the fan-out hot path, so it is deduplicated). Concurrent
        watchers of one event rendezvous on an in-flight marker: one
        encodes, the rest wait for its bytes.

        ``obj`` may be a zero-arg thunk (the fast translate path passes
        ``rv`` explicitly and defers the decode): it is only called when
        the caches miss — a cache-hit delivery touches no codec."""
        lazy = callable(obj) and rv is not None
        if rv is None:
            rv = self._rv_of(obj)
        if not rv:
            # uncacheable payloads (Status objects in ERROR frames)
            try:
                return self._assemble(ev_type,
                                      self.scheme.encode(obj, version))
            except Exception:
                return self._assemble(ev_type, self._ENCODE_FALLBACK)
        fkey = (rv, ev_type, version)
        wkey = (rv, version)
        with self._frame_lock:
            entry = self._frame_cache.get(fkey)
            if entry is not None:
                self.metric_frame_hits.inc()
                return entry
            obj_json = self._wire_cache.get(wkey)
        if obj_json is None and self.share is not None:
            # before paying an encode (or parking on one), drain the
            # sibling rings: the worker that COMMITTED this revision
            # published its bytes at write time
            self._drain_share_seeds()
            with self._frame_lock:
                obj_json = self._wire_cache.get(wkey)
            if obj_json is not None:
                self.metric_seed_hits.inc()
        waiter = leader = None
        if obj_json is None:
            with self._frame_lock:
                obj_json = self._wire_cache.get(wkey)
                if obj_json is None:
                    waiter = self._encode_inflight.get(wkey)
                    if waiter is None:
                        leader = threading.Event()
                        self._encode_inflight[wkey] = leader
        if obj_json is None and waiter is not None:
            waiter.wait(timeout=2.0)
            with self._frame_lock:
                obj_json = self._wire_cache.get(wkey)
        if obj_json is None:
            if lazy:
                try:
                    obj = obj()
                except Exception:
                    # a DECODE failure must surface as an ERROR frame (the
                    # caller's contract), never as a typed frame wrapping a
                    # Status — release any waiters first
                    if leader is not None:
                        with self._frame_lock:
                            self._encode_inflight.pop(wkey, None)
                        leader.set()
                    raise
            try:
                obj_json = self.scheme.encode(obj, version)
            except Exception:
                # never cache the fallback: a transient encode failure must
                # not poison this revision for later watchers
                if leader is not None:
                    with self._frame_lock:
                        self._encode_inflight.pop(wkey, None)
                    leader.set()
                return self._assemble(ev_type, self._ENCODE_FALLBACK)
            self.metric_frame_misses.inc()
            with self._frame_lock:
                self._wire_cache[wkey] = obj_json
                while len(self._wire_cache) > self._WIRE_CACHE_MAX:
                    self._wire_cache.popitem(last=False)
        else:
            # assembled from cached/seeded wire JSON: the encode was avoided
            self.metric_frame_hits.inc()
        if leader is not None:
            with self._frame_lock:
                self._encode_inflight.pop(wkey, None)
            leader.set()
        entry = self._assemble(ev_type, obj_json)
        with self._frame_lock:
            self._frame_cache[fkey] = entry
            while len(self._frame_cache) > self._FRAME_CACHE_MAX:
                self._frame_cache.popitem(last=False)
        return entry

    def _drain_share_seeds(self) -> None:
        """Import sibling-published encodings (apiserver/share.py) into
        the local wire cache. Single-drainer: the mmap cursors are
        per-process state, so one thread drains while concurrent missers
        wait for its imports and then re-check the cache."""
        share = self.share
        if share is None:
            return
        if not self._share_drain_lock.acquire(blocking=False):
            with self._share_drain_lock:  # ride out the active drain
                return
        try:
            drops0 = share.ring_drops
            records = share.drain_frames()
            if share.ring_drops > drops0:
                self.metric_seed_ring_drops.inc(
                    by=share.ring_drops - drops0)
            if not records:
                return
            waiters = []
            with self._frame_lock:
                for rv, ver, wire_json in records:
                    key = (rv, ver)
                    if key in self._wire_cache:
                        continue
                    self._wire_cache[key] = wire_json
                    self.metric_seed_imported.inc()
                    w = self._encode_inflight.pop(key, None)
                    if w is not None:
                        waiters.append(w)
                while len(self._wire_cache) > self._WIRE_CACHE_MAX:
                    self._wire_cache.popitem(last=False)
            for w in waiters:
                w.set()
        finally:
            self._share_drain_lock.release()

    def event_frame(self, ev, version: str) -> str:
        """One JSON watch frame per (object revision, event type, wire
        version), shared across all watchers."""
        return self.frame_entry(ev.type, ev.object, version)[0]

    _LAG_STATUS = ('{"kind": "Status", "apiVersion": "%s", '
                   '"status": "Failure", "reason": "Expired", "code": 410, '
                   '"message": "watch lag bound exceeded; re-list required"}')

    def lag_resync_entry(self, version: str):
        """The bookmark-style drop-to-resync marker: a 410 Expired Status
        ERROR frame (pre-assembled per version)."""
        key = ("", "ERROR", version)
        with self._frame_lock:
            entry = self._frame_cache.get(key)
        if entry is None:
            entry = self._assemble("ERROR", self._LAG_STATUS % version)
            with self._frame_lock:
                self._frame_cache[key] = entry
        return entry

    def track_watcher(self, w) -> None:
        with self._watch_lock:
            self._watchers.add(w)

    def untrack_watcher(self, w) -> None:
        with self._watch_lock:
            self._watchers.discard(w)

    # -- deep health (ref: pkg/healthz + ComponentStatus) ------------------

    def health_components(self) -> Tuple[Dict[str, Any], bool]:
        """/healthz body: componentstatus-style per-dependency verdicts
        using the probe package's result vocabulary. Probes the two
        things this server cannot serve without: the backing store
        (in-process, durable, or a remote kube-store — one cheap list
        proves the round trip) and the watch hub (a subscribe+cancel
        proves the fan-out layer still accepts watchers)."""
        from kubernetes_tpu import probe

        items = []
        ok = True
        try:
            self.master.dispatch("list", "namespaces")
            items.append({"name": "store", "status": probe.SUCCESS,
                          "message": "list round-trip ok"})
        except Exception as e:
            items.append({"name": "store", "status": probe.FAILURE,
                          "message": repr(e)})
            ok = False
        # kube-chaos recovery disclosure (docs/design/ha.md): when the
        # backing store is an in-process DurableStore, /healthz carries
        # what the last crash recovery cost — replayed records, snapshot
        # age, torn-tail bytes, recovery wall time — so a respawned
        # apiserver proves "bounded recovery" instead of asserting it
        # (the remote-store topology discloses the same via kube-store's
        # own /healthz)
        recovery = getattr(self.master.store, "recovery", None)
        try:
            w, _translate = self.master.dispatch(
                "watch_raw", "namespaces", namespace="", label_selector="",
                field_selector="", resource_version="", user=None,
                lag_limit=16)
            w.stop()
            items.append({"name": "watch-hub", "status": probe.SUCCESS,
                          "message": "subscribe ok"})
        except Exception as e:
            items.append({"name": "watch-hub", "status": probe.FAILURE,
                          "message": repr(e)})
            ok = False
        payload: Dict[str, Any] = {"kind": "ComponentStatusList",
                                   "healthy": ok, "items": items}
        if recovery is not None:
            payload["recovery"] = dict(recovery)
        return payload, ok

    # -- kube-flightrec ----------------------------------------------------

    def flightrec_vars(self, since_ns: int = 0) -> Dict[str, Any]:
        """The /debug/vars shard. First pull arms the sampler (lazy, like
        the kube-trace ring) and registers this server's per-instance
        metrics Registry alongside the process default registry."""
        if not metrics_pkg.flightrec_armed():
            metrics_pkg.flightrec_arm(service="apiserver", sample=False)
        metrics_pkg.flightrec_watch(self.metrics_registry)
        if since_ns == 0:
            metrics_pkg.flightrec_sample_now()
        return metrics_pkg.flightrec_vars(since_ns)

    # -- cluster validation (ref: master.go:516-551) ----------------------

    def validate_components(self) -> Tuple[Dict[str, Any], bool]:
        statuses: Dict[str, Any] = {}
        ok = True
        try:
            self.master.dispatch("list", "namespaces")
            statuses["store"] = {"healthy": True}
        except Exception as e:
            statuses["store"] = {"healthy": False, "error": repr(e)}
            ok = False
        return statuses, ok

    # -- resource locations (ref: pod/rest.go, service/rest.go,
    #    minion ResourceLocation) -----------------------------------------

    def resource_location(self, resource: str, namespace: str, name: str,
                          user=None) -> Optional[str]:
        if resource in ("pods", "pod"):
            pod = self.master.dispatch("get", "pods", namespace=namespace,
                                       name=name, user=user)
            ip = getattr(pod.status, "pod_ip", "") or getattr(pod.status, "host", "")
            return ip or None
        if resource in ("services", "service"):
            eps = self.master.dispatch("get", "endpoints", namespace=namespace,
                                       name=name, user=user)
            endpoints = list(getattr(eps, "endpoints", []) or [])
            if not endpoints:
                return None
            # ref: service/rest.go ResourceLocation — pick an endpoint
            ep = endpoints[hash(name) % len(endpoints)]
            return f"{ep.ip}:{ep.port}"
        if resource in ("nodes", "minions", "node"):
            node = self.master.dispatch("get", "nodes", name=name, user=user)
            if node is None:
                return None
            if self.node_locator is not None:
                # harness/deployment hook: node name -> "host:port" of its
                # kubelet server (ref: minion registry ResourceLocation via
                # client.ConnectionInfoGetter)
                return self.node_locator(name)
            addrs = getattr(node.status, "addresses", []) or []
            host = addrs[0].address if addrs else node.metadata.name
            return f"{host}:{self.kubelet_port}"
        return None
