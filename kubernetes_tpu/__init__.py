"""kubernetes_tpu — a TPU-native cluster-orchestration framework.

A ground-up rebuild of the capabilities of early Kubernetes (reference:
smarterclayton/kubernetes, surveyed in SURVEY.md): a declarative object model
(pods / replication controllers / services / nodes / ...) over a versioned,
watchable store; level-triggered control loops; a pluggable admission/auth
pipeline; a node agent; a service proxy; and a CLI.

The defining departure from the reference is the scheduler: instead of the
serial per-pod predicate/priority loop
(reference: pkg/scheduler/generic_scheduler.go:54-128), the Filter and Score
phases are vmapped boolean-mask and score kernels over a dense
(pending_pods x nodes) tensor solved in one JAX/XLA call on TPU
(kubernetes_tpu.models.batch_solver), behind the same pluggable
predicate/priority registry and Binding write path, so the serial Python
implementation (kubernetes_tpu.scheduler.generic) remains a bit-identical
oracle.

Layer map (mirrors SURVEY.md section 1):
  L0 storage/        versioned KV + CAS + watch        (ref: pkg/tools)
  L1 api/, runtime/  object model, codecs, selectors   (ref: pkg/api, pkg/runtime)
  L2 registry/       per-resource storage logic        (ref: pkg/registry)
  L3 apiserver/      REST + watch + admission + auth   (ref: pkg/apiserver, pkg/master)
  L4 client/         typed client + list-watch caches  (ref: pkg/client)
  L5 scheduler/, controllers/  control loops           (ref: plugin/pkg/scheduler, pkg/controller)
  L6 kubelet/, proxy/ node agent + data plane          (ref: pkg/kubelet, pkg/proxy)
  L7 kubectl/        CLI                               (ref: pkg/kubectl)
  -- models/, ops/, parallel/  the TPU compute path (JAX/pallas/pjit)
"""

__version__ = "0.1.0"

# Race-probe hook — the -race build flag analog (ref: hack/test-go.sh:50).
# hack/test.sh --race exports KTPU_RACE=1; forcing a ~1us thread switch
# interval HERE (not only in the test harness) means every spawned
# component binary (storeserver, apiserver workers, scheduler) that
# imports this package runs under the same aggressive preemption, so
# server-side check-then-act races are probed too, not just the client
# half living in the pytest process. No-op unless KTPU_RACE is set.
import os as _os

if _os.environ.get("KTPU_RACE"):
    import sys as _sys

    _sys.setswitchinterval(1e-6)

    # Lock-order sanitizer (util/locksmith.py): armed in every process
    # that imports the package under --race, so spawned component
    # binaries probe their lock ordering too. A child has no pytest
    # sessionfinish hook, so cycles are reported at interpreter exit on
    # stderr (exit code untouched: the parent suite's own locksmith
    # run is the gating instance).
    import atexit as _atexit

    from kubernetes_tpu.util import locksmith as _locksmith

    _locksmith.arm()

    def _locksmith_exit_report() -> None:
        reps = _locksmith.reports()
        if reps:
            print("[locksmith] potential deadlocks in this process:",
                  file=_sys.stderr)
            for _r in reps:
                print(_locksmith.format_report(_r), file=_sys.stderr)

    _atexit.register(_locksmith_exit_report)
