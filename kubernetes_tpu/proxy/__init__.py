"""Service data plane — userspace L4 proxy (ref: pkg/proxy/).

- ``roundrobin`` — LoadBalancerRR endpoint selection with session affinity
- ``proxier``    — per-service listener sockets relaying to endpoints
- ``config``     — watch-driven service/endpoints config distribution
"""

from kubernetes_tpu.proxy.proxier import Proxier  # noqa: F401
from kubernetes_tpu.proxy.roundrobin import LoadBalancerRR  # noqa: F401
