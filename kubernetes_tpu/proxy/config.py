"""Watch-driven proxy configuration (ref: pkg/proxy/config/).

``ServiceConfig``/``EndpointsConfig`` watch the API and push full-state
updates into handlers (the Proxier and LoadBalancerRR OnUpdate hooks),
mirroring pkg/proxy/config/config.go's mux→merge→full-state-broadcast
design (handlers always receive the complete object set, never deltas).
"""

from __future__ import annotations

import threading
import traceback
from typing import Callable, List

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import Reflector, Store

__all__ = ["ServiceConfig", "EndpointsConfig"]


class _NotifyingStore(Store):
    """Store that flags an event on every mutation, so the broadcast pump
    wakes without polling (stands in for config.go's channel mux)."""

    def __init__(self, notify: threading.Event):
        super().__init__()
        self._notify_event = notify

    def add(self, obj):
        super().add(obj)
        self._notify_event.set()

    def update(self, obj):
        super().update(obj)
        self._notify_event.set()

    def delete(self, obj):
        super().delete(obj)
        self._notify_event.set()

    def replace(self, objs):
        super().replace(objs)
        self._notify_event.set()


class _WatchConfig:
    """List-watch a resource into a Store; on every change, hand the full
    object list to each registered handler."""

    def __init__(self, list_watch, handlers: List[Callable]):
        self._notify = threading.Event()
        self.store = _NotifyingStore(self._notify)
        self.handlers = list(handlers)
        self._lw = list_watch
        self._reflector = None
        self._stop = threading.Event()

    def run(self) -> "_WatchConfig":
        self._reflector = Reflector(self._lw, self.store,
                                    name=f"proxycfg-{type(self).__name__}")
        self._reflector.run()
        t = threading.Thread(target=self._pump, daemon=True,
                             name=f"proxycfg-{type(self).__name__}")
        t.start()
        return self

    def _pump(self) -> None:
        while not self._stop.is_set():
            if not self._notify.wait(timeout=0.5):
                continue
            self._notify.clear()
            objs = self.store.list()
            for h in self.handlers:
                try:
                    h(objs)
                except Exception:
                    # crash-only like the Reflector: a failing handler must
                    # not kill config distribution for every later update
                    traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        if self._reflector is not None:
            self._reflector.stop()


class ServiceConfig(_WatchConfig):
    """ref: config.go ServiceConfig — handlers get List[api.Service]."""

    def __init__(self, client, handlers: List[Callable]):
        super().__init__(client.services(api.NamespaceAll).list_watch(),
                         handlers)


class EndpointsConfig(_WatchConfig):
    """ref: config.go EndpointsConfig — handlers get List[api.Endpoints]."""

    def __init__(self, client, handlers: List[Callable]):
        super().__init__(client.endpoints(api.NamespaceAll).list_watch(),
                         handlers)
