"""Userspace L4 proxy (ref: pkg/proxy/proxier.go).

One listener socket per service; every accepted TCP connection is relayed
to an endpoint chosen by the load balancer (ref: tcpProxySocket.ProxyLoop
:91-151). UDP uses a single socket with a per-client activity map
(:166-266). Portal rules — the reference's iptables REDIRECT from
portalIP:port to the proxy port (:360-388) — go through the
``util.iptables`` seam so they're assertable without netfilter.

The reference spawns a goroutine per service + per connection; here each
service gets an accept thread and each connection a relay thread pair —
the same topology on OS threads (this is IO-bound; the GIL is released in
socket syscalls).
"""

from __future__ import annotations

import select
import socket
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import meta_namespace_key_func
from kubernetes_tpu.proxy.roundrobin import (ErrMissingEndpoints,
                                             ErrMissingServiceEntry,
                                             LoadBalancerRR)
from kubernetes_tpu.util import iptables as iptablespkg

__all__ = ["Proxier", "ServiceInfo"]

IPTABLES_PROXY_CHAIN = "KUBE-PROXY"  # ref: proxier.go iptablesProxyChain


@dataclass
class ServiceInfo:
    """ref: proxier.go serviceInfo."""

    name: str = ""                 # "namespace/name"
    portal_ip: str = ""
    portal_port: int = 0
    protocol: str = api.ProtocolTCP
    proxy_port: int = 0
    session_affinity: str = api.AffinityNone
    active: bool = True
    sock: Optional[socket.socket] = None
    thread: Optional[threading.Thread] = None


class _TCPProxy:
    """Accept loop + bidirectional relay (ref: tcpProxySocket :91-151)."""

    def __init__(self, proxier: "Proxier", info: ServiceInfo):
        self.proxier = proxier
        self.info = info

    def run(self) -> None:
        sock = self.info.sock
        while self.info.active:
            try:
                # select first: a close() from stop_proxy can't interrupt a
                # thread already blocked in accept(), and the blocked syscall
                # would keep the listening socket alive in the kernel
                ready, _, _ = select.select([sock], [], [], 0.5)
                if not ready:
                    continue
                client, addr = sock.accept()
            except (OSError, ValueError):
                return  # socket closed by stop_proxy
            try:
                backend = self.proxier.connect_to_backend(
                    self.info.name, addr[0], self.info.protocol)
            except (ErrMissingServiceEntry, ErrMissingEndpoints, OSError):
                client.close()
                continue
            t = threading.Thread(target=self._relay, args=(client, backend),
                                 daemon=True,
                                 name=f"proxy-conn-{self.info.name}")
            t.start()

    def _relay(self, client: socket.socket, backend: socket.socket) -> None:
        """io.Copy both ways (ref: proxyTCP :121-135). Idle connections are
        NOT killed — like the reference's io.Copy, only EOF/error ends the
        relay; the timeout exists solely to notice service shutdown."""
        socks = [client, backend]
        try:
            while True:
                readable, _, _ = select.select(socks, [], [], 5.0)
                if not readable:
                    if not self.info.active:
                        return
                    continue
                for s in readable:
                    other = backend if s is client else client
                    data = s.recv(65536)
                    if not data:
                        return
                    other.sendall(data)
        except OSError:
            pass
        finally:
            client.close()
            backend.close()


class _UDPProxy:
    """Single socket, per-client backend map with TTL
    (ref: udpProxySocket :166-266)."""

    CLIENT_TTL = 60.0  # ref: proxier.go udpIdleTimeout flag default scale

    def __init__(self, proxier: "Proxier", info: ServiceInfo):
        self.proxier = proxier
        self.info = info
        self.clients: Dict[Tuple[str, int], socket.socket] = {}
        self.last_seen: Dict[Tuple[str, int], float] = {}
        self.lock = threading.Lock()

    def run(self) -> None:
        sock = self.info.sock
        while self.info.active:
            try:
                ready, _, _ = select.select([sock], [], [], 0.5)
                if not ready:
                    continue
                data, addr = sock.recvfrom(65536)
            except (OSError, ValueError):
                break
            if addr is None:  # shutdown() makes recvfrom return (b'', None)
                break
            backend = self._backend_for(addr)
            if backend is None:
                continue
            try:
                backend.send(data)
            except OSError:
                with self.lock:
                    self.clients.pop(addr, None)
                    self.last_seen.pop(addr, None)
        self._close_all()

    def _backend_for(self, addr) -> Optional[socket.socket]:
        with self.lock:
            now = time.monotonic()
            sock = self.clients.get(addr)
            if sock is not None and \
                    now - self.last_seen.get(addr, 0) < self.CLIENT_TTL:
                self.last_seen[addr] = now
                return sock
            try:
                ep = self.proxier.lb.next_endpoint(self.info.name, addr[0])
            except (ErrMissingServiceEntry, ErrMissingEndpoints):
                return None
            host, _, port = ep.rpartition(":")
            try:
                backend = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                backend.connect((host, int(port)))
            except OSError:
                return None
            self.clients[addr] = backend
            self.last_seen[addr] = now
            t = threading.Thread(target=self._pump_back,
                                 args=(addr, backend), daemon=True)
            t.start()
            return backend

    def _pump_back(self, addr, backend: socket.socket) -> None:
        while self.info.active:
            try:
                backend.settimeout(self.CLIENT_TTL)
                data = backend.recv(65536)
            except OSError:
                break
            if not data:
                break
            try:
                self.info.sock.sendto(data, addr)
            except OSError:
                break
        with self.lock:
            if self.clients.get(addr) is backend:
                del self.clients[addr]
                self.last_seen.pop(addr, None)
        backend.close()

    def _close_all(self):
        with self.lock:
            for s in self.clients.values():
                s.close()
            self.clients.clear()
            self.last_seen.clear()


class Proxier:
    """ref: proxier.go Proxier — OnUpdate is the full-state service config
    hook; SyncLoop re-ensures portal rules periodically."""

    def __init__(self, lb: Optional[LoadBalancerRR] = None,
                 listen_ip: str = "127.0.0.1",
                 iptables: Optional[iptablespkg.IPTables] = None,
                 sync_period: float = 5.0):
        self.lb = lb or LoadBalancerRR()
        self.listen_ip = listen_ip
        self.iptables = iptables or iptablespkg.FakeIPTables()
        self.sync_period = sync_period
        self.service_map: Dict[str, ServiceInfo] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._init_iptables()

    # -- portal rules ------------------------------------------------------
    def _init_iptables(self) -> None:
        """ref: proxier.go iptablesInit:330-358."""
        ipt = self.iptables
        ipt.ensure_chain(iptablespkg.TableNAT, IPTABLES_PROXY_CHAIN)
        ipt.ensure_rule(iptablespkg.TableNAT, iptablespkg.ChainPrerouting,
                        "-j", IPTABLES_PROXY_CHAIN)
        ipt.ensure_rule(iptablespkg.TableNAT, iptablespkg.ChainOutput,
                        "-j", IPTABLES_PROXY_CHAIN)

    def _portal_args(self, info: ServiceInfo) -> tuple:
        """ref: proxier.go iptablesPortalArgs:390-423."""
        return ("-m", info.protocol.lower(),
                "-p", info.protocol.lower(),
                "-d", f"{info.portal_ip}/32",
                "--dport", str(info.portal_port),
                "-j", "REDIRECT", "--to-ports", str(info.proxy_port))

    def open_portal(self, info: ServiceInfo) -> None:
        """ref: proxier.go openPortal."""
        if info.portal_ip:
            self.iptables.ensure_rule(iptablespkg.TableNAT,
                                      IPTABLES_PROXY_CHAIN,
                                      *self._portal_args(info))

    def close_portal(self, info: ServiceInfo) -> None:
        if info.portal_ip:
            self.iptables.delete_rule(iptablespkg.TableNAT,
                                      IPTABLES_PROXY_CHAIN,
                                      *self._portal_args(info))

    def ensure_portals(self) -> None:
        """Reinstall portal rules for every known service
        (ref: proxier.go ensurePortals:375-388, called from SyncLoop)."""
        with self._lock:
            for info in self.service_map.values():
                self.open_portal(info)

    def sync_loop(self) -> None:
        """ref: proxier.go SyncLoop:360-373."""
        while not self._stopped.wait(self.sync_period):
            self.ensure_portals()
            self.clean_stale_sessions()

    def clean_stale_sessions(self) -> None:
        with self._lock:
            names = list(self.service_map)
        for name in names:
            self.lb.clean_up_stale_sessions(name)

    # -- proxy socket management ------------------------------------------
    def connect_to_backend(self, service: str, src_ip: str,
                           protocol: str) -> socket.socket:
        """Dial an endpoint with one retry through the balancer
        (ref: tcpProxySocket.ProxyLoop retry over sessionAffinity reset)."""
        last_err: Optional[Exception] = None
        for attempt in range(2):
            ep = self.lb.next_endpoint(service, src_ip,
                                       reset_affinity=attempt > 0)
            host, _, port = ep.rpartition(":")
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.settimeout(5.0)
            try:
                s.connect((host, int(port)))
                s.settimeout(None)
                return s
            except OSError as e:
                s.close()
                last_err = e
        raise last_err

    def add_service_on_port(self, name: str, protocol: str,
                            proxy_port: int = 0) -> ServiceInfo:
        """Open a local listener for a service
        (ref: proxier.go addServiceOnPort:425-451)."""
        if protocol == api.ProtocolUDP:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.listen_ip, proxy_port))
        if protocol != api.ProtocolUDP:
            sock.listen(128)
        info = ServiceInfo(name=name, protocol=protocol,
                           proxy_port=sock.getsockname()[1], sock=sock)
        runner = _UDPProxy(self, info) if protocol == api.ProtocolUDP \
            else _TCPProxy(self, info)
        info.thread = threading.Thread(target=runner.run, daemon=True,
                                       name=f"proxy-{name}")
        info.thread.start()
        return info

    def stop_proxy(self, info: ServiceInfo) -> None:
        info.active = False
        if info.sock is not None:
            try:
                # shutdown wakes a thread blocked in accept() and makes the
                # kernel refuse new connections immediately even while the
                # accept thread still holds the file open
                info.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                info.sock.close()
            except OSError:
                pass

    # -- config hook -------------------------------------------------------
    def on_update(self, services: List[api.Service]) -> None:
        """Full-state service list (ref: proxier.go OnUpdate:467-530):
        start proxies for new services, restart on portal changes, stop
        proxies for removed services."""
        with self._lock:
            active: set = set()
            for svc in services:
                name = meta_namespace_key_func(svc)
                active.add(name)
                info = self.service_map.get(name)
                if info is not None and \
                        info.portal_ip == svc.spec.portal_ip and \
                        info.portal_port == svc.spec.port and \
                        info.protocol == svc.spec.protocol:
                    if info.session_affinity != svc.spec.session_affinity:
                        # affinity change needs no socket restart, just a
                        # balancer update (ref: proxier.go updates lb state
                        # from serviceInfo on every OnUpdate pass)
                        info.session_affinity = svc.spec.session_affinity
                        self.lb.new_service(name, svc.spec.session_affinity)
                    continue
                if info is not None:
                    self.close_portal(info)
                    self.stop_proxy(info)
                info = self.add_service_on_port(name, svc.spec.protocol)
                info.portal_ip = svc.spec.portal_ip
                info.portal_port = svc.spec.port
                info.session_affinity = svc.spec.session_affinity
                self.service_map[name] = info
                self.lb.new_service(name, svc.spec.session_affinity)
                self.open_portal(info)
            for name in list(self.service_map):
                if name not in active:
                    info = self.service_map.pop(name)
                    self.close_portal(info)
                    self.stop_proxy(info)

    def proxy_port_of(self, namespace: str, name: str) -> Optional[int]:
        info = self.service_map.get(f"{namespace}/{name}")
        return info.proxy_port if info else None

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            for info in self.service_map.values():
                self.close_portal(info)
                self.stop_proxy(info)
            self.service_map.clear()
