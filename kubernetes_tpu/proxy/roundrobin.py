"""Round-robin load balancer with session affinity
(ref: pkg/proxy/roundrobin.go).

``LoadBalancerRR`` keeps, per service, the endpoint list and a rotating
index; ``next_endpoint(service, src_ip)`` returns the next endpoint, or the
affinitized one when the service has ClientIP session affinity and the
client was seen within the TTL (ref: roundrobin.go affinityState /
LoadBalancerRR.NextEndpoint:54-118).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import meta_namespace_key_func

__all__ = ["LoadBalancerRR", "ErrMissingServiceEntry", "ErrMissingEndpoints"]


class ErrMissingServiceEntry(Exception):
    pass


class ErrMissingEndpoints(Exception):
    pass


@dataclass
class _AffinityState:
    """ref: roundrobin.go affinityState{clientIP, endpoint, lastUsed}."""

    endpoint: str = ""
    last_used: float = 0.0


@dataclass
class _BalancerState:
    endpoints: List[str] = field(default_factory=list)
    index: int = 0
    affinity_type: str = api.AffinityNone
    ttl_seconds: float = 180 * 60  # ref: proxier.go newServiceInfo default
    affinity_map: Dict[str, _AffinityState] = field(default_factory=dict)


class LoadBalancerRR:
    """ref: roundrobin.go LoadBalancerRR."""

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._services: Dict[str, _BalancerState] = {}
        self._clock = clock

    def new_service(self, service: str, affinity_type: str = api.AffinityNone,
                    ttl_seconds: float = 0.0) -> None:
        """ref: roundrobin.go NewService."""
        with self._lock:
            state = self._services.setdefault(service, _BalancerState())
            state.affinity_type = affinity_type
            if ttl_seconds > 0:
                state.ttl_seconds = ttl_seconds

    def next_endpoint(self, service: str, src_ip: str = "",
                      reset_affinity: bool = False) -> str:
        """ref: roundrobin.go NextEndpoint:54-118. ``reset_affinity`` drops
        the client's sticky entry first — the dial-retry path uses it so a
        dead affinitized endpoint doesn't pin the client forever
        (ref: proxier.go sessionAffinityReset in TryConnectEndpoints)."""
        with self._lock:
            state = self._services.get(service)
            if state is None:
                raise ErrMissingServiceEntry(service)
            if not state.endpoints:
                raise ErrMissingEndpoints(service)
            use_affinity = (state.affinity_type == api.AffinityClientIP
                            and src_ip)
            if use_affinity and reset_affinity:
                state.affinity_map.pop(src_ip, None)
            if use_affinity and not reset_affinity:
                sess = state.affinity_map.get(src_ip)
                now = self._clock()
                if sess is not None and \
                        now - sess.last_used < state.ttl_seconds and \
                        sess.endpoint in state.endpoints:
                    sess.last_used = now
                    return sess.endpoint
            endpoint = state.endpoints[state.index]
            state.index = (state.index + 1) % len(state.endpoints)
            if use_affinity:
                state.affinity_map[src_ip] = _AffinityState(
                    endpoint=endpoint, last_used=self._clock())
            return endpoint

    def on_update(self, endpoints_list: List[api.Endpoints]) -> None:
        """Full-state endpoints update (ref: roundrobin.go OnUpdate:122-168):
        registered services missing from the update lose their endpoints;
        changed endpoint sets reset the rotation and purge stale affinity."""
        with self._lock:
            seen = set()
            for ep in endpoints_list:
                name = meta_namespace_key_func(ep)
                seen.add(name)
                eps = [f"{e.ip}:{e.port}" for e in ep.endpoints]
                state = self._services.setdefault(name, _BalancerState())
                if sorted(eps) != sorted(state.endpoints):
                    state.endpoints = eps
                    state.index = 0
                    for ip, sess in list(state.affinity_map.items()):
                        if sess.endpoint not in eps:
                            del state.affinity_map[ip]
            for name, state in self._services.items():
                if name not in seen:
                    state.endpoints = []
                    state.index = 0

    def clean_up_stale_sessions(self, service: str) -> None:
        """ref: roundrobin.go removeStaleAffinity."""
        with self._lock:
            state = self._services.get(service)
            if state is None:
                return
            now = self._clock()
            for ip, sess in list(state.affinity_map.items()):
                if now - sess.last_used >= state.ttl_seconds:
                    del state.affinity_map[ip]

    def endpoints_of(self, service: str) -> List[str]:
        with self._lock:
            state = self._services.get(service)
            return list(state.endpoints) if state else []
