"""API object validation (ref: pkg/api/validation/validation.go).

Pure functions returning a list of ValidationError; empty list = valid.
Key entry points mirror the reference: validate_pod, validate_service,
validate_replication_controller, validate_node, validate_namespace.
``accumulate_unique_host_ports`` is shared with the kubelet's on-node
admission (ref: pkg/kubelet/kubelet.go:1706).
"""

from __future__ import annotations

import re
from typing import List, Optional, Set, Tuple

from kubernetes_tpu import capabilities
from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api

__all__ = [
    "ValidationError",
    "validate_object_meta",
    "validate_pod",
    "validate_pod_update",
    "validate_service",
    "validate_replication_controller",
    "validate_node",
    "validate_namespace",
    "validate_event",
    "validate_priority_class",
    "accumulate_unique_host_ports",
    "is_dns1123_label",
    "is_dns1123_subdomain",
]

_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_C_IDENTIFIER = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


class ValidationError(Exception):
    def __init__(self, etype: str, field: str, value=None, detail: str = ""):
        self.type = etype
        self.field = field
        self.value = value
        self.detail = detail
        msg = f"{field}: {etype}"
        if value not in (None, ""):
            msg += f" {value!r}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def _required(field):
    return ValidationError("required value", field)


def _invalid(field, value, detail=""):
    return ValidationError("invalid value", field, value, detail)


def _duplicate(field, value):
    return ValidationError("duplicate value", field, value)


def _unsupported(field, value, detail=""):
    return ValidationError("unsupported value", field, value, detail)


def is_dns1123_label(s: str) -> bool:
    return len(s) <= 63 and bool(_DNS1123_LABEL.match(s))


def is_dns1123_subdomain(s: str) -> bool:
    return len(s) <= 253 and bool(_DNS1123_SUBDOMAIN.match(s))


def validate_labels(lbls, field) -> List[ValidationError]:
    errs = []
    for k, v in (lbls or {}).items():
        if not labels_pkg.validate_label_key(k):
            errs.append(_invalid(f"{field}.{k}", k, "invalid label key"))
        if not labels_pkg.validate_label_value(v):
            errs.append(_invalid(f"{field}.{k}", v, "invalid label value"))
    return errs


def validate_object_meta(meta: api.ObjectMeta, namespaced: bool, name_fn=None,
                         field: str = "metadata") -> List[ValidationError]:
    """ref: validation.go ValidateObjectMeta."""
    errs: List[ValidationError] = []
    if not meta.name and not meta.generate_name:
        errs.append(_required(f"{field}.name"))
    elif meta.name and not is_dns1123_subdomain(meta.name):
        errs.append(_invalid(f"{field}.name", meta.name, "must be a DNS subdomain"))
    if name_fn and meta.name:
        errs.extend(name_fn(meta.name, f"{field}.name"))
    if namespaced:
        if not meta.namespace:
            errs.append(_required(f"{field}.namespace"))
        elif not is_dns1123_label(meta.namespace):
            errs.append(_invalid(f"{field}.namespace", meta.namespace, "must be a DNS label"))
    elif meta.namespace:
        errs.append(_invalid(f"{field}.namespace", meta.namespace,
                             "namespace is not allowed on this type"))
    errs.extend(validate_labels(meta.labels, f"{field}.labels"))
    return errs


def accumulate_unique_host_ports(containers: List[api.Container],
                                 accumulator: Optional[Set[Tuple[int, str]]] = None
                                 ) -> List[ValidationError]:
    """ref: validation.go AccumulateUniquePorts / checkHostPortConflicts —
    also reused by the scheduler predicate (pkg/scheduler/predicates.go:326)
    and the kubelet (pkg/kubelet/kubelet.go:1706)."""
    errs: List[ValidationError] = []
    ports = accumulator if accumulator is not None else set()
    for ci, c in enumerate(containers):
        for pi, p in enumerate(c.ports):
            if not p.host_port:
                continue
            key = (p.host_port, p.protocol or api.ProtocolTCP)
            if key in ports:
                errs.append(_duplicate(f"spec.containers[{ci}].ports[{pi}].hostPort", p.host_port))
            ports.add(key)
    return errs


def _validate_volumes(volumes: List[api.Volume]) -> Tuple[Set[str], List[ValidationError]]:
    errs: List[ValidationError] = []
    names: Set[str] = set()
    for i, v in enumerate(volumes or []):
        fld = f"spec.volumes[{i}]"
        if not v.name:
            errs.append(_required(f"{fld}.name"))
        elif not is_dns1123_label(v.name):
            errs.append(_invalid(f"{fld}.name", v.name, "must be a DNS label"))
        elif v.name in names:
            errs.append(_duplicate(f"{fld}.name", v.name))
        names.add(v.name)
        src = v.source
        set_sources = [s for s in (src.empty_dir, src.host_path, src.gce_persistent_disk,
                                   src.git_repo, src.secret, src.nfs) if s is not None]
        if len(set_sources) > 1:
            errs.append(_invalid(f"{fld}.source", None, "exactly one volume source may be set"))
    return names, errs


def _validate_containers(containers: List[api.Container], volume_names: Set[str]
                         ) -> List[ValidationError]:
    errs: List[ValidationError] = []
    if not containers:
        return [_required("spec.containers")]
    names: Set[str] = set()
    for i, c in enumerate(containers):
        fld = f"spec.containers[{i}]"
        if not c.name:
            errs.append(_required(f"{fld}.name"))
        elif not is_dns1123_label(c.name):
            errs.append(_invalid(f"{fld}.name", c.name, "must be a DNS label"))
        elif c.name in names:
            errs.append(_duplicate(f"{fld}.name", c.name))
        names.add(c.name)
        if not c.image:
            errs.append(_required(f"{fld}.image"))
        if c.privileged and not capabilities.get().allow_privileged:
            # ref: validation.go:612-613 — privileged mode is a per-binary
            # capability (--allow_privileged), off by default
            errs.append(ValidationError(
                "forbidden", f"{fld}.privileged", True,
                "privileged mode is disallowed (start with --allow-privileged)"))
        port_names: Set[str] = set()
        for pi, p in enumerate(c.ports):
            pfld = f"{fld}.ports[{pi}]"
            if p.name:
                if not is_dns1123_label(p.name):
                    errs.append(_invalid(f"{pfld}.name", p.name))
                elif p.name in port_names:
                    errs.append(_duplicate(f"{pfld}.name", p.name))
                port_names.add(p.name)
            if not (0 < p.container_port < 65536):
                errs.append(_invalid(f"{pfld}.containerPort", p.container_port))
            if p.host_port and not (0 < p.host_port < 65536):
                errs.append(_invalid(f"{pfld}.hostPort", p.host_port))
            if p.protocol and p.protocol not in (api.ProtocolTCP, api.ProtocolUDP):
                errs.append(_unsupported(f"{pfld}.protocol", p.protocol))
        for ei, e in enumerate(c.env):
            if not e.name:
                errs.append(_required(f"{fld}.env[{ei}].name"))
            elif not _C_IDENTIFIER.match(e.name):
                errs.append(_invalid(f"{fld}.env[{ei}].name", e.name))
        for mi, m in enumerate(c.volume_mounts):
            mfld = f"{fld}.volumeMounts[{mi}]"
            if not m.name:
                errs.append(_required(f"{mfld}.name"))
            elif m.name not in volume_names:
                errs.append(ValidationError("not found", f"{mfld}.name", m.name))
            if not m.mount_path:
                errs.append(_required(f"{mfld}.mountPath"))
    errs.extend(accumulate_unique_host_ports(containers))
    return errs


def validate_pod_spec(spec: api.PodSpec) -> List[ValidationError]:
    volume_names, errs = _validate_volumes(spec.volumes)
    errs.extend(_validate_containers(spec.containers, volume_names))
    if spec.restart_policy not in (api.RestartPolicyAlways, api.RestartPolicyOnFailure,
                                   api.RestartPolicyNever):
        errs.append(_unsupported("spec.restartPolicy", spec.restart_policy))
    if spec.dns_policy not in (api.DNSClusterFirst, api.DNSDefault):
        errs.append(_unsupported("spec.dnsPolicy", spec.dns_policy))
    errs.extend(validate_labels(spec.node_selector, "spec.nodeSelector"))
    if spec.priority_class_name and \
            not is_dns1123_subdomain(spec.priority_class_name):
        errs.append(_invalid("spec.priorityClassName",
                             spec.priority_class_name,
                             "must be a DNS subdomain"))
    if spec.priority is not None and \
            spec.priority > api.HighestUserDefinablePriority:
        errs.append(_invalid("spec.priority", spec.priority,
                             "must not exceed the highest user-definable "
                             f"priority ({api.HighestUserDefinablePriority})"))
    if spec.preemption_policy not in ("", api.PreemptLowerPriority,
                                      api.PreemptNever):
        errs.append(_unsupported("spec.preemptionPolicy",
                                 spec.preemption_policy))
    return errs


def validate_pod(pod: api.Pod) -> List[ValidationError]:
    """ref: validation.go ValidatePod."""
    errs = validate_object_meta(pod.metadata, namespaced=True)
    errs.extend(validate_pod_spec(pod.spec))
    return errs


def validate_pod_update(new: api.Pod, old: api.Pod) -> List[ValidationError]:
    """ref: validation.go ValidatePodUpdate — spec is mostly immutable; only
    container image updates are allowed in the reference."""
    errs: List[ValidationError] = []
    if new.metadata.name != old.metadata.name or new.metadata.namespace != old.metadata.namespace:
        errs.append(_invalid("metadata.name", new.metadata.name, "may not be changed"))
    ns, os_ = new.spec, old.spec
    if len(ns.containers) != len(os_.containers):
        errs.append(_invalid("spec.containers", None, "may not add or remove containers"))
        return errs
    # Whole-container equality with image masked out: everything except the
    # image is immutable (ref: validation.go ValidatePodUpdate copies
    # containers and overwrites Image before DeepEqual).
    import dataclasses as _dc

    for nc, oc in zip(ns.containers, os_.containers):
        if _dc.replace(nc, image=oc.image) != oc:
            errs.append(_invalid("spec.containers", nc.name,
                                 "only container image updates are allowed"))
            break
    if ns.host != os_.host and os_.host:
        errs.append(_invalid("spec.host", ns.host, "may not be changed once set"))
    return errs


def validate_service(svc: api.Service) -> List[ValidationError]:
    """ref: validation.go ValidateService."""
    def name_fn(name, field):
        return [] if is_dns1123_label(name) else [_invalid(field, name, "must be a DNS label")]

    errs = validate_object_meta(svc.metadata, namespaced=True, name_fn=name_fn)
    if not (0 < svc.spec.port < 65536):
        errs.append(_invalid("spec.port", svc.spec.port))
    if svc.spec.protocol and svc.spec.protocol not in (api.ProtocolTCP, api.ProtocolUDP):
        errs.append(_unsupported("spec.protocol", svc.spec.protocol))
    if svc.spec.session_affinity not in (api.AffinityNone, api.AffinityClientIP):
        errs.append(_unsupported("spec.sessionAffinity", svc.spec.session_affinity))
    errs.extend(validate_labels(svc.spec.selector, "spec.selector"))
    return errs


def validate_replication_controller(rc: api.ReplicationController) -> List[ValidationError]:
    """ref: validation.go ValidateReplicationController."""
    errs = validate_object_meta(rc.metadata, namespaced=True)
    if rc.spec.replicas < 0:
        errs.append(_invalid("spec.replicas", rc.spec.replicas, "must be non-negative"))
    if not rc.spec.selector:
        errs.append(_required("spec.selector"))
    tmpl = rc.spec.template
    if tmpl is None:
        if rc.spec.replicas > 0:
            errs.append(_required("spec.template"))
    else:
        sel = rc.spec.selector or {}
        tl = tmpl.metadata.labels or {}
        if any(tl.get(k) != v for k, v in sel.items()):
            errs.append(_invalid("spec.template.metadata.labels", tl,
                                 "selector does not match template labels"))
        errs.extend(validate_pod_spec(tmpl.spec))
        if tmpl.spec.restart_policy != api.RestartPolicyAlways:
            errs.append(_unsupported("spec.template.spec.restartPolicy",
                                     tmpl.spec.restart_policy,
                                     "replicated pods must have RestartPolicy=Always"))
    return errs


def validate_node(node: api.Node) -> List[ValidationError]:
    errs = validate_object_meta(node.metadata, namespaced=False)
    for k, q in (node.spec.capacity or {}).items():
        if q.value < 0:
            errs.append(_invalid(f"spec.capacity.{k}", str(q), "must be non-negative"))
    return errs


def validate_namespace(ns: api.Namespace) -> List[ValidationError]:
    def name_fn(name, field):
        return [] if is_dns1123_label(name) else [_invalid(field, name, "must be a DNS label")]

    return validate_object_meta(ns.metadata, namespaced=False, name_fn=name_fn)


def validate_priority_class(pc: api.PriorityClass) -> List[ValidationError]:
    """kube-preempt: PriorityClass is cluster-scoped; value is a bounded
    int32 (the upstream user-definable ceiling), the preemption policy an
    enum. The at-most-one-globalDefault invariant is enforced by the
    registry (it needs the stored set)."""
    def name_fn(name, field):
        return [] if is_dns1123_subdomain(name) else \
            [_invalid(field, name, "must be a DNS subdomain")]

    errs = validate_object_meta(pc.metadata, namespaced=False,
                                name_fn=name_fn)
    if not isinstance(pc.value, int) or isinstance(pc.value, bool):
        errs.append(_invalid("value", pc.value, "must be an integer"))
    elif not (-(1 << 31) <= pc.value <= api.HighestUserDefinablePriority):
        errs.append(_invalid(
            "value", pc.value,
            "must be an int32 no greater than the highest user-definable "
            f"priority ({api.HighestUserDefinablePriority})"))
    if pc.preemption_policy not in (api.PreemptLowerPriority,
                                    api.PreemptNever):
        errs.append(_unsupported("preemptionPolicy", pc.preemption_policy))
    return errs


def validate_event(ev: api.Event) -> List[ValidationError]:
    """ref: validation.go ValidateEvent — event namespace must match the
    involved object's namespace."""
    errs: List[ValidationError] = []
    if ev.involved_object.namespace and ev.metadata.namespace != ev.involved_object.namespace:
        errs.append(_invalid("involvedObject.namespace", ev.involved_object.namespace,
                             "does not match event namespace"))
    return errs
