"""Generic object metadata access + kind<->resource mapping.

ref: pkg/api/meta/ — ``Accessor`` for generic ObjectMeta access and
``RESTMapper`` mapping kind <-> resource name <-> scope.
"""

from __future__ import annotations

from typing import Any, Optional

from kubernetes_tpu.api import types as api

__all__ = ["accessor", "RESTMapper", "default_rest_mapper"]


class _Accessor:
    """Uniform access to metadata on any API object (ref: meta.Accessor)."""

    def metadata(self, obj: Any) -> api.ObjectMeta:
        m = getattr(obj, "metadata", None)
        if not isinstance(m, api.ObjectMeta):
            raise TypeError(f"object of type {type(obj).__name__} has no ObjectMeta")
        return m

    def name(self, obj: Any) -> str:
        return self.metadata(obj).name

    def namespace(self, obj: Any) -> str:
        return self.metadata(obj).namespace

    def uid(self, obj: Any) -> str:
        return self.metadata(obj).uid

    def resource_version(self, obj: Any) -> str:
        m = getattr(obj, "metadata", None)
        return getattr(m, "resource_version", "") or ""

    def set_resource_version(self, obj: Any, rv: str) -> None:
        m = getattr(obj, "metadata", None)
        if m is not None:
            m.resource_version = rv

    def labels(self, obj: Any) -> dict:
        return self.metadata(obj).labels or {}

    def kind(self, obj: Any) -> str:
        return getattr(obj, "kind", "") or type(obj).__name__


accessor = _Accessor()


class RESTMapper:
    """kind <-> resource-name <-> scope mapping (ref: pkg/api/meta/restmapper.go)."""

    def __init__(self):
        # resource -> (kind name, type, namespaced)
        self._by_resource = {}
        self._by_kind = {}

    def add(self, resource: str, kind: str, obj_type: type, namespaced: bool = True,
            list_type: Optional[type] = None, aliases: tuple = ()):
        entry = (resource, kind, obj_type, namespaced, list_type)
        self._by_resource[resource] = entry
        self._by_kind[kind] = entry
        for a in aliases:
            self._by_resource[a] = entry

    def resource_for(self, kind: str) -> str:
        return self._by_kind[kind][0]

    def kind_for(self, resource: str) -> str:
        return self._by_resource[resource.lower()][1]

    def type_for(self, resource: str) -> type:
        return self._by_resource[resource.lower()][2]

    def list_type_for(self, resource: str) -> Optional[type]:
        return self._by_resource[resource.lower()][4]

    def is_namespaced(self, resource: str) -> bool:
        return self._by_resource[resource.lower()][3]

    def resources(self):
        return sorted({e[0] for e in self._by_resource.values()})

    def has_resource(self, resource: str) -> bool:
        return resource.lower() in self._by_resource


def default_rest_mapper() -> RESTMapper:
    m = RESTMapper()
    m.add("pods", "Pod", api.Pod, True, api.PodList, aliases=("pod", "po"))
    m.add("replicationcontrollers", "ReplicationController", api.ReplicationController, True,
          api.ReplicationControllerList, aliases=("replicationcontroller", "rc"))
    m.add("services", "Service", api.Service, True, api.ServiceList, aliases=("service", "svc"))
    m.add("endpoints", "Endpoints", api.Endpoints, True, api.EndpointsList)
    m.add("nodes", "Node", api.Node, False, api.NodeList, aliases=("node", "minions", "minion"))
    m.add("namespaces", "Namespace", api.Namespace, False, api.NamespaceList,
          aliases=("namespace", "ns"))
    m.add("bindings", "Binding", api.Binding, True, api.BindingList)
    m.add("events", "Event", api.Event, True, api.EventList, aliases=("event", "ev"))
    m.add("secrets", "Secret", api.Secret, True, api.SecretList, aliases=("secret",))
    m.add("limitranges", "LimitRange", api.LimitRange, True, api.LimitRangeList,
          aliases=("limitrange", "limits"))
    m.add("resourcequotas", "ResourceQuota", api.ResourceQuota, True, api.ResourceQuotaList,
          aliases=("resourcequota", "quota"))
    m.add("priorityclasses", "PriorityClass", api.PriorityClass, False,
          api.PriorityClassList, aliases=("priorityclass", "pc"))
    m.add("bindings", "Binding", api.Binding, True, None)
    return m
