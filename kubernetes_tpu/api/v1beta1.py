"""v1beta1 — the legacy wire API, structurally divergent from v1.

ref: pkg/api/v1beta1/{types,conversion,defaults}.go. The reference shipped
v1beta1/v1beta2 (flat metadata, desiredState/currentState envelopes,
manifest-nested pod specs, object-shaped restart policies, "Minion" for
Node, "podID" on bindings, "ip:port" endpoint strings) side by side with
the nested-metadata v1beta3 that became v1. This module gives our "v1"
internal model that same genuinely-restructured sibling so the conversion
engine is proven against a REAL divergent format, not a field-rename toy:

- metadata flattens to the top level with ``name`` spelled ``id``;
- Pod/PodTemplate specs nest under ``desiredState.manifest`` with the
  restart policy as a one-of object (``{"always": {}}``), status under
  ``currentState`` with phase spelled ``status`` and container statuses
  as ``info``;
- ReplicationController uses ``desiredState.{replicas,replicaSelector,
  podTemplate}``;
- Service flattens its spec to the top level;
- Node rides the wire as kind ``Minion`` with capacity under
  ``resources.capacity``;
- Endpoints carry ``"ip:port"`` strings plus a parallel ``targetRefs``;
- Binding names its pod ``podID``;
- Namespace/ResourceQuota/LimitRange hoist their specs.

Every transform is exactly invertible (fuzz: tests/test_serialization.py
asserts internal -> v1beta1 wire -> internal identity over randomized
objects of every kind), decode applies the era's defaulting pass, and
field labels convert per version (``DesiredState.Host`` <->
``spec.host``, ref: pkg/api/v1beta1/conversion.go field-label funcs).

v1beta1 additionally carries the era's *deprecated wire aliases*, which
are exactly what distinguishes it from its v1beta2 sibling in the
reference (v1beta2 is the same envelope shape minus the aliases):

- ``EnvVar.key`` — deprecated duplicate of ``name``; encode writes both,
  decode prefers ``name`` and falls back to ``key``
  (ref: pkg/api/v1beta1/conversion.go:114-129, absent from v1beta2);
- ``VolumeMount.path``/``mountType`` — deprecated aliases of
  ``mountPath``; decode falls back to ``path``
  (ref: pkg/api/v1beta1/conversion.go:131-149);
- ``MinionList.minions`` — duplicate of ``items`` on the wire; decode
  prefers ``items`` (ref: pkg/api/v1beta1/conversion.go:151-196
  "MinionList.Items had a wrong name in v1beta1").

The transform registry is built by :func:`make_kind_transforms` so the
v1beta2 module can instantiate the shared envelope with
``legacy_aliases=False`` and its own manifest version stamp.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

__all__ = ["KIND_TRANSFORMS", "KIND_ALIASES", "DEFAULTERS",
           "FIELD_LABELS", "encode_for", "decode_for",
           "make_kind_transforms"]


# -- metadata flattening (name is spelled "id") ------------------------------

_META_FLAT = (
    ("name", "id"),
    ("namespace", "namespace"),
    ("uid", "uid"),
    ("resourceVersion", "resourceVersion"),
    ("creationTimestamp", "creationTimestamp"),
    ("deletionTimestamp", "deletionTimestamp"),
    ("selfLink", "selfLink"),
    ("labels", "labels"),
    ("annotations", "annotations"),
    ("generateName", "generateName"),
)


def _meta_out(wire: dict) -> dict:
    wire = dict(wire)
    meta = wire.pop("metadata", None)
    if isinstance(meta, dict):
        for internal_name, beta_name in _META_FLAT:
            if internal_name in meta:
                wire[beta_name] = meta[internal_name]
    return wire


def _meta_in(wire: dict) -> dict:
    wire = dict(wire)
    meta = {}
    for internal_name, beta_name in _META_FLAT:
        if beta_name in wire:
            meta[internal_name] = wire.pop(beta_name)
    if meta:
        wire["metadata"] = meta
    return wire


def _move(d: dict, src: str, dst: dict, dst_key: str) -> None:
    if src in d:
        dst[dst_key] = d.pop(src)


# -- pod spec <-> desiredState.manifest --------------------------------------

# restartPolicy: string <-> one-of object (ref: v1beta1 RestartPolicy
# {Always *RestartPolicyAlways, ...})
_POLICY_OUT = {"Always": "always", "OnFailure": "onFailure", "Never": "never"}
_POLICY_IN = {v: k for k, v in _POLICY_OUT.items()}


def _containers_alias_out(containers: list) -> list:
    """Write the v1beta1-only deprecated duplicates: EnvVar.key mirrors
    name, VolumeMount.path mirrors mountPath (ref: v1beta1/conversion.go
    EnvVar/VolumeMount funcs; v1beta2 dropped both fields)."""
    out = []
    for c in containers:
        if not isinstance(c, dict):
            out.append(c)
            continue
        c = dict(c)
        env = c.get("env")
        if isinstance(env, list):
            c["env"] = [dict(e, key=e["name"])
                        if isinstance(e, dict) and e.get("name") else e
                        for e in env]
        vms = c.get("volumeMounts")
        if isinstance(vms, list):
            c["volumeMounts"] = [dict(v, path=v["mountPath"])
                                 if isinstance(v, dict) and v.get("mountPath")
                                 else v for v in vms]
        out.append(c)
    return out


def _containers_alias_in(containers: list) -> list:
    """Accept the deprecated aliases: key -> name, path -> mountPath;
    mountType is ignored (ref: v1beta1/conversion.go "MountType is
    ignored")."""
    out = []
    for c in containers:
        if not isinstance(c, dict):
            out.append(c)
            continue
        c = dict(c)
        env = c.get("env")
        if isinstance(env, list):
            fixed = []
            for e in env:
                if isinstance(e, dict):
                    e = dict(e)
                    key = e.pop("key", None)
                    if not e.get("name") and key:
                        e["name"] = key
                fixed.append(e)
            c["env"] = fixed
        vms = c.get("volumeMounts")
        if isinstance(vms, list):
            fixed = []
            for v in vms:
                if isinstance(v, dict):
                    v = dict(v)
                    path = v.pop("path", None)
                    v.pop("mountType", None)
                    if not v.get("mountPath") and path:
                        v["mountPath"] = path
                fixed.append(v)
            c["volumeMounts"] = fixed
        out.append(c)
    return out


def _podspec_out(spec: dict, version: str = "v1beta1",
                 legacy: bool = True) -> dict:
    spec = dict(spec)
    manifest: dict = {"version": version}
    for k, mk in (("containers", "containers"), ("volumes", "volumes"),
                  ("dnsPolicy", "dnsPolicy"), ("hostNetwork", "hostNetwork"),
                  ("terminationGracePeriodSeconds",
                   "terminationGracePeriodSeconds")):
        _move(spec, k, manifest, mk)
    if legacy and isinstance(manifest.get("containers"), list):
        manifest["containers"] = _containers_alias_out(manifest["containers"])
    rp = spec.pop("restartPolicy", None)
    if rp is not None:
        manifest["restartPolicy"] = {_POLICY_OUT.get(rp, "always"): {}}
    out: dict = {"manifest": manifest}
    _move(spec, "host", out, "host")
    _move(spec, "nodeSelector", out, "nodeSelector")
    out.update(spec)  # forward-compat: unknown spec fields ride along
    return out


def _podspec_in(ds: dict, legacy: bool = True) -> dict:
    ds = dict(ds)
    spec: dict = {}
    manifest = dict(ds.pop("manifest", {}) or {})
    manifest.pop("version", None)
    manifest.pop("id", None)
    if legacy and isinstance(manifest.get("containers"), list):
        manifest["containers"] = _containers_alias_in(manifest["containers"])
    rp = manifest.pop("restartPolicy", None)
    if isinstance(rp, dict) and rp:
        spec["restartPolicy"] = _POLICY_IN.get(next(iter(rp)), "Always")
    spec.update(manifest)
    _move(ds, "host", spec, "host")
    _move(ds, "nodeSelector", spec, "nodeSelector")
    spec.update(ds)
    return spec


def _podstatus_out(status: dict) -> dict:
    cs = dict(status)
    out: dict = {}
    _move(cs, "phase", out, "status")          # phase is spelled "status"
    _move(cs, "containerStatuses", out, "info")
    out.update(cs)
    return out


def _podstatus_in(cs: dict) -> dict:
    cs = dict(cs)
    status: dict = {}
    _move(cs, "status", status, "phase")
    _move(cs, "info", status, "containerStatuses")
    status.update(cs)
    return status


def _pod_out(wire: dict, version: str = "v1beta1",
             legacy: bool = True) -> dict:
    wire = _meta_out(wire)
    if "spec" in wire:
        wire["desiredState"] = _podspec_out(wire.pop("spec"), version, legacy)
    if "status" in wire:
        wire["currentState"] = _podstatus_out(wire.pop("status"))
    return wire


def _pod_in(wire: dict, legacy: bool = True) -> dict:
    wire = _meta_in(wire)
    if "desiredState" in wire:
        wire["spec"] = _podspec_in(wire.pop("desiredState"), legacy)
    if "currentState" in wire:
        wire["status"] = _podstatus_in(wire.pop("currentState"))
    return wire


# -- replication controller --------------------------------------------------

def _template_out(t: dict, version: str = "v1beta1",
                  legacy: bool = True) -> dict:
    t = _meta_out(t)  # template metadata flattens like any object's
    if "spec" in t:
        t["desiredState"] = _podspec_out(t.pop("spec"), version, legacy)
    return t


def _template_in(t: dict, legacy: bool = True) -> dict:
    t = _meta_in(t)
    if "desiredState" in t:
        t["spec"] = _podspec_in(t.pop("desiredState"), legacy)
    return t


def _rc_out(wire: dict, version: str = "v1beta1",
            legacy: bool = True) -> dict:
    wire = _meta_out(wire)
    spec = dict(wire.pop("spec", {}) or {})
    ds: dict = {}
    _move(spec, "replicas", ds, "replicas")
    _move(spec, "selector", ds, "replicaSelector")
    if "template" in spec:
        ds["podTemplate"] = _template_out(spec.pop("template"), version,
                                          legacy)
    ds.update(spec)
    wire["desiredState"] = ds
    if "status" in wire:
        wire["currentState"] = wire.pop("status")
    return wire


def _rc_in(wire: dict, legacy: bool = True) -> dict:
    wire = _meta_in(wire)
    ds = dict(wire.pop("desiredState", {}) or {})
    spec: dict = {}
    _move(ds, "replicas", spec, "replicas")
    _move(ds, "replicaSelector", spec, "selector")
    if "podTemplate" in ds:
        spec["template"] = _template_in(ds.pop("podTemplate"), legacy)
    spec.update(ds)
    wire["spec"] = spec
    if "currentState" in wire:
        wire["status"] = wire.pop("currentState")
    return wire


# -- service: spec flattened to the top level --------------------------------

_SVC_FLAT = ("port", "protocol", "selector", "portalIp",
             "createExternalLoadBalancer", "publicIps", "containerPort",
             "sessionAffinity")


def _service_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    spec = dict(wire.pop("spec", {}) or {})
    # only the shared _SVC_FLAT keys move — both directions are driven by
    # the one table, so a new ServiceSpec field fails loudly in round-trip
    # fuzz instead of silently flattening out but never restoring
    for k in _SVC_FLAT:
        _move(spec, k, wire, k)
    if spec:
        wire["spec"] = spec  # unmapped spec fields stay nested (lossless)
    wire.pop("status", None)  # ServiceStatus is empty in this era
    return wire


def _service_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    spec = dict(wire.pop("spec", {}) or {})
    for k in _SVC_FLAT:
        if k in wire:
            spec[k] = wire.pop(k)
    wire["spec"] = spec
    return wire


# -- node (wire kind "Minion"): resources envelope ---------------------------

_NODE_FLAT = ("podCidr", "externalId", "unschedulable")


def _node_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    spec = dict(wire.pop("spec", {}) or {})
    if "capacity" in spec:
        wire["resources"] = {"capacity": spec.pop("capacity")}
    for k in _NODE_FLAT:
        _move(spec, k, wire, k)
    if spec:
        wire["spec"] = spec  # unmapped spec fields stay nested (lossless)
    return wire


def _node_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    spec = dict(wire.pop("spec", {}) or {})
    res = wire.pop("resources", None)
    if isinstance(res, dict) and "capacity" in res:
        spec["capacity"] = res["capacity"]
    for k in _NODE_FLAT:
        if k in wire:
            spec[k] = wire.pop(k)
    wire["spec"] = spec
    return wire


# -- endpoints: "ip:port" strings + parallel targetRefs ----------------------

def _endpoints_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    eps = wire.pop("endpoints", None)
    if isinstance(eps, list):
        flat, refs = [], []
        for i, e in enumerate(eps):
            addr = f"{e.get('ip', '')}:{e.get('port', 0)}"
            flat.append(addr)
            if e.get("targetRef") is not None:
                # positional pairing: several endpoints may share ip:port
                # (distinct target pods behind one address), so refs keyed
                # by address would collide and corrupt on decode
                refs.append({"endpoint": addr, "i": i,
                             "target": e["targetRef"]})
        wire["endpoints"] = flat
        if refs:
            wire["targetRefs"] = refs
    return wire


def _endpoints_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    eps = wire.pop("endpoints", None)
    refs = {r["i"]: r.get("target")
            for r in wire.pop("targetRefs", []) or [] if "i" in r}
    if isinstance(eps, list):
        out = []
        for i, addr in enumerate(eps):
            ip, _, port = str(addr).rpartition(":")
            e = {"ip": ip, "port": int(port or 0)}
            if i in refs:
                e["targetRef"] = refs[i]
            out.append(e)
        wire["endpoints"] = out
    return wire


# -- binding: podID ----------------------------------------------------------

def _binding_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    _move(wire, "podName", wire, "podID")
    return wire


def _binding_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    _move(wire, "podID", wire, "podName")
    return wire


# -- namespace / quota / limitrange: hoisted specs ---------------------------

def _namespace_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    spec = dict(wire.pop("spec", {}) or {})
    _move(spec, "finalizers", wire, "finalizers")
    status = dict(wire.pop("status", {}) or {})
    _move(status, "phase", wire, "phase")
    return wire


def _namespace_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    if "finalizers" in wire:
        wire["spec"] = {"finalizers": wire.pop("finalizers")}
    if "phase" in wire:
        wire["status"] = {"phase": wire.pop("phase")}
    return wire


def _quota_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    spec = dict(wire.pop("spec", {}) or {})
    _move(spec, "hard", wire, "hard")
    if "status" in wire:
        wire["currentStatus"] = wire.pop("status")
    return wire


def _quota_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    if "hard" in wire:
        wire["spec"] = {"hard": wire.pop("hard")}
    if "currentStatus" in wire:
        wire["status"] = wire.pop("currentStatus")
    return wire


def _limitrange_out(wire: dict) -> dict:
    wire = _meta_out(wire)
    spec = dict(wire.pop("spec", {}) or {})
    _move(spec, "limits", wire, "limits")
    return wire


def _limitrange_in(wire: dict) -> dict:
    wire = _meta_in(wire)
    if "limits" in wire:
        wire["spec"] = {"limits": wire.pop("limits")}
    return wire


# -- registry ----------------------------------------------------------------

WireFn = Callable[[dict], dict]


def make_kind_transforms(manifest_version: str = "v1beta1",
                         legacy_aliases: bool = True,
                         ) -> Dict[str, Tuple[WireFn, WireFn]]:
    """Build the kind -> (encode, decode) registry for one legacy wire
    version. v1beta1 = ("v1beta1", True); the v1beta2 sibling shares the
    whole envelope shape but stamps its own manifest version and drops
    the deprecated aliases (ref: pkg/api/v1beta2/ is v1beta1 minus
    EnvVar.Key / VolumeMount.Path / MinionList.Minions)."""
    v, leg = manifest_version, legacy_aliases
    reg: Dict[str, Tuple[WireFn, WireFn]] = {
        "Pod": (lambda w: _pod_out(w, v, leg),
                lambda w: _pod_in(w, leg)),
        "ReplicationController": (lambda w: _rc_out(w, v, leg),
                                  lambda w: _rc_in(w, leg)),
        "Service": (_service_out, _service_in),
        "Node": (_node_out, _node_in),
        "Endpoints": (_endpoints_out, _endpoints_in),
        "Binding": (_binding_out, _binding_in),
        "Namespace": (_namespace_out, _namespace_in),
        "ResourceQuota": (_quota_out, _quota_in),
        "LimitRange": (_limitrange_out, _limitrange_in),
        # flat-metadata-only kinds
        "Event": (_meta_out, _meta_in),
        "Secret": (_meta_out, _meta_in),
        "Status": (lambda w: w, lambda w: w),
        "DeleteOptions": (lambda w: w, lambda w: w),
    }
    if legacy_aliases:
        # MinionList carries a duplicate "minions" field on the wire;
        # decode prefers "items" and falls back to "minions"
        # (ref: v1beta1/conversion.go "MinionList.Items had a wrong name")
        node_out, node_in = reg["Node"]

        def _nodelist_out(wire: dict) -> dict:
            wire = _list_out(node_out, wire)
            if isinstance(wire.get("items"), list):
                wire["minions"] = wire["items"]
            return wire

        def _nodelist_in(wire: dict) -> dict:
            wire = dict(wire)
            minions = wire.pop("minions", None)
            if "items" not in wire and isinstance(minions, list):
                wire["items"] = minions
            return _list_in(node_in, wire)

        reg["NodeList"] = (_nodelist_out, _nodelist_in)
    return reg


def _list_out(item: WireFn, wire: dict) -> dict:
    wire = _meta_out(wire)
    items = wire.get("items")
    if isinstance(items, list):
        wire["items"] = [item(i) if isinstance(i, dict) else i
                         for i in items]
    return wire


def _list_in(item: WireFn, wire: dict) -> dict:
    wire = _meta_in(wire)
    items = wire.get("items")
    if isinstance(items, list):
        wire["items"] = [item(i) if isinstance(i, dict) else i
                         for i in items]
    return wire


# kind -> (encode internal-wire -> v1beta1-wire, decode back)
KIND_TRANSFORMS: Dict[str, Tuple[WireFn, WireFn]] = make_kind_transforms()

# v1beta1 wire kind -> internal kind (ref: Node was "Minion" on the wire)
KIND_ALIASES: Dict[str, str] = {"Minion": "Node", "MinionList": "NodeList"}


def encode_for(kind: str, registry: Dict[str, Tuple[WireFn, WireFn]]
               = KIND_TRANSFORMS) -> WireFn:
    """Encoder for a kind, deriving List transforms from the item kind."""
    if kind in registry:
        return registry[kind][0]
    if kind.endswith("List") and kind[:-4] in registry:
        item = registry[kind[:-4]][0]
        return lambda wire: _list_out(item, wire)
    return _meta_out


def decode_for(kind: str, registry: Dict[str, Tuple[WireFn, WireFn]]
               = KIND_TRANSFORMS) -> WireFn:
    if kind in registry:
        return registry[kind][1]
    if kind.endswith("List") and kind[:-4] in registry:
        item = registry[kind[:-4]][1]
        return lambda wire: _list_in(item, wire)
    return _meta_in


# -- defaulting (ref: pkg/api/v1beta1/defaults.go) ---------------------------

def _default_pod(pod) -> None:
    if not pod.spec.restart_policy:
        pod.spec.restart_policy = "Always"
    if not pod.spec.dns_policy:
        pod.spec.dns_policy = "ClusterFirst"
    for c in pod.spec.containers:
        for p in c.ports:
            if not p.protocol:
                p.protocol = "TCP"
            # with host networking, unset host ports default to the
            # container port (ref: v1beta1/defaults.go:112-121
            # defaultHostNetworkPorts; v1beta2/defaults.go:114-123 is
            # code-identical — only its comment claims the reverse)
            if pod.spec.host_network and not p.host_port:
                p.host_port = p.container_port


def _default_service(svc) -> None:
    if not svc.spec.protocol:
        svc.spec.protocol = "TCP"
    if not svc.spec.session_affinity:
        svc.spec.session_affinity = "None"


def _default_endpoints(eps) -> None:
    if not eps.protocol:
        eps.protocol = "TCP"


# kind -> defaulter(obj); applied on decode of that version's wire
DEFAULTERS: Dict[str, Callable] = {
    "Pod": _default_pod,
    "Service": _default_service,
    "Endpoints": _default_endpoints,
}


# -- field-label conversion (ref: v1beta1/conversion.go field-label funcs) ---

_POD_FIELDS = {
    "DesiredState.Host": "spec.host",
    "DesiredState.Status": "status.phase",
    "Status.Phase": "status.phase",
    "id": "metadata.name",
}
_NODE_FIELDS = {"id": "metadata.name", "unschedulable": "spec.unschedulable"}
_GENERIC_FIELDS = {"id": "metadata.name"}


def _label_fn(mapping):
    def convert(label: str, value: str) -> Tuple[str, str]:
        return mapping.get(label, label), value
    return convert


# kind -> fn(label, value) -> (internal label, value)
FIELD_LABELS: Dict[str, Callable[[str, str], Tuple[str, str]]] = {
    "Pod": _label_fn(_POD_FIELDS),
    "Node": _label_fn(_NODE_FIELDS),
    "Service": _label_fn(_GENERIC_FIELDS),
    "ReplicationController": _label_fn(_GENERIC_FIELDS),
    "Event": _label_fn(_GENERIC_FIELDS),
}
