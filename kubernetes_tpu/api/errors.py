"""API status errors (ref: pkg/api/errors/errors.go).

Every API failure is represented as a ``Status`` object; these exception
classes carry one and map to HTTP status codes in the apiserver layer
(ref: pkg/apiserver/errors.go).
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api import types as api

__all__ = [
    "StatusError",
    "new_not_found",
    "new_already_exists",
    "new_conflict",
    "new_invalid",
    "new_bad_request",
    "new_unauthorized",
    "new_forbidden",
    "new_method_not_supported",
    "new_internal_error",
    "is_not_found",
    "is_already_exists",
    "is_conflict",
    "is_invalid",
    "from_status",
]


class StatusError(Exception):
    """An error that is also an api.Status (ref: errors.go StatusError)."""

    def __init__(self, status: api.Status):
        super().__init__(status.message)
        self.status = status

    @property
    def reason(self) -> str:
        return self.status.reason

    @property
    def code(self) -> int:
        return self.status.code


def _status(code: int, reason: str, message: str, details: Optional[api.StatusDetails] = None):
    return StatusError(
        api.Status(
            status=api.StatusFailure, code=code, reason=reason, message=message, details=details
        )
    )


def new_not_found(kind: str, name: str) -> StatusError:
    return _status(
        404,
        api.ReasonNotFound,
        f'{kind} "{name}" not found',
        api.StatusDetails(name=name, kind=kind),
    )


def new_already_exists(kind: str, name: str) -> StatusError:
    return _status(
        409,
        api.ReasonAlreadyExists,
        f'{kind} "{name}" already exists',
        api.StatusDetails(name=name, kind=kind),
    )


def new_conflict(kind: str, name: str, message: str = "") -> StatusError:
    return _status(
        409,
        api.ReasonConflict,
        message or f'{kind} "{name}" cannot be updated: the object has been modified',
        api.StatusDetails(name=name, kind=kind),
    )


def new_invalid(kind: str, name: str, errs) -> StatusError:
    causes = [
        api.StatusCause(reason=api.ReasonInvalid, message=str(e), field_path=getattr(e, "field", ""))
        for e in (errs or [])
    ]
    return _status(
        422,
        api.ReasonInvalid,
        f'{kind} "{name}" is invalid: ' + "; ".join(str(e) for e in (errs or [])),
        api.StatusDetails(name=name, kind=kind, causes=causes),
    )


def new_bad_request(message: str) -> StatusError:
    return _status(400, api.ReasonBadRequest, message)


def new_unauthorized(message: str = "not authorized") -> StatusError:
    return _status(401, api.ReasonUnauthorized, message)


def new_forbidden(kind: str, name: str, message: str = "") -> StatusError:
    return _status(403, api.ReasonForbidden, message or f'{kind} "{name}" is forbidden')


def new_method_not_supported(kind: str, action: str) -> StatusError:
    return _status(405, api.ReasonMethodNotAllowed, f"{action} is not supported on resources of kind {kind}")


def new_internal_error(message: str) -> StatusError:
    return _status(500, api.ReasonInternalError, message)


def new_too_many_requests(message: str = "rate limit exceeded",
                          retry_after_s: int = 0) -> StatusError:
    """ref: handlers.go RateLimit — the read-only port's 429, grown a
    ``retry_after_s`` hint (kube-fairshed): the same number the
    Retry-After header carries also rides ``details.retryAfterSeconds``
    so JSON clients that never see response headers (error bodies
    decoded through from_status) can still honor it."""
    details = api.StatusDetails(retry_after_seconds=int(retry_after_s)) \
        if retry_after_s else None
    return _status(429, api.ReasonTooManyRequests, message, details)


def new_expired(message: str) -> StatusError:
    """410 Gone — the requested resourceVersion fell out of the watch window
    (ref: errors.go NewResourceExpired); clients respond by relisting."""
    return _status(410, api.ReasonExpired, message)


def is_resource_expired(e: BaseException) -> bool:
    return isinstance(e, StatusError) and e.reason == api.ReasonExpired


def from_status(status: api.Status) -> StatusError:
    return StatusError(status)


def is_not_found(e: BaseException) -> bool:
    return isinstance(e, StatusError) and e.reason == api.ReasonNotFound


def is_already_exists(e: BaseException) -> bool:
    return isinstance(e, StatusError) and e.reason == api.ReasonAlreadyExists


def is_conflict(e: BaseException) -> bool:
    return isinstance(e, StatusError) and e.reason == api.ReasonConflict


def is_invalid(e: BaseException) -> bool:
    return isinstance(e, StatusError) and e.reason == api.ReasonInvalid
