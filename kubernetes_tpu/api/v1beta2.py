"""v1beta2 — the second legacy wire version: v1beta1's envelope without
the deprecated aliases.

ref: pkg/api/v1beta2/{types,conversion,defaults}.go. In the reference,
v1beta2 is a near-copy of v1beta1 that shipped side by side with it: the
same flat metadata/``id``, desiredState/currentState envelopes,
manifest-nested pod specs, one-of restart policies, ``Minion`` wire kind,
``podID`` bindings and ``ip:port`` endpoints — but with the era's
deprecated duplicate fields *removed*:

- no ``EnvVar.key`` (v1beta1 writes it as a duplicate of ``name``;
  v1beta2/types.go has no Key field — the v1beta1-only conversion is
  pkg/api/v1beta1/conversion.go:114-129);
- no ``VolumeMount.path``/``mountType`` (v1beta1/conversion.go:131-149);
- no ``MinionList.minions`` duplicate of ``items``
  (v1beta1/conversion.go:151-196);
- manifests stamp ``version: v1beta2``.

Defaulting is code-identical to v1beta1 (diff of the two defaults.go
files shows only a comment divergence over defaultHostNetworkPorts), so
the DEFAULTERS/FIELD_LABELS/KIND_ALIASES registries are shared. What
this module proves is the *version lifecycle*: three wire formats
registered simultaneously, each decodable, with cross-version
conversion through the internal form (the kube-version-change path).
"""

from __future__ import annotations

from typing import Dict, Tuple

from kubernetes_tpu.api import v1beta1 as _beta1
from kubernetes_tpu.api.v1beta1 import (DEFAULTERS, FIELD_LABELS,
                                        KIND_ALIASES, WireFn)

__all__ = ["KIND_TRANSFORMS", "KIND_ALIASES", "DEFAULTERS",
           "FIELD_LABELS", "encode_for", "decode_for"]

# same envelope, no legacy aliases, own manifest stamp
KIND_TRANSFORMS: Dict[str, Tuple[WireFn, WireFn]] = \
    _beta1.make_kind_transforms("v1beta2", legacy_aliases=False)


def encode_for(kind: str) -> WireFn:
    return _beta1.encode_for(kind, KIND_TRANSFORMS)


def decode_for(kind: str) -> WireFn:
    return _beta1.decode_for(kind, KIND_TRANSFORMS)
