"""Field selectors (ref: pkg/fields/).

Select objects by field values, e.g. ``spec.host=`` selects unscheduled pods
(used by the scheduler's unassigned-pod reflector, ref:
plugin/pkg/scheduler/factory/factory.go:177). Only equality / inequality are
supported, mirroring the reference (pkg/fields/selector.go ParseSelector).
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["FieldSelector", "parse_field_selector", "everything"]


class FieldSelector:
    __slots__ = ("requirements",)

    def __init__(self, requirements=None):
        # list of (field, op, value) with op in {"=", "!="}
        self.requirements = list(requirements or [])

    def matches(self, fields: Dict[str, str]) -> bool:
        for field, op, value in self.requirements:
            has = field in fields
            if op == "=":
                if not has or fields[field] != value:
                    return False
            elif op == "!=":
                if has and fields[field] == value:
                    return False
            else:
                raise ValueError(f"invalid operator {op!r}")
        return True

    def empty(self) -> bool:
        return not self.requirements

    def __str__(self) -> str:
        return ",".join(f"{f}{'=' if op == '=' else '!='}{v}" for f, op, v in self.requirements)

    def __eq__(self, other):
        return isinstance(other, FieldSelector) and sorted(self.requirements) == sorted(
            other.requirements
        )


def everything() -> FieldSelector:
    return FieldSelector()


def parse_field_selector(s: Optional[str]) -> FieldSelector:
    """ref: pkg/fields/selector.go ParseSelector — terms split on ','."""
    if not s:
        return everything()
    reqs = []
    for part in s.split(","):
        if not part:
            continue
        if "!=" in part:
            f, v = part.split("!=", 1)
            reqs.append((f.strip(), "!=", v.strip()))
        elif "==" in part:
            f, v = part.split("==", 1)
            reqs.append((f.strip(), "=", v.strip()))
        elif "=" in part:
            f, v = part.split("=", 1)
            reqs.append((f.strip(), "=", v.strip()))
        else:
            raise ValueError(f"invalid field selector {part!r}")
    return FieldSelector(reqs)
