"""Labels and selectors — the universal grouping mechanism.

Rebuild of the reference's `pkg/labels/` (labels.go, selector.go): a label set
is a str->str map; a Selector matches label sets. Two selector families are
supported, mirroring the reference:

- equality/set-based expression selectors parsed from strings like
  ``"env in (a,b), tier notin (db), partition, !legacy, k=v, k!=v"``
  (ref: pkg/labels/selector.go:626 Parse, grammar at :430-470).
- ``SelectorFromSet`` / plain dict match-labels (ref: labels.go Set.AsSelector).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional

__all__ = [
    "Selector",
    "Requirement",
    "parse_selector",
    "selector_from_set",
    "everything",
    "nothing",
    "format_labels",
    "parse_labels",
]

# Operators (ref: pkg/labels/selector.go:117-124).
IN = "in"
NOT_IN = "notin"
EQUALS = "="
DOUBLE_EQUALS = "=="
NOT_EQUALS = "!="
EXISTS = "exists"
DOES_NOT_EXIST = "!"

_LABEL_VALUE_RE = re.compile(r"^(?:[A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$|^$")
_QUALIFIED_NAME_RE = re.compile(
    r"^(?:[a-z0-9](?:[-a-z0-9.]*[a-z0-9])?/)?[A-Za-z0-9](?:[-A-Za-z0-9_.]*[A-Za-z0-9])?$"
)


def validate_label_key(k: str) -> bool:
    """Qualified name: optional DNS-subdomain prefix (<=253) + '/' + name (<=63)
    (ref: pkg/util/validation IsQualifiedName)."""
    if not k:
        return False
    prefix, _, name = k.rpartition("/")
    if prefix and len(prefix) > 253:
        return False
    if not name or len(name) > 63:
        return False
    return bool(_QUALIFIED_NAME_RE.match(k))


def validate_label_value(v: str) -> bool:
    return len(v) <= 63 and bool(_LABEL_VALUE_RE.match(v))


class Requirement:
    """One term of a selector: key op [values] (ref: selector.go:104-259)."""

    __slots__ = ("key", "op", "values")

    def __init__(self, key: str, op: str, values: Iterable[str] = ()):
        self.key = key
        self.op = op
        self.values = sorted(set(values))
        if op in (IN, NOT_IN) and not self.values:
            raise ValueError(f"for {op!r} operator, values set can't be empty")
        if op in (EQUALS, DOUBLE_EQUALS, NOT_EQUALS) and len(self.values) != 1:
            raise ValueError(f"exact-match requires exactly one value, got {self.values}")
        if op in (EXISTS, DOES_NOT_EXIST) and self.values:
            raise ValueError(f"values set must be empty for {op!r}")

    def matches(self, labels: Dict[str, str]) -> bool:
        # ref: selector.go Requirement.Matches (:152-176)
        if self.op in (IN, EQUALS, DOUBLE_EQUALS):
            return self.key in labels and labels[self.key] in self.values
        if self.op in (NOT_IN, NOT_EQUALS):
            return self.key not in labels or labels[self.key] not in self.values
        if self.op == EXISTS:
            return self.key in labels
        if self.op == DOES_NOT_EXIST:
            return self.key not in labels
        raise ValueError(f"unknown operator {self.op!r}")

    def __str__(self) -> str:
        if self.op == EXISTS:
            return self.key
        if self.op == DOES_NOT_EXIST:
            return "!" + self.key
        if self.op in (EQUALS, DOUBLE_EQUALS, NOT_EQUALS):
            return f"{self.key}{self.op}{self.values[0]}"
        return f"{self.key} {self.op} ({','.join(self.values)})"

    def __eq__(self, other):
        return (
            isinstance(other, Requirement)
            and (self.key, self.op, self.values) == (other.key, other.op, other.values)
        )


class Selector:
    """A conjunction of Requirements (ref: selector.go internalSelector)."""

    __slots__ = ("requirements", "_nothing")

    def __init__(self, requirements: Optional[List[Requirement]] = None, nothing: bool = False):
        self.requirements = list(requirements or [])
        self._nothing = nothing

    def matches(self, labels: Optional[Dict[str, str]]) -> bool:
        if self._nothing:
            return False
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def empty(self) -> bool:
        return not self._nothing and not self.requirements

    def add(self, *reqs: Requirement) -> "Selector":
        return Selector(self.requirements + list(reqs), self._nothing)

    def exact_match_labels(self) -> Optional[Dict[str, str]]:
        """If the selector is purely conjunctive equality, return the map."""
        out = {}
        for r in self.requirements:
            if r.op in (EQUALS, DOUBLE_EQUALS) or (r.op == IN and len(r.values) == 1):
                out[r.key] = r.values[0]
            else:
                return None
        return out

    def __str__(self) -> str:
        if self._nothing:
            return "<nothing>"
        return ",".join(str(r) for r in sorted(self.requirements, key=lambda r: r.key))

    def __eq__(self, other):
        return (
            isinstance(other, Selector)
            and self._nothing == other._nothing
            and sorted(map(str, self.requirements)) == sorted(map(str, other.requirements))
        )

    def __repr__(self):
        return f"Selector({str(self)!r})"


def everything() -> Selector:
    return Selector()


def nothing() -> Selector:
    return Selector(nothing=True)


def selector_from_set(labels: Optional[Dict[str, str]]) -> Selector:
    """ref: labels.go SelectorFromSet — nil/empty set selects everything."""
    if not labels:
        return everything()
    return Selector([Requirement(k, EQUALS, [v]) for k, v in sorted(labels.items())])


# ---------------------------------------------------------------------------
# Parser (ref: pkg/labels/selector.go lexer/parser :262-626)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<comma>,) |
        (?P<lparen>\() |
        (?P<rparen>\)) |
        (?P<op>==|=|!=) |
        (?P<bang>!) |
        (?P<ident>[A-Za-z0-9_][A-Za-z0-9_./\-]*)
    )""",
    re.VERBOSE,
)


def _tokenize(s: str):
    pos, out = 0, []
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"unable to parse selector at {s[pos:]!r}")
        kind = m.lastgroup
        out.append((kind, m.group(kind)))
        pos = m.end()
    return out


def parse_selector(s: Optional[str]) -> Selector:
    """Parse a set-based selector string (ref: selector.go:626 Parse)."""
    if s is None or s.strip() == "":
        return everything()
    toks = _tokenize(s)
    reqs: List[Requirement] = []
    i = 0
    need_sep = False  # a requirement just ended; only ',' (or end) may follow

    def peek(j=0):
        return toks[i + j] if i + j < len(toks) else (None, None)

    while i < len(toks):
        kind, val = toks[i]
        if kind == "comma":
            need_sep = False
            i += 1
            continue
        if need_sep:
            raise ValueError(f"expected ',' before {val!r} in selector {s!r}")
        if kind == "bang":
            nkind, nval = peek(1)
            if nkind != "ident":
                raise ValueError(f"expected identifier after '!' in {s!r}")
            reqs.append(Requirement(nval, DOES_NOT_EXIST))
            i += 2
            need_sep = True
            continue
        if kind != "ident":
            raise ValueError(f"unexpected token {val!r} in selector {s!r}")
        key = val
        nkind, nval = peek(1)
        if nkind in (None, "comma"):
            reqs.append(Requirement(key, EXISTS))
            i += 1
            need_sep = True
            continue
        if nkind == "op":
            vkind, vval = peek(2)
            if vkind == "ident":
                value = vval
                i += 3
            elif vkind in (None, "comma"):  # empty value, e.g. "k="
                value = ""
                i += 2
            else:
                raise ValueError(f"expected value after {nval!r} in {s!r}")
            op = NOT_EQUALS if nval == "!=" else EQUALS
            reqs.append(Requirement(key, op, [value]))
            need_sep = True
            continue
        if nkind == "ident" and nval in ("in", "notin"):
            op = IN if nval == "in" else NOT_IN
            if peek(2)[0] != "lparen":
                raise ValueError(f"expected '(' after {nval!r} in {s!r}")
            j = i + 3
            values = []
            expect_value = True
            while j < len(toks):
                tkind, tval = toks[j]
                if tkind == "rparen":
                    break
                if tkind == "comma":
                    if expect_value:
                        values.append("")
                    expect_value = True
                elif tkind == "ident":
                    values.append(tval)
                    expect_value = False
                else:
                    raise ValueError(f"unexpected {tval!r} inside () in {s!r}")
                j += 1
            else:
                raise ValueError(f"missing ')' in {s!r}")
            reqs.append(Requirement(key, op, values))
            i = j + 1
            need_sep = True
            continue
        raise ValueError(f"unexpected token {nval!r} after key {key!r} in {s!r}")
    return Selector(reqs)


def format_labels(labels: Dict[str, str]) -> str:
    """ref: labels.go Set.String — k1=v1,k2=v2 sorted."""
    return ",".join(f"{k}={v}" for k, v in sorted((labels or {}).items()))


def parse_labels(s: str) -> Dict[str, str]:
    """Parse "k1=v1,k2=v2" into a map (strict equality only)."""
    out: Dict[str, str] = {}
    if not s:
        return out
    for part in s.split(","):
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid label spec {part!r}")
        k, v = part.split("=", 1)
        out[k] = v
    return out
