"""resource.Quantity — canonicalized SI resource amounts.

Rebuild of the reference's `pkg/api/resource/quantity.go` + `suffix.go`: a
fixed-point decimal/binary quantity with suffix canonicalization. This is the
basis of all capacity math (node capacity, pod requests/limits, quota).

Internally the amount is an exact rational (numerator/denominator over powers
of 2 and 10), so milli-CPU arithmetic and binary-SI byte arithmetic are both
exact. Formatting follows the reference's canonicalization rules: the suffix
family of the original string is preserved (BinarySI / DecimalSI /
DecimalExponent), and values are printed with the largest suffix that keeps
the mantissa integral.
"""

from __future__ import annotations

import re
from fractions import Fraction
from functools import total_ordering

__all__ = ["Quantity", "parse_quantity", "QuantityError"]


class QuantityError(ValueError):
    pass


# Suffix tables (ref: pkg/api/resource/suffix.go).
_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}
# Ordered largest-first for canonical formatting.
_BINARY_ORDER = ["Ei", "Pi", "Ti", "Gi", "Mi", "Ki", ""]
_DECIMAL_ORDER = ["E", "P", "T", "G", "M", "k", "", "m", "u", "n"]

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<suffix>[numkMGTPE]|[KMGTPE]i)|[eE](?P<exp>[+-]?\d+))?$"
)

BINARY_SI = "BinarySI"
DECIMAL_SI = "DecimalSI"
DECIMAL_EXPONENT = "DecimalExponent"


@total_ordering
class Quantity:
    """An exact resource amount with a preferred display format.

    Construction: ``Quantity("100m")``, ``Quantity("1.5Gi")``, ``Quantity(2)``,
    ``Quantity("3e6")``. Arithmetic (+, -, comparison) is exact.
    """

    # _milli_cache/_int_cache/_str_cache memoize the accessor results
    # (arithmetic always returns new Quantity objects, so .value never
    # mutates in place); the str form is the wire encoding and dominates
    # per-object serialization cost via Fraction arithmetic otherwise
    __slots__ = ("value", "format", "_milli_cache", "_int_cache",
                 "_str_cache")

    def __init__(self, value="0", fmt=None):
        if isinstance(value, Quantity):
            self.value = value.value
            self.format = fmt or value.format
            return
        if isinstance(value, (int,)):
            self.value = Fraction(value)
            self.format = fmt or DECIMAL_SI
            return
        if isinstance(value, float):
            # Floats are accepted for convenience but converted via str to
            # avoid binary-float dust (0.1 -> 1/10, not 0.1000000000000000055).
            value = repr(value)
        if isinstance(value, Fraction):
            self.value = value
            self.format = fmt or DECIMAL_SI
            return
        if not isinstance(value, str):
            raise QuantityError(f"cannot parse quantity from {type(value)!r}")
        v, f = _parse(value)
        self.value = v
        self.format = fmt or f

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other):
        other = Quantity(other)
        # a zero accumulator adopts the operand's format so that
        # Quantity("0") + Quantity("64Mi") prints "64Mi", not raw bytes
        # (quota usage strings stay human-canonical)
        fmt = self.format if self.value else other.format
        return Quantity(self.value + other.value, fmt)

    def __sub__(self, other):
        other = Quantity(other)
        fmt = self.format if self.value else other.format
        return Quantity(self.value - other.value, fmt)

    def __neg__(self):
        return Quantity(-self.value, self.format)

    def __eq__(self, other):
        if other is None:
            return False
        try:
            return self.value == Quantity(other).value
        except (QuantityError, TypeError):
            return NotImplemented

    def __lt__(self, other):
        try:
            return self.value < Quantity(other).value
        except (QuantityError, TypeError):
            return NotImplemented

    def __hash__(self):
        return hash(self.value)

    def __bool__(self):
        return self.value != 0

    # Value-immutable: arithmetic returns new instances and the caches are
    # pure memos, so isolation copies (the in-process transport and the
    # store make one per request) can share the instance. This prunes the
    # deepest, most object-heavy leaves out of every Pod deepcopy — the
    # dominant cost of the create path at churn rates.
    def __copy__(self):
        return self

    def __deepcopy__(self, memo):
        return self

    # -- accessors ----------------------------------------------------------
    # memoized: the snapshot encoder calls these once per pod-resource per
    # wave and Fraction arithmetic dominates the host encode profile
    def milli_value(self) -> int:
        """Value scaled by 1000, rounded up (ref: quantity.go MilliValue)."""
        cached = getattr(self, "_milli_cache", None)
        if cached is None:
            v = self.value * 1000
            cached = -(-v.numerator // v.denominator)  # ceil
            object.__setattr__(self, "_milli_cache", cached)
        return cached

    def int_value(self) -> int:
        """Value rounded up to the nearest integer (ref: quantity.go Value)."""
        cached = getattr(self, "_int_cache", None)
        if cached is None:
            v = self.value
            cached = -(-v.numerator // v.denominator)
            object.__setattr__(self, "_int_cache", cached)
        return cached

    def to_float(self) -> float:
        return float(self.value)

    def is_zero(self) -> bool:
        return self.value == 0

    def copy(self) -> "Quantity":
        return Quantity(self.value, self.format)

    # -- formatting ---------------------------------------------------------
    def __str__(self) -> str:
        cached = getattr(self, "_str_cache", None)
        if cached is None:
            # global memo too: decode creates a fresh instance per object
            # (so the per-instance cache starts cold every time), yet the
            # wire value vocabulary under churn is a handful of strings
            fk = (self.value, self.format)
            cached = _FORMAT_CACHE.get(fk)
            if cached is None:
                cached = _format(self.value, self.format)
                if len(_FORMAT_CACHE) >= _PARSE_CACHE_MAX:
                    _FORMAT_CACHE.clear()
                _FORMAT_CACHE[fk] = cached
            object.__setattr__(self, "_str_cache", cached)
        return cached

    def __repr__(self) -> str:
        return f"Quantity({str(self)!r})"


# string -> (Fraction, fmt) memo: decode paths parse the same handful of
# wire strings ("100m", "128Mi", ...) millions of times under churn, and
# Fraction construction dominates. Both members of the tuple are
# immutable, so sharing across instances is safe. Bounded by wholesale
# clear (the working set is tiny; eviction order is irrelevant).
_PARSE_CACHE: dict = {}
_PARSE_CACHE_MAX = 4096
# (Fraction value, fmt) -> wire string; same bounding discipline
_FORMAT_CACHE: dict = {}


def _parse(s: str):
    hit = _PARSE_CACHE.get(s)
    if hit is not None:
        return hit
    out = _parse_uncached(s)
    if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
        _PARSE_CACHE.clear()
    _PARSE_CACHE[s] = out
    return out


def _parse_uncached(s: str):
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise QuantityError(f"unable to parse quantity {s!r}")
    sign = -1 if m.group("sign") == "-" else 1
    num = Fraction(m.group("num"))
    suffix = m.group("suffix")
    exp = m.group("exp")
    if exp is not None:
        val = num * Fraction(10) ** int(exp)
        fmt = DECIMAL_EXPONENT
    elif suffix is None:
        val, fmt = num, DECIMAL_SI
    elif suffix in _BINARY_SUFFIXES:
        val, fmt = num * _BINARY_SUFFIXES[suffix], BINARY_SI
    else:
        val, fmt = num * _DECIMAL_SUFFIXES[suffix], DECIMAL_SI
    return sign * val, fmt


def _format(v: Fraction, fmt: str) -> str:
    if v == 0:
        return "0"
    sign = "-" if v < 0 else ""
    v = abs(v)
    if fmt == BINARY_SI:
        # Largest binary suffix with an integral mantissa; fall back to
        # decimal-SI for sub-integer amounts (ref: suffix.go interpretation).
        for suf in _BINARY_ORDER:
            scale = _BINARY_SUFFIXES.get(suf, 1)
            scaled = v / scale
            if scaled.denominator == 1 and (suf == "" or scaled.numerator >= 1):
                return f"{sign}{scaled.numerator}{suf}"
        fmt = DECIMAL_SI
    if fmt == DECIMAL_EXPONENT:
        # mantissa * 10^exp with integral mantissa; exponent a multiple of 3
        # (ref: suffix.go decimalExponent formats via e3/e6/...). Rationals
        # whose denominator is not 2^a*5^b (e.g. 1/3) have no finite decimal
        # form — round those up at nano precision like the DecimalSI fallback.
        exp = 0
        val = v
        for _ in range(30):
            if val.denominator == 1:
                break
            val *= 10
            exp -= 1
        if val.denominator != 1:
            val = Fraction(-(-val.numerator // val.denominator))
        mant = val.numerator
        while mant % 10 == 0 and mant != 0:
            mant //= 10
            exp += 1
        while exp % 3 != 0:
            mant *= 10
            exp -= 1
        if exp == 0:
            return f"{sign}{mant}"
        return f"{sign}{mant}e{exp}"
    # DecimalSI: largest decimal suffix keeping the mantissa integral.
    for suf in _DECIMAL_ORDER:
        scale = _DECIMAL_SUFFIXES[suf]
        scaled = v / scale
        if scaled.denominator == 1:
            return f"{sign}{scaled.numerator}{suf}"
    # Smaller than 1n: print as nano rounded up (reference rounds up on
    # lossy canonicalization, quantity.go:239).
    scaled = v / _DECIMAL_SUFFIXES["n"]
    return f"{sign}{-(-scaled.numerator // scaled.denominator)}n"


def parse_quantity(s) -> Quantity:
    return Quantity(s)
