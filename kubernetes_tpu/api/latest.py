"""Default scheme wiring — registered versions and conversions.

ref: pkg/api/latest/latest.go — declares the supported external versions
("v1" current, "v1beta1" legacy) and registers every kind plus conversion
functions. The v1beta1 conversions exercise the same seam the reference uses
for its hand-written v1beta1/v1beta2 conversions
(ref: pkg/api/v1beta1/conversion.go): metadata fields are flattened to the
top level and ``name`` is spelled ``id``.
"""

from __future__ import annotations

from kubernetes_tpu.api import types as api
from kubernetes_tpu.runtime.scheme import Scheme

__all__ = ["scheme", "VERSIONS", "LATEST_VERSION", "new_scheme"]

LATEST_VERSION = "v1"
OLDEST_VERSION = "v1beta1"
# v1beta2 shares v1beta1's flattened-metadata wire shape — in the reference
# the two differ only in minor defaulting (ref: pkg/api/v1beta2/ is
# generated from v1beta1 with small deltas); v1beta3 introduced the nested
# metadata that became v1, which is our "v1" here.
VERSIONS = ("v1", "v1beta1", "v1beta2")

_ALL_KINDS = (
    api.Pod, api.PodList,
    api.ReplicationController, api.ReplicationControllerList,
    api.Service, api.ServiceList,
    api.Endpoints, api.EndpointsList,
    api.Node, api.NodeList,
    api.Namespace, api.NamespaceList,
    api.Binding,
    api.Event, api.EventList,
    api.Secret, api.SecretList,
    api.LimitRange, api.LimitRangeList,
    api.ResourceQuota, api.ResourceQuotaList,
    api.Status,
    api.DeleteOptions,
)

# Metadata fields flattened to top level in v1beta1 (name is spelled "id").
_META_FLAT = (
    ("name", "id"),
    ("namespace", "namespace"),
    ("uid", "uid"),
    ("resourceVersion", "resourceVersion"),
    ("creationTimestamp", "creationTimestamp"),
    ("deletionTimestamp", "deletionTimestamp"),
    ("selfLink", "selfLink"),
    ("labels", "labels"),
    ("annotations", "annotations"),
    ("generateName", "generateName"),
)


def _v1beta1_encode(wire: dict) -> dict:
    """internal wire -> v1beta1 wire: flatten metadata (ref: v1beta1/conversion.go)."""
    wire = dict(wire)
    meta = wire.pop("metadata", None)
    if isinstance(meta, dict):
        for internal_name, beta_name in _META_FLAT:
            if internal_name in meta:
                wire[beta_name] = meta[internal_name]
    items = wire.get("items")
    if isinstance(items, list):
        wire["items"] = [_v1beta1_encode(i) if isinstance(i, dict) else i for i in items]
    return wire


def _v1beta1_decode(wire: dict) -> dict:
    """v1beta1 wire -> internal wire: nest metadata back."""
    wire = dict(wire)
    meta = {}
    for internal_name, beta_name in _META_FLAT:
        if beta_name in wire:
            meta[internal_name] = wire.pop(beta_name)
    if meta:
        wire["metadata"] = meta
    items = wire.get("items")
    if isinstance(items, list):
        wire["items"] = [_v1beta1_decode(i) if isinstance(i, dict) else i for i in items]
    return wire


def new_scheme() -> Scheme:
    s = Scheme(default_version=LATEST_VERSION)
    s.add_known_types("v1", *_ALL_KINDS)
    s.add_known_types("v1beta1", *_ALL_KINDS)
    s.add_known_types("v1beta2", *_ALL_KINDS)
    for t in _ALL_KINDS:
        kind = getattr(t, "kind", t.__name__) or t.__name__
        s.add_conversion("v1beta1", kind, _v1beta1_encode, _v1beta1_decode)
        s.add_conversion("v1beta2", kind, _v1beta1_encode, _v1beta1_decode)
    return s


# The shared default scheme (ref: api.Scheme package variable).
scheme = new_scheme()
