"""Default scheme wiring — registered versions, conversions, defaults.

ref: pkg/api/latest/latest.go — declares the supported external versions
("v1" current, "v1beta1"/"v1beta2" legacy) and registers every kind plus
conversion functions, defaulting, kind aliases, and field-label
conversions. The legacy wire format lives in kubernetes_tpu.api.v1beta1:
a genuinely restructured sibling (flat metadata with ``id``,
desiredState/currentState envelopes, manifest-nested pod specs,
one-of-object restart policies, "Minion", "podID", "ip:port" endpoints)
exercising the same seam the reference used for its hand-written
v1beta1/v1beta2 conversions (ref: pkg/api/v1beta1/conversion.go).
v1beta2 (kubernetes_tpu.api.v1beta2) shares that envelope but drops the
era's deprecated aliases (EnvVar.key, VolumeMount.path,
MinionList.minions) and stamps its own manifest version — the same
delta separating the reference's two betas (ref: pkg/api/v1beta2/
types.go vs v1beta1/conversion.go:114-196); v1beta3 introduced the
nested metadata that became v1, which is our "v1" here.
"""

from __future__ import annotations

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import v1beta1, v1beta2
from kubernetes_tpu.runtime.scheme import Scheme

__all__ = ["scheme", "VERSIONS", "LATEST_VERSION", "new_scheme"]

LATEST_VERSION = "v1"
OLDEST_VERSION = "v1beta1"
VERSIONS = ("v1", "v1beta1", "v1beta2")
# each legacy version registers from its own wire module
_LEGACY = {"v1beta1": v1beta1, "v1beta2": v1beta2}

_ALL_KINDS = (
    api.Pod, api.PodList,
    api.ReplicationController, api.ReplicationControllerList,
    api.Service, api.ServiceList,
    api.Endpoints, api.EndpointsList,
    api.Node, api.NodeList,
    api.Namespace, api.NamespaceList,
    api.Binding, api.BindingList, api.BindingResultList,
    api.Event, api.EventList,
    api.Secret, api.SecretList,
    api.LimitRange, api.LimitRangeList,
    api.ResourceQuota, api.ResourceQuotaList,
    api.PriorityClass, api.PriorityClassList,
    api.Status,
    api.DeleteOptions,
)


def new_scheme() -> Scheme:
    s = Scheme(default_version=LATEST_VERSION)
    for v in VERSIONS:
        s.add_known_types(v, *_ALL_KINDS)
    for t in _ALL_KINDS:
        kind = getattr(t, "kind", t.__name__) or t.__name__
        for v, mod in _LEGACY.items():
            s.add_conversion(v, kind, mod.encode_for(kind),
                             mod.decode_for(kind))
    for v, mod in _LEGACY.items():
        for wire_kind, kind in mod.KIND_ALIASES.items():
            s.add_kind_alias(v, wire_kind, kind)
        for kind, fn in mod.DEFAULTERS.items():
            s.add_defaulter(v, kind, fn)
        for kind, fn in mod.FIELD_LABELS.items():
            s.add_field_label_conversion(v, kind, fn)
    # v1 applies the same era defaults on decode (ref: v1beta3/defaults.go)
    for kind, fn in v1beta1.DEFAULTERS.items():
        s.add_defaulter("v1", kind, fn)
    return s


# The shared default scheme (ref: api.Scheme package variable).
scheme = new_scheme()
