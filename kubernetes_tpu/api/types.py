"""Internal API object model.

Rebuild of the reference's internal types (ref: pkg/api/types.go:1-1623):
Pod/PodSpec/PodStatus (:695-758), ReplicationController (:816), Service
(:908), Endpoints (:921), Node/NodeSpec/NodeStatus (:953-1087), Namespace
(:1125), Binding (:1145), Event (:1383), Status (:1167), plus the container,
volume, probe, and condition substructures they reference.

These are plain dataclasses; wire encoding/decoding and versioning live in
kubernetes_tpu.runtime (scheme + serialize), keeping the internal model
version-free exactly like the reference's ``pkg/api`` package.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.quantity import Quantity

# ---------------------------------------------------------------------------
# Constants / enums (string enums, like the reference)
# ---------------------------------------------------------------------------

NamespaceDefault = "default"
NamespaceAll = ""
NamespaceNone = ""

# PodPhase (ref: types.go:550-570)
PodPending = "Pending"
PodRunning = "Running"
PodSucceeded = "Succeeded"
PodFailed = "Failed"
PodUnknown = "Unknown"

# ConditionStatus (ref: types.go:608-618)
ConditionTrue = "True"
ConditionFalse = "False"
ConditionUnknown = "Unknown"

# PodConditionType
PodReady = "Ready"

# RestartPolicy
RestartPolicyAlways = "Always"
RestartPolicyOnFailure = "OnFailure"
RestartPolicyNever = "Never"

# DNSPolicy
DNSClusterFirst = "ClusterFirst"
DNSDefault = "Default"

# Protocols
ProtocolTCP = "TCP"
ProtocolUDP = "UDP"

# PullPolicy (ref: types.go PullAlways/PullNever/PullIfNotPresent)
PullAlways = "Always"
PullNever = "Never"
PullIfNotPresent = "IfNotPresent"

# Resource names (ref: types.go ResourceCPU/ResourceMemory + quota names)
ResourceCPU = "cpu"
ResourceMemory = "memory"
ResourcePods = "pods"
ResourceServices = "services"
ResourceReplicationControllers = "replicationcontrollers"
ResourceQuotas = "resourcequotas"
ResourceSecrets = "secrets"

# NodeConditionType (ref: types.go NodeReady/NodeReachable/NodeSchedulable)
NodeReady = "Ready"
NodeReachable = "Reachable"
NodeSchedulable = "Schedulable"

# NodePhase
NodePending = "Pending"
NodeRunning = "Running"
NodeTerminated = "Terminated"

# NodeAddressType
NodeInternalIP = "InternalIP"
NodeExternalIP = "ExternalIP"
NodeHostName = "Hostname"

# NamespacePhase (ref: types.go NamespaceActive/NamespaceTerminating)
NamespaceActive = "Active"
NamespaceTerminating = "Terminating"
FinalizerKubernetes = "kubernetes"

# Status values (ref: types.go:1167-1260)
StatusSuccess = "Success"
StatusFailure = "Failure"

# StatusReason (ref: types.go:1203-1260)
ReasonNotFound = "NotFound"
ReasonAlreadyExists = "AlreadyExists"
ReasonConflict = "Conflict"
ReasonInvalid = "Invalid"
ReasonBadRequest = "BadRequest"
ReasonForbidden = "Forbidden"
ReasonUnauthorized = "Unauthorized"
ReasonMethodNotAllowed = "MethodNotAllowed"
ReasonInternalError = "InternalError"
ReasonExpired = "Expired"
ReasonTooManyRequests = "TooManyRequests"

# Session affinity
AffinityNone = "None"
AffinityClientIP = "ClientIP"

# PreemptionPolicy (PriorityClass / PodSpec): whether a pod of this
# priority may claim a node by evicting strictly-lower-priority pods.
PreemptLowerPriority = "PreemptLowerPriority"
PreemptNever = "Never"

# Priority values: user classes must stay below the system band, like the
# upstream HighestUserDefinablePriority / system-cluster-critical split.
HighestUserDefinablePriority = 1_000_000_000
DefaultPodPriority = 0

# Event source components
DefaultSchedulerName = "scheduler"

ResourceList = Dict[str, Quantity]  # resource name -> Quantity


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------


@dataclass
class ObjectMeta:
    """ref: types.go ObjectMeta (:83-141)."""

    name: str = ""
    generate_name: str = ""
    namespace: str = ""
    self_link: str = ""
    uid: str = ""
    resource_version: str = ""
    creation_timestamp: Optional[datetime.datetime] = None
    deletion_timestamp: Optional[datetime.datetime] = None
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class ListMeta:
    self_link: str = ""
    resource_version: str = ""


@dataclass
class ObjectReference:
    """ref: types.go ObjectReference (:1330-1360)."""

    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    api_version: str = ""
    resource_version: str = ""
    field_path: str = ""


# ---------------------------------------------------------------------------
# Volumes (ref: types.go:147-330; plugin impls pkg/volume/)
# ---------------------------------------------------------------------------


@dataclass
class EmptyDirVolumeSource:
    medium: str = ""


@dataclass
class HostPathVolumeSource:
    path: str = ""


@dataclass
class GCEPersistentDiskVolumeSource:
    pd_name: str = ""
    fs_type: str = ""
    partition: int = 0
    read_only: bool = False


@dataclass
class GitRepoVolumeSource:
    repository: str = ""
    revision: str = ""


@dataclass
class SecretVolumeSource:
    secret_name: str = ""


@dataclass
class NFSVolumeSource:
    server: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class VolumeSource:
    empty_dir: Optional[EmptyDirVolumeSource] = None
    host_path: Optional[HostPathVolumeSource] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    git_repo: Optional[GitRepoVolumeSource] = None
    secret: Optional[SecretVolumeSource] = None
    nfs: Optional[NFSVolumeSource] = None


@dataclass
class Volume:
    name: str = ""
    source: VolumeSource = field(default_factory=VolumeSource)


@dataclass
class VolumeMount:
    name: str = ""
    read_only: bool = False
    mount_path: str = ""


# ---------------------------------------------------------------------------
# Containers & probes (ref: types.go:330-550)
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    name: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = ProtocolTCP
    host_ip: str = ""


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class ExecAction:
    command: List[str] = field(default_factory=list)


@dataclass
class HTTPGetAction:
    path: str = ""
    port: int = 0
    host: str = ""


@dataclass
class TCPSocketAction:
    port: int = 0


@dataclass
class Handler:
    exec: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None


@dataclass
class Probe:
    exec: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None
    initial_delay_seconds: int = 0
    timeout_seconds: int = 1


@dataclass
class Lifecycle:
    post_start: Optional[Handler] = None
    pre_stop: Optional[Handler] = None


@dataclass
class ResourceRequirements:
    limits: ResourceList = field(default_factory=dict)
    requests: ResourceList = field(default_factory=dict)


@dataclass
class Container:
    """ref: types.go Container (:420-470)."""

    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    working_dir: str = ""
    ports: List[ContainerPort] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    lifecycle: Optional[Lifecycle] = None
    termination_message_path: str = "/dev/termination-log"
    privileged: bool = False
    image_pull_policy: str = ""


@dataclass
class ContainerStateWaiting:
    reason: str = ""


@dataclass
class ContainerStateRunning:
    started_at: Optional[datetime.datetime] = None


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    signal: int = 0
    reason: str = ""
    message: str = ""
    started_at: Optional[datetime.datetime] = None
    finished_at: Optional[datetime.datetime] = None


@dataclass
class ContainerState:
    waiting: Optional[ContainerStateWaiting] = None
    running: Optional[ContainerStateRunning] = None
    termination: Optional[ContainerStateTerminated] = None


@dataclass
class ContainerStatus:
    """ref: types.go ContainerStatus (:583-607)."""

    name: str = ""
    state: ContainerState = field(default_factory=ContainerState)
    last_termination_state: ContainerState = field(default_factory=ContainerState)
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    image_id: str = ""
    container_id: str = ""


# ---------------------------------------------------------------------------
# Pod (ref: types.go:620-815)
# ---------------------------------------------------------------------------


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""


@dataclass
class PodSpec:
    """ref: types.go PodSpec (:695-720)."""

    volumes: List[Volume] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    restart_policy: str = RestartPolicyAlways
    termination_grace_period_seconds: Optional[int] = None
    dns_policy: str = DNSClusterFirst
    node_selector: Dict[str, str] = field(default_factory=dict)
    host: str = ""
    host_network: bool = False
    # kube-preempt: the resolved integer priority (admission fills it from
    # priority_class_name; None = unresolved, treated as 0) and the
    # effective preemption policy ("" inherits the class's, defaulting to
    # PreemptLowerPriority). The scheduler reads ONLY the resolved fields.
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: str = ""


@dataclass
class PodStatus:
    """ref: types.go PodStatus (:721-745)."""

    phase: str = ""
    conditions: List[PodCondition] = field(default_factory=list)
    message: str = ""
    host: str = ""
    host_ip: str = ""
    pod_ip: str = ""
    container_statuses: List[ContainerStatus] = field(default_factory=list)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"


@dataclass
class PodList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Pod] = field(default_factory=list)
    kind: str = "PodList"


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


# ---------------------------------------------------------------------------
# ReplicationController (ref: types.go:816-880)
# ---------------------------------------------------------------------------


@dataclass
class ReplicationControllerSpec:
    replicas: int = 0
    selector: Dict[str, str] = field(default_factory=dict)
    template: Optional[PodTemplateSpec] = None


@dataclass
class ReplicationControllerStatus:
    replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicationController:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicationControllerSpec = field(default_factory=ReplicationControllerSpec)
    status: ReplicationControllerStatus = field(default_factory=ReplicationControllerStatus)
    kind: str = "ReplicationController"


@dataclass
class ReplicationControllerList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[ReplicationController] = field(default_factory=list)
    kind: str = "ReplicationControllerList"


# ---------------------------------------------------------------------------
# Service & Endpoints (ref: types.go:881-952)
# ---------------------------------------------------------------------------


@dataclass
class ServiceSpec:
    """ref: types.go ServiceSpec (:908-940)."""

    port: int = 0
    protocol: str = ProtocolTCP
    selector: Dict[str, str] = field(default_factory=dict)
    portal_ip: str = ""
    create_external_load_balancer: bool = False
    public_ips: List[str] = field(default_factory=list)
    container_port: int = 0  # target port on the pod
    session_affinity: str = AffinityNone


@dataclass
class ServiceStatus:
    pass


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)
    kind: str = "Service"


@dataclass
class ServiceList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Service] = field(default_factory=list)
    kind: str = "ServiceList"


@dataclass
class Endpoint:
    ip: str = ""
    port: int = 0
    target_ref: Optional[ObjectReference] = None


@dataclass
class Endpoints:
    """ref: types.go Endpoints (:921). The reference stores "ip:port" strings;
    structured Endpoint records carry the same information plus a target ref."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    protocol: str = ProtocolTCP
    endpoints: List[Endpoint] = field(default_factory=list)
    kind: str = "Endpoints"


@dataclass
class EndpointsList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Endpoints] = field(default_factory=list)
    kind: str = "EndpointsList"


# ---------------------------------------------------------------------------
# Node (ref: types.go:953-1124; called "Minion" in the reference wire API)
# ---------------------------------------------------------------------------


@dataclass
class NodeSpec:
    """ref: types.go NodeSpec — capacity lives on the spec in this era and is
    what the scheduler reads (ref: pkg/scheduler/predicates.go:137)."""

    capacity: ResourceList = field(default_factory=dict)
    pod_cidr: str = ""
    external_id: str = ""
    unschedulable: bool = False


@dataclass
class NodeCondition:
    type: str = ""
    status: str = ""
    last_probe_time: Optional[datetime.datetime] = None
    last_transition_time: Optional[datetime.datetime] = None
    reason: str = ""
    message: str = ""


@dataclass
class NodeAddress:
    type: str = ""
    address: str = ""


@dataclass
class NodeSystemInfo:
    machine_id: str = ""
    system_uuid: str = ""
    boot_id: str = ""
    kernel_version: str = ""
    os_image: str = ""
    container_runtime_version: str = ""
    kubelet_version: str = ""


@dataclass
class NodeStatus:
    phase: str = ""
    conditions: List[NodeCondition] = field(default_factory=list)
    addresses: List[NodeAddress] = field(default_factory=list)
    node_info: NodeSystemInfo = field(default_factory=NodeSystemInfo)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"


@dataclass
class NodeList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Node] = field(default_factory=list)
    kind: str = "NodeList"


# ---------------------------------------------------------------------------
# Namespace (ref: types.go:1125-1165)
# ---------------------------------------------------------------------------


@dataclass
class NamespaceSpec:
    finalizers: List[str] = field(default_factory=lambda: [FinalizerKubernetes])


@dataclass
class NamespaceStatus:
    phase: str = NamespaceActive


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NamespaceSpec = field(default_factory=NamespaceSpec)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)
    kind: str = "Namespace"


@dataclass
class NamespaceList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Namespace] = field(default_factory=list)
    kind: str = "NamespaceList"


# ---------------------------------------------------------------------------
# PriorityClass (kube-preempt: the scheduling.k8s.io/v1 shape on the
# era-appropriate surface — cluster-scoped, int32 value, optional
# preemption policy, at most one globalDefault)
# ---------------------------------------------------------------------------


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    description: str = ""
    preemption_policy: str = PreemptLowerPriority
    kind: str = "PriorityClass"


@dataclass
class PriorityClassList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[PriorityClass] = field(default_factory=list)
    kind: str = "PriorityClassList"


# ---------------------------------------------------------------------------
# Binding (ref: types.go:1145-1155; write path pkg/registry/pod/etcd/etcd.go:98)
# ---------------------------------------------------------------------------


@dataclass
class Binding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_name: str = ""
    host: str = ""
    # kube-preempt: pods the server must evict (delete) atomically with
    # this bind — either every victim is deleted AND the pod binds, or the
    # item fails 409 and nothing is applied. Each ref names a pod in the
    # binding's namespace; uid guards against name reuse.
    victims: List[ObjectReference] = field(default_factory=list)
    # kube-defrag: when set, this is a MIGRATION bind — the pod is
    # expected to be bound to from_host already and is atomically moved
    # (evict-here + bind-there) to ``host``. pod_uid guards against the
    # pod being deleted/recreated between the descheduler's proposal and
    # the commit; any mismatch fails the item 409 with nothing applied.
    # The scheduler never sets these, so the hot bind path is untouched.
    from_host: str = ""
    pod_uid: str = ""
    kind: str = "Binding"


@dataclass
class BindingList:
    """A wave's bindings, committed in one transactional store pass — the
    batch extension SURVEY §7 hard part (e) calls for (10k binds landing in
    one wave must not pay 10k apiserver round-trips). Each item keeps the
    reference's per-pod CAS semantics; results come back positionally."""

    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Binding] = field(default_factory=list)
    kind: str = "BindingList"


@dataclass
class BindingResult:
    pod_name: str = ""
    error: str = ""      # empty = bound; else the per-pod failure message
    code: int = 0        # HTTP-ish status code for the failure


@dataclass
class BindingResultList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[BindingResult] = field(default_factory=list)
    kind: str = "BindingResultList"


# ---------------------------------------------------------------------------
# Status & options (ref: types.go:1167-1330)
# ---------------------------------------------------------------------------


@dataclass
class StatusCause:
    reason: str = ""
    message: str = ""
    field_path: str = ""


@dataclass
class StatusDetails:
    name: str = ""
    kind: str = ""
    causes: List[StatusCause] = field(default_factory=list)
    retry_after_seconds: int = 0


@dataclass
class Status:
    metadata: ListMeta = field(default_factory=ListMeta)
    status: str = ""
    message: str = ""
    reason: str = ""
    details: Optional[StatusDetails] = None
    code: int = 0
    kind: str = "Status"


@dataclass
class DeleteOptions:
    grace_period_seconds: Optional[int] = None
    kind: str = "DeleteOptions"


@dataclass
class ListOptions:
    label_selector: str = ""
    field_selector: str = ""
    watch: bool = False
    resource_version: str = ""


# ---------------------------------------------------------------------------
# Events (ref: types.go:1383-1420; recorder pkg/client/record/event.go)
# ---------------------------------------------------------------------------


@dataclass
class EventSource:
    component: str = ""
    host: str = ""


@dataclass
class Event:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    source: EventSource = field(default_factory=EventSource)
    first_timestamp: Optional[datetime.datetime] = None
    last_timestamp: Optional[datetime.datetime] = None
    count: int = 0
    kind: str = "Event"


@dataclass
class EventList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Event] = field(default_factory=list)
    kind: str = "EventList"


# ---------------------------------------------------------------------------
# Secrets, LimitRange, ResourceQuota (ref: types.go:1430-1623)
# ---------------------------------------------------------------------------


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)  # base64-encoded values
    type: str = "Opaque"
    kind: str = "Secret"


@dataclass
class SecretList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[Secret] = field(default_factory=list)
    kind: str = "SecretList"


@dataclass
class LimitRangeItem:
    type: str = ""  # "Pod" or "Container"
    max: ResourceList = field(default_factory=dict)
    min: ResourceList = field(default_factory=dict)
    default: ResourceList = field(default_factory=dict)


@dataclass
class LimitRangeSpec:
    limits: List[LimitRangeItem] = field(default_factory=list)


@dataclass
class LimitRange:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)
    kind: str = "LimitRange"


@dataclass
class LimitRangeList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[LimitRange] = field(default_factory=list)
    kind: str = "LimitRangeList"


@dataclass
class ResourceQuotaSpec:
    hard: ResourceList = field(default_factory=dict)


@dataclass
class ResourceQuotaStatus:
    hard: ResourceList = field(default_factory=dict)
    used: ResourceList = field(default_factory=dict)


@dataclass
class ResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)
    kind: str = "ResourceQuota"


@dataclass
class ResourceQuotaList:
    metadata: ListMeta = field(default_factory=ListMeta)
    items: List[ResourceQuota] = field(default_factory=list)
    kind: str = "ResourceQuotaList"


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

LIST_KINDS = {
    "PodList": PodList,
    "ReplicationControllerList": ReplicationControllerList,
    "ServiceList": ServiceList,
    "EndpointsList": EndpointsList,
    "NodeList": NodeList,
    "NamespaceList": NamespaceList,
    "EventList": EventList,
    "SecretList": SecretList,
    "LimitRangeList": LimitRangeList,
    "ResourceQuotaList": ResourceQuotaList,
    "PriorityClassList": PriorityClassList,
}


def pod_priority(pod: Pod) -> int:
    """The scheduler-effective priority of a pod: the admission-resolved
    spec.priority, 0 (DefaultPodPriority) when unresolved."""
    p = pod.spec.priority
    return DefaultPodPriority if p is None else int(p)


def pod_can_preempt(pod: Pod) -> bool:
    """Whether this pod may claim a node by evicting lower-priority pods:
    the resolved spec.preemption_policy, defaulting to
    PreemptLowerPriority exactly like the upstream API."""
    return pod.spec.preemption_policy != PreemptNever


def is_pod_active(pod: Pod) -> bool:
    """ref: pkg/controller/replication_controller.go FilterActivePods (:182)."""
    return pod.status.phase not in (PodSucceeded, PodFailed)


def pod_requests(pod: Pod) -> Dict[str, int]:
    """Sum container resource requests; cpu in millicores, memory in bytes.

    Mirrors the capacity math in ref: pkg/scheduler/predicates.go:86-101
    (getResourceRequest): limits in this era double as requests.
    """
    cpu = 0
    mem = 0
    for c in pod.spec.containers:
        req = c.resources.requests or c.resources.limits
        q = req.get(ResourceCPU)
        if q is not None:
            cpu += q.milli_value()
        q = req.get(ResourceMemory)
        if q is not None:
            mem += q.int_value()
    return {ResourceCPU: cpu, ResourceMemory: mem}
