"""Watch primitives (ref: pkg/watch/).

``Watcher`` is the consumer handle (ref: watch.Interface — a result channel
plus Stop). ``Broadcaster`` fans one event stream out to many watchers
(ref: pkg/watch/mux.go:63-143).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Iterator, Optional

__all__ = ["ADDED", "MODIFIED", "DELETED", "ERROR", "Event", "Watcher", "Broadcaster"]

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"


@dataclass
class Event:
    type: str
    object: Any


_SENTINEL = object()


class Watcher:
    """A stream of watch Events. Iterate it, or poll with next_event().

    ref: pkg/watch/watch.go Interface — ResultChan() + Stop().
    """

    def __init__(self, maxsize: int = 0, on_stop=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._stopped = threading.Event()
        self._on_stop = on_stop

    # producer side -------------------------------------------------------
    def send(self, event: Event, timeout: Optional[float] = None) -> bool:
        if self._stopped.is_set():
            return False
        try:
            self._q.put(event, timeout=timeout)
            return True
        except queue.Full:
            return False

    def close(self) -> None:
        """End of stream: consumers see StopIteration after draining."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        # Never block here: a full bounded queue would deadlock stop(). The
        # stream is ending, so dropping one queued event to make room for the
        # sentinel is safe.
        while True:
            try:
                self._q.put_nowait(_SENTINEL)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    pass

    # consumer side -------------------------------------------------------
    def stop(self) -> None:
        """Consumer is done (ref: watch.Interface.Stop)."""
        cb, self._on_stop = self._on_stop, None
        self.close()
        if cb:
            cb(self)

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def next_event(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event or None on end-of-stream; raises queue.Empty on timeout."""
        ev = self._q.get(timeout=timeout)
        if ev is _SENTINEL:
            self._q.put(_SENTINEL)  # keep the stream terminated for others
            return None
        return ev

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self._q.get()
            if ev is _SENTINEL:
                self._q.put(_SENTINEL)
                return
            yield ev


class Broadcaster:
    """Distributes events to many watchers (ref: pkg/watch/mux.go).

    Watchers that fall behind beyond ``queue_length`` block the broadcast
    (the reference's WaitIfChannelFull behavior) so no event is lost.
    """

    def __init__(self, queue_length: int = 25):
        self._lock = threading.Lock()
        self._watchers: set = set()
        self._queue_length = queue_length
        self._closed = False

    def watch(self) -> Watcher:
        with self._lock:
            if self._closed:
                raise RuntimeError("broadcaster is shut down")
            w = Watcher(maxsize=self._queue_length, on_stop=self._forget)
            self._watchers.add(w)
            return w

    def _forget(self, w: Watcher) -> None:
        with self._lock:
            self._watchers.discard(w)

    def action(self, event_type: str, obj: Any) -> None:
        ev = Event(event_type, obj)
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.send(ev)

    def shutdown(self) -> None:
        with self._lock:
            watchers, self._watchers = list(self._watchers), set()
            self._closed = True
        for w in watchers:
            w.close()
