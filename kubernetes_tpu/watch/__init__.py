"""Watch primitives (ref: pkg/watch/).

``Watcher`` is the consumer handle (ref: watch.Interface — a result channel
plus Stop). ``Broadcaster`` fans one event stream out to many watchers
(ref: pkg/watch/mux.go:63-143).

Bounded-lag mode (``lag_limit``): the apiserver's fan-out path must never
let one slow watch connection grow an unbounded queue of encoded state.
A watcher constructed with ``lag_limit`` sheds load in two stages when
its consumer falls behind:

1. **coalescing** — once the queue is at the bound, a new event is merged
   into the newest queued event for the same key when the supplied
   ``coalesce`` function can prove the two are a contiguous
   modify-chain (v1->v2 + v2->v3 becomes v1->v3). The consumer still
   sees every key's latest state, just fewer intermediate revisions.
2. **drop-to-resync** — when coalescing cannot absorb the event, the
   queue is discarded wholesale and the consumer receives one ERROR
   event followed by end-of-stream (the bookmark-style "you lagged out"
   marker). Clients handle it with the Reflector contract: re-list and
   re-watch from the fresh resourceVersion.

Both degradations are counted (``watch_events_coalesced_total``,
``watch_lag_resyncs_total``) so fan-out loss is observable, never
silent; plain bounded watchers that overflow count
``watch_events_dropped_total`` and log once per watcher.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterator, List, Optional

__all__ = ["ADDED", "MODIFIED", "DELETED", "ERROR", "Event", "Watcher",
           "Broadcaster"]

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"

_log = logging.getLogger("kubernetes_tpu.watch")


@dataclass
class Event:
    type: str
    object: Any


_SENTINEL = object()


class _WatchMetrics:
    """Process-wide fan-out loss counters (default registry; the apiserver
    merges the default registry into its /metrics payload)."""

    _singleton = None

    def __init__(self):
        from kubernetes_tpu.util import metrics as metrics_pkg
        reg = metrics_pkg.default_registry()
        self.dropped = reg.counter(
            "watch_events_dropped_total",
            "Watch events dropped on a full bounded watcher queue")
        self.coalesced = reg.counter(
            "watch_events_coalesced_total",
            "Watch events merged into a queued same-key event on a "
            "lagging watcher")
        self.lag_resyncs = reg.counter(
            "watch_lag_resyncs_total",
            "Watchers dropped to resync (ERROR + end-of-stream) after "
            "exceeding their lag bound")


def _watch_metrics() -> _WatchMetrics:
    if _WatchMetrics._singleton is None:
        _WatchMetrics._singleton = _WatchMetrics()
    return _WatchMetrics._singleton


class Watcher:
    """A stream of watch Events. Iterate it, or poll with next_event().

    ref: pkg/watch/watch.go Interface — ResultChan() + Stop().
    """

    def __init__(self, maxsize: int = 0, on_stop=None,
                 lag_limit: Optional[int] = None,
                 coalesce: Optional[Callable[[Event, Event],
                                             Optional[Event]]] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._stopped = threading.Event()
        self._on_stop = on_stop
        self._lag_limit = lag_limit
        self._coalesce = coalesce
        self._lagged = False
        self._warned_drop = False

    # producer side -------------------------------------------------------
    def send(self, event: Event, timeout: Optional[float] = None) -> bool:
        if self._stopped.is_set():
            return False
        if self._lag_limit is not None \
                and self._q.qsize() >= self._lag_limit:
            if self._coalesce is not None and self._try_coalesce(event):
                return True
            self.drop_to_resync()
            return False
        try:
            self._q.put(event, timeout=timeout)
            return True
        except queue.Full:
            self._count_drop()
            return False

    def _count_drop(self) -> None:
        _watch_metrics().dropped.inc()
        if not self._warned_drop:
            self._warned_drop = True
            _log.warning(
                "watcher queue full (maxsize=%d): dropping event(s); "
                "further drops on this watcher are counted in "
                "watch_events_dropped_total without logging",
                self._q.maxsize)

    # Coalescing only runs once a watcher is AT its lag bound, and the
    # producer calls it from the store's notify path (under the store
    # lock) — so the backward scan for a same-key predecessor is depth-
    # bounded: an unbounded scan of a 64k-deep queue per write would let
    # one stuck watcher serialize every store mutation behind it. A
    # predecessor deeper than this is a cold key on a hopeless watcher;
    # giving up degrades to drop-to-resync, which is where that watcher
    # is headed anyway.
    _COALESCE_SCAN_MAX = 256

    def _try_coalesce(self, event: Event) -> bool:
        """Merge ``event`` into the newest queued event for the same key.
        The coalesce function proves chain contiguity itself (by comparing
        store indices), so only one queued event can possibly merge."""
        merged = None
        with self._q.mutex:
            dq = self._q.queue
            lo = max(-1, len(dq) - 1 - self._COALESCE_SCAN_MAX)
            for i in range(len(dq) - 1, lo, -1):
                old = dq[i]
                if old is _SENTINEL:
                    continue
                merged = self._coalesce(old, event)
                if merged is not None:
                    del dq[i]
                    dq.append(merged)
                    break
        if merged is None:
            return False
        _watch_metrics().coalesced.inc()
        return True

    def drop_to_resync(self) -> None:
        """Bounded-lag overflow: discard everything queued, deliver one
        ERROR event (object=None — the transport layers substitute their
        own 410 Expired payload), end the stream. The consumer re-lists
        (the Reflector contract, ref: pkg/client/cache/reflector.go:83)."""
        if self._stopped.is_set():
            return
        self._lagged = True
        self._stopped.set()
        _watch_metrics().lag_resyncs.inc()
        _log.warning("watcher exceeded lag bound (%s queued): dropping to "
                     "resync", self._lag_limit)
        with self._q.mutex:
            self._q.queue.clear()
            self._q.queue.append(Event(ERROR, None))
            self._q.queue.append(_SENTINEL)
            self._q.not_empty.notify_all()

    @property
    def lagged(self) -> bool:
        """True once this watcher was dropped to resync."""
        return self._lagged

    def close(self) -> None:
        """End of stream: consumers see StopIteration after draining."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        # Never block here: a full bounded queue would deadlock stop(). The
        # stream is ending, so dropping one queued event to make room for the
        # sentinel is safe.
        while True:
            try:
                self._q.put_nowait(_SENTINEL)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self._count_drop()
                except queue.Empty:
                    pass

    # consumer side -------------------------------------------------------
    def stop(self) -> None:
        """Consumer is done (ref: watch.Interface.Stop)."""
        cb, self._on_stop = self._on_stop, None
        self.close()
        if cb:
            cb(self)

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def next_event(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event or None on end-of-stream; raises queue.Empty on timeout."""
        ev = self._q.get(timeout=timeout)
        if ev is _SENTINEL:
            self._q.put(_SENTINEL)  # keep the stream terminated for others
            return None
        return ev

    def next_batch(self, max_items: int = 128,
                   timeout: Optional[float] = None,
                   linger: float = 0.0) -> Optional[List[Event]]:
        """Block for one event, then greedily drain up to ``max_items``
        without blocking — the fan-out writer's unit of work (one write
        syscall per batch instead of one per event). ``linger`` sleeps
        that long after the first event before draining: at a steady
        event rate this turns one wakeup + one write PER EVENT per
        watcher into one per batch — the difference between N watchers
        costing N condition-wakeup/GIL-handoff/syscall storms and N
        cheap byte copies (a few ms of delivery latency is invisible
        next to the scheduler's wave cadence). Returns None on
        end-of-stream; raises queue.Empty on timeout like next_event."""
        ev = self._q.get(timeout=timeout)
        if ev is _SENTINEL:
            self._q.put(_SENTINEL)
            return None
        out = [ev]
        # linger only when the queue is shallow: its purpose is to let a
        # TRICKLE accumulate into one write. When a backlog already fills
        # the batch, sleeping would cap drain throughput at
        # max_items/linger and a fast consumer could be paced into the
        # lag bound by its own writer.
        if linger > 0.0 and not self._stopped.is_set() \
                and self._q.qsize() < max_items:
            time.sleep(linger)
        while len(out) < max_items:
            try:
                ev = self._q.get_nowait()
            except queue.Empty:
                break
            if ev is _SENTINEL:
                self._q.put(_SENTINEL)
                break
            out.append(ev)
        return out

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self._q.get()
            if ev is _SENTINEL:
                self._q.put(_SENTINEL)
                return
            yield ev


class Broadcaster:
    """Distributes events to many watchers (ref: pkg/watch/mux.go).

    Watchers that fall behind beyond ``queue_length`` block the broadcast
    (the reference's WaitIfChannelFull behavior) so no event is lost.
    """

    def __init__(self, queue_length: int = 25):
        self._lock = threading.Lock()
        self._watchers: set = set()
        self._queue_length = queue_length
        self._closed = False

    def watch(self) -> Watcher:
        with self._lock:
            if self._closed:
                raise RuntimeError("broadcaster is shut down")
            w = Watcher(maxsize=self._queue_length, on_stop=self._forget)
            self._watchers.add(w)
            return w

    def _forget(self, w: Watcher) -> None:
        with self._lock:
            self._watchers.discard(w)

    def action(self, event_type: str, obj: Any) -> None:
        ev = Event(event_type, obj)
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.send(ev)

    def shutdown(self) -> None:
        with self._lock:
            watchers, self._watchers = list(self._watchers), set()
            self._closed = True
        for w in watchers:
            w.close()
