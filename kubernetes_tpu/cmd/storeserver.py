"""kube-store — the cluster store as its own server process.

The reference does not ship this binary because it delegates the role to
etcd (ref: DESIGN.md:17 "all persistent master state is stored in etcd";
cmd/kube-apiserver flags --etcd_servers). This is that missing process
for the rebuild: it owns the one MemStore/DurableStore and serves it to
any number of apiserver workers over the RemoteStore protocol.

Usage: python -m kubernetes_tpu.cmd.storeserver [--port 2379]
           [--data-dir DIR]
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

__all__ = ["main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-store", exit_on_error=False)
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2379)  # etcd's port, homage
    p.add_argument("--data-dir", "--data_dir", default="",
                   help="persist state here (WAL + snapshots); empty = "
                        "in-memory only")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from kubernetes_tpu.storage.remote import StoreServer

    if opts.data_dir:
        from kubernetes_tpu.storage.durable import DurableStore
        store = DurableStore(opts.data_dir)
    else:
        from kubernetes_tpu.storage.memstore import MemStore
        store = MemStore()
    srv = StoreServer(store, host=opts.address, port=opts.port)
    print(f"kube-store listening on {srv.address}", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
