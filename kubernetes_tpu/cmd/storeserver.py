"""kube-store — the cluster store as its own server process.

The reference does not ship this binary because it delegates the role to
etcd (ref: DESIGN.md:17 "all persistent master state is stored in etcd";
cmd/kube-apiserver flags --etcd_servers). This is that missing process
for the rebuild: it owns the one MemStore/DurableStore and serves it to
any number of apiserver workers over the RemoteStore protocol.

kube-chaos (docs/design/ha.md) grew it an observability sidecar:
``--metrics-port`` serves /healthz (recovery disclosure: replayed
records, snapshot age, recovery wall time — the numbers that make
"bounded recovery" a measured claim), /metrics (the ``store_wal_*``
family), and /debug/vars (flightrec), so a respawned kube-store proves
what its recovery cost instead of silently replaying.

Usage: python -m kubernetes_tpu.cmd.storeserver [--port 2379]
           [--data-dir DIR] [--fsync] [--compact-every N]
           [--metrics-port PORT] [--flightrec]
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

__all__ = ["main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-store", exit_on_error=False)
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2379)  # etcd's port, homage
    p.add_argument("--data-dir", "--data_dir", default="",
                   help="persist state here (WAL + snapshots); empty = "
                        "in-memory only")
    p.add_argument("--fsync", action="store_true",
                   help="fsync(2) every WAL group commit (media-crash "
                        "durability; default flush-only survives process "
                        "kill)")
    p.add_argument("--compact-every", "--compact_every", type=int,
                   default=10_000,
                   help="snapshot + truncate the WAL every N records")
    p.add_argument("--shards", type=int, default=1,
                   help="kube-stripe: shard the keyspace by namespace "
                        "hash into this many shards (power of two; per-"
                        "shard locks, rings, and watcher lists under one "
                        "global revision counter). 1 = the unsharded "
                        "MemStore/DurableStore twin.")
    p.add_argument("--max-inflight", "--max_inflight", type=int, default=0,
                   help="kube-fairshed overload valve: shed ops past "
                        "this many concurrent dispatches with a "
                        "retryable ErrTooManyRequests + measured-drain "
                        "retry_after hint (RemoteStore honors it "
                        "transparently). 0 disables.")
    p.add_argument("--metrics-port", "--metrics_port", type=int, default=0,
                   help="serve /metrics, /healthz (recovery disclosure) "
                        "and /debug/vars on this port (0 disables)")
    p.add_argument("--flightrec", action="store_true",
                   help="kube-flightrec: sample every metric series into "
                        "the per-process ring from boot (served at "
                        "GET /debug/vars on --metrics-port)")
    p.add_argument("--flightrec-period", "--flightrec_period", type=float,
                   default=1.0, help="flight recorder sample period, "
                        "seconds")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from kubernetes_tpu.storage.remote import StoreServer

    if opts.shards > 1:
        if opts.data_dir:
            from kubernetes_tpu.storage.stripestore import DurableStripedStore
            store = DurableStripedStore(
                opts.data_dir, shards=opts.shards, fsync=opts.fsync,
                compact_every=opts.compact_every)
        else:
            from kubernetes_tpu.storage.stripestore import StripedStore
            store = StripedStore(shards=opts.shards)
    elif opts.data_dir:
        from kubernetes_tpu.storage.durable import DurableStore
        store = DurableStore(opts.data_dir, fsync=opts.fsync,
                             compact_every=opts.compact_every)
    else:
        from kubernetes_tpu.storage.memstore import MemStore
        store = MemStore()
    if opts.flightrec:
        from kubernetes_tpu.util import metrics as metrics_pkg
        metrics_pkg.flightrec_arm(
            "storeserver", period_s=opts.flightrec_period)
    if opts.metrics_port:
        from kubernetes_tpu import probe
        from kubernetes_tpu.cmd.scheduler import _serve_debug

        def health():
            payload = {
                "kind": "ComponentStatusList", "healthy": True,
                "items": [{"name": "store", "status": probe.SUCCESS,
                           "message": f"{type(store).__name__} serving "
                                      f"index {store.index}"}],
            }
            recovery = getattr(store, "recovery", None)
            if recovery is not None:
                payload["recovery"] = dict(recovery)
                payload["data_dir"] = opts.data_dir
            return payload, True

        _serve_debug(opts.metrics_port, service="storeserver",
                     health=health)
    srv = StoreServer(store, host=opts.address, port=opts.port,
                      max_inflight=opts.max_inflight)
    # the "listening" line FIRST — harness readiness checks key on it;
    # the recovery disclosure follows (and stays on /healthz forever)
    print(f"kube-store listening on {srv.address}", flush=True)
    recovery = getattr(store, "recovery", None)
    if recovery is not None:
        print(f"kube-store recovered {opts.data_dir}: "
              f"{recovery['replayed_records']} WAL records "
              f"({recovery['replayed_ops']} ops) replayed in "
              f"{recovery['recovery_s']}s, snapshot "
              + (f"age {recovery['snapshot_age_s']}s"
                 if recovery["snapshot"] else "absent")
              + (f", torn tail {recovery['torn_bytes']}B discarded"
                 if recovery["torn_bytes"] else ""), flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
