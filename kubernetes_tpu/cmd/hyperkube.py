"""hyperkube — every server in one binary (ref: cmd/hyperkube/main.go +
pkg/hyperkube). ``python -m kubernetes_tpu.cmd.hyperkube <server> [flags]``.
"""

from __future__ import annotations

import sys
from typing import List

__all__ = ["main", "SERVERS"]


def _apiserver(argv):
    from kubernetes_tpu.cmd.apiserver import apiserver_server
    return apiserver_server(argv)


def _controller_manager(argv):
    from kubernetes_tpu.cmd.controller_manager import controller_manager_server
    return controller_manager_server(argv)


def _scheduler(argv):
    from kubernetes_tpu.cmd.scheduler import scheduler_server
    return scheduler_server(argv)


def _kubelet(argv):
    from kubernetes_tpu.cmd.kubelet import kubelet_server
    return kubelet_server(argv)


def _proxy(argv):
    from kubernetes_tpu.cmd.proxy import proxy_server
    return proxy_server(argv)


def _kubectl(argv):
    from kubernetes_tpu.kubectl.cmd import main as kubectl_main
    return kubectl_main(argv)


def _standalone(argv):
    from kubernetes_tpu.cmd.standalone import standalone_server
    return standalone_server(argv)


def _version_change(argv):
    from kubernetes_tpu.cmd.version_change import version_change
    return version_change(argv)


def _solverd(argv):
    from kubernetes_tpu.cmd.solverd import solverd_server
    return solverd_server(argv)


def _dns(argv):
    from kubernetes_tpu.cmd.dns import dns_server
    return dns_server(argv)


def _monitoring(argv):
    from kubernetes_tpu.cmd.monitoring import monitoring_server
    return monitoring_server(argv)


SERVERS = {
    "apiserver": _apiserver,
    "kube-apiserver": _apiserver,
    "controller-manager": _controller_manager,
    "kube-controller-manager": _controller_manager,
    "scheduler": _scheduler,
    "kube-scheduler": _scheduler,
    "kubelet": _kubelet,
    "proxy": _proxy,
    "kube-proxy": _proxy,
    "kubectl": _kubectl,
    "standalone": _standalone,
    "kubernetes": _standalone,
    "version-change": _version_change,
    "kube-version-change": _version_change,
    "solverd": _solverd,
    "kube-solverd": _solverd,
    "dns": _dns,
    "cluster-dns": _dns,
    "monitoring": _monitoring,
    "cluster-monitoring": _monitoring,
}


def main(argv: List[str] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv or argv[0] in ("-h", "--help", "help"):
        names = ", ".join(sorted(set(SERVERS)))
        print(f"usage: hyperkube <server> [flags]\nservers: {names}",
              file=sys.stderr)
        return 0 if argv else 1
    server = SERVERS.get(argv[0])
    if server is None:
        print(f"error: unknown server {argv[0]!r}", file=sys.stderr)
        return 1
    return server(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
