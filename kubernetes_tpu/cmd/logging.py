"""cluster-logging binary — the fluentd-elasticsearch-analog aggregator
(ref: cluster/addons/fluentd-elasticsearch deployment)."""

from __future__ import annotations

import argparse
import sys
import threading
from typing import List, Optional

__all__ = ["logging_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cluster-logging", exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080",
                   help="apiserver URL")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10252)
    p.add_argument("--kubelet-port", "--kubelet_port", type=int,
                   default=10250)
    p.add_argument("--period", type=float, default=2.0,
                   help="log tail period seconds")
    p.add_argument("--max-records", "--max_records", type=int,
                   default=100_000, help="retention ring size")
    return p


def logging_server(argv: List[str],
                   ready: Optional[threading.Event] = None,
                   stop: Optional[threading.Event] = None) -> int:
    from kubernetes_tpu.addons.logging import (LogAggregator,
                                               http_kubelet_log_fetcher)
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport

    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    client = Client(HTTPTransport(opts.master))
    agg = LogAggregator(client,
                        fetch=http_kubelet_log_fetcher(opts.kubelet_port),
                        period_s=opts.period, max_records=opts.max_records,
                        host=opts.address, port=opts.port).start()
    print(f"cluster-logging on http://{opts.address}:{agg.port} "
          f"(/logs, /metrics)", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    agg.stop()
    return 0


def main() -> int:
    return logging_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
