"""kube-proxy binary (ref: cmd/kube-proxy/app/server.go:65).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["proxy_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-proxy", exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--bind-address", "--bind_address", default="127.0.0.1")
    p.add_argument("--real-iptables", action="store_true",
                   help="program real netfilter rules (needs root); default "
                        "uses the in-memory rule table")
    return p


def build_proxy(opts):
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport
    from kubernetes_tpu.proxy.config import EndpointsConfig, ServiceConfig
    from kubernetes_tpu.proxy.proxier import Proxier
    from kubernetes_tpu.util.iptables import ExecIPTables, FakeIPTables

    client = Client(HTTPTransport(opts.master, user_agent="kube-proxy"))
    ipt = ExecIPTables() if opts.real_iptables else FakeIPTables()
    proxier = Proxier(listen_ip=opts.bind_address, iptables=ipt)
    svc_cfg = ServiceConfig(client, [proxier.on_update])
    ep_cfg = EndpointsConfig(client, [proxier.lb.on_update])
    return proxier, svc_cfg, ep_cfg


def proxy_server(argv: List[str],
                 ready: Optional[threading.Event] = None,
                 stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    proxier, svc_cfg, ep_cfg = build_proxy(opts)
    svc_cfg.run()
    ep_cfg.run()
    sync = threading.Thread(target=proxier.sync_loop, daemon=True,
                            name="proxy-sync")
    sync.start()
    print("kube-proxy running", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    svc_cfg.stop()
    ep_cfg.stop()
    proxier.stop()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return proxy_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
