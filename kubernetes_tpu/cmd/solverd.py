"""kube-solverd binary — the shared batch-solver daemon.

The reference has no analog: its scheduler is a per-pod loop with no
accelerator to share. In this rebuild the solver runtime (JAX + compiled
wave programs) is the one component that must NOT be replicated per
scheduler worker — one hot daemon serves them all (see
docs/design/solver.md and kubernetes_tpu/solver/service.py).

Usage: python -m kubernetes_tpu.cmd.solverd [--port 10450]
           [--gather-window 0.003] [--max-batch 16] [--max-queue 64]
           [--metrics-port 0]
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["solverd_server", "main"]

DEFAULT_PORT = 10450


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-solverd", exit_on_error=False)
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument("--gather-window", "--gather_window", type=float,
                   default=0.003,
                   help="seconds to gather concurrent waves into one "
                        "batched solve (wave coalescing)")
    p.add_argument("--max-batch", "--max_batch", type=int, default=16,
                   help="max waves per batched device call")
    p.add_argument("--max-queue", "--max_queue", type=int, default=64,
                   help="bounded request queue; beyond this, requests get "
                        "an immediate BUSY reply (backpressure) instead of "
                        "unbounded latency")
    p.add_argument("--cache-entries", "--cache_entries", type=int,
                   default=64,
                   help="delta-wire resident plane cache entries (one per "
                        "worker thread x shape bucket); evictions cost the "
                        "evicted client one full-frame resync")
    p.add_argument("--metrics-port", "--metrics_port", type=int, default=0,
                   help="serve /metrics, /healthz and /debug/pprof on this "
                        "port (0 disables)")
    p.add_argument("--mesh", choices=("auto", "on", "off"), default="auto",
                   help="device-mesh production dispatch "
                        "(solver/mesh_exec.py): auto enables it whenever "
                        ">1 device is attached — real multi-chip, or CPU "
                        "sub-meshes via XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N; waves "
                        "above --mesh-min-nodes then solve from "
                        "device-resident sharded planes")
    p.add_argument("--pods-axis", "--pods_axis", type=int, default=1,
                   help="mesh 'pods' axis length; the rest of the devices "
                        "shard the node axis (pods_axis=1 is pure "
                        "tensor-parallel over nodes)")
    p.add_argument("--mesh-min-nodes", "--mesh_min_nodes", type=int,
                   default=None,
                   help="node-count floor for the mesh dispatch (default "
                        "parallel.mesh.DEFAULT_MESH_MIN_NODES); smaller "
                        "waves keep the padded vmap path")
    p.add_argument("--mesh-dispatch", "--mesh_dispatch",
                   choices=("auto", "shard", "single"), default="auto",
                   help="node-axis layout: auto times the fully-sharded "
                        "scan against the single-device submesh once per "
                        "shape (persisted in the warm-start dir) and runs "
                        "the winner; shard/single pin a layout")
    p.add_argument("--mesh-probe", "--mesh_probe",
                   choices=("first", "all", "off"), default="first",
                   help="live bit-identity probe: re-solve mesh-path "
                        "waves in the other layout and compare bitwise "
                        "(first = once per daemon run)")
    p.add_argument("--prewarm", action="store_true",
                   help="kube-slipstream: compile the shape-bucket set "
                        "implied by --prewarm-nodes/-pods/-batch at boot, "
                        "off the solve path, before the first request; "
                        "compile_prewarm_ready flips to 1 on /metrics "
                        "when done (the churn harness gates its load "
                        "window on it). The fill-trigger prewarm thread "
                        "runs regardless unless KTPU_PREWARM=off.")
    p.add_argument("--prewarm-nodes", "--prewarm_nodes", type=int,
                   default=0,
                   help="declared cluster node count for the boot "
                        "prewarm set (pow-2 rounded)")
    p.add_argument("--prewarm-pods", "--prewarm_pods", type=int,
                   default=1024,
                   help="top of the pod-axis bucket ladder to prewarm "
                        "(ladder descends to 256)")
    p.add_argument("--prewarm-batch", "--prewarm_batch", type=int,
                   default=1,
                   help="vmap batch axis to prewarm in addition to 1 "
                        "(set to the expected concurrent-worker count)")
    p.add_argument("--trace", action="store_true",
                   help="kube-trace: record queue-wait + solve spans, "
                        "attached to the requesting wave's trace when the "
                        "v3 frame carries one; drain via GET /debug/trace "
                        "on --metrics-port. Default OFF.")
    p.add_argument("--flightrec", action="store_true",
                   help="kube-flightrec: sample every metric series into "
                        "a per-process (monotonic_ns, value) ring from "
                        "boot, served incrementally at GET /debug/vars on "
                        "--metrics-port. Default OFF (the first "
                        "/debug/vars pull arms sampling lazily anyway).")
    p.add_argument("--flightrec-period", "--flightrec_period", type=float,
                   default=1.0,
                   help="flight recorder sample period, seconds")
    p.add_argument("--trace-device", "--trace_device", default="",
                   help="directory for a jax.profiler device trace of the "
                        "daemon's solves (open in Perfetto/TensorBoard "
                        "alongside the kube-trace host spans). Empty "
                        "disables. Orthogonal to --trace: this is XLA's "
                        "own profiler, started at daemon boot and stopped "
                        "on shutdown.")
    return p


def _solverd_health(srv):
    """Deep-health probe set for the daemon: the solver backend (a JAX
    runtime that lost its devices cannot serve waves) and — when the
    mesh dispatch is on — the device mesh itself. componentstatus-style
    payload; the metrics-port server answers 503 when unhealthy."""
    from kubernetes_tpu import probe

    def health():
        items = []
        ok = True
        try:
            import jax
            n = jax.device_count()
            backend = jax.default_backend()
            st = probe.SUCCESS if n >= 1 else probe.FAILURE
            items.append({"name": "backend", "status": st,
                          "message": f"{backend}, {n} device(s)"})
            ok &= st == probe.SUCCESS
        except Exception as e:
            items.append({"name": "backend", "status": probe.FAILURE,
                          "message": repr(e)})
            ok = False
        me = getattr(srv, "_mesh_exec", None)
        if me is not None:
            shards = getattr(me, "node_shards", 0)
            st = probe.SUCCESS if shards >= 1 else probe.FAILURE
            items.append({"name": "mesh", "status": st,
                          "message": f"{shards} node-shard(s) x "
                                     f"{getattr(me, 'pods_axis', 1)} pods"})
            ok &= st == probe.SUCCESS
        return ({"kind": "ComponentStatusList", "healthy": bool(ok),
                 "items": items}, bool(ok))

    return health


def solverd_server(argv: List[str],
                   ready: Optional[threading.Event] = None,
                   stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from kubernetes_tpu.solver.service import SolverService
    from kubernetes_tpu.util import warmstart
    # the daemon owns the hottest solver runtime in the topology: reuse
    # compiled wave programs + router calibrations across restarts
    warmstart.enable()
    if opts.trace:
        from kubernetes_tpu.util import tracing
        tracing.enable("solverd")
    device_trace = None
    if opts.trace_device:
        # XLA's own device profiler rides alongside the kube-trace host
        # spans; failures are non-fatal (the CPU backend's profiler is
        # optional in some jax builds)
        try:
            import jax.profiler as _jprof
            _jprof.start_trace(opts.trace_device)
            device_trace = _jprof
            print(f"kube-solverd: jax device trace -> {opts.trace_device}",
                  file=sys.stderr)
        except Exception as e:  # pragma: no cover - env-dependent
            print(f"kube-solverd: --trace-device unavailable: {e}",
                  file=sys.stderr)

    srv = SolverService(host=opts.address, port=opts.port,
                        gather_window_s=opts.gather_window,
                        max_batch=opts.max_batch,
                        max_queue=opts.max_queue,
                        cache_entries=opts.cache_entries,
                        mesh=opts.mesh, pods_axis=opts.pods_axis,
                        mesh_min_nodes=opts.mesh_min_nodes,
                        mesh_dispatch=opts.mesh_dispatch,
                        mesh_probe=opts.mesh_probe,
                        prewarm=opts.prewarm,
                        prewarm_nodes=opts.prewarm_nodes,
                        prewarm_pods=opts.prewarm_pods,
                        prewarm_batch=opts.prewarm_batch)
    if opts.flightrec:
        from kubernetes_tpu.util import metrics as metrics_pkg
        metrics_pkg.flightrec_arm("solverd",
                                  period_s=opts.flightrec_period)
    if opts.metrics_port:
        from kubernetes_tpu.cmd.scheduler import _serve_debug
        _serve_debug(opts.metrics_port, service="solverd",
                     health=_solverd_health(srv))
    me = srv._mesh_exec
    mesh_desc = (f", mesh {me.node_shards} node-shards x "
                 f"{me.pods_axis} pods (min {me.min_nodes} nodes, "
                 f"dispatch {opts.mesh_dispatch})"
                 if me is not None else ", mesh off")
    print(f"kube-solverd listening on {srv.address} "
          f"(gather {opts.gather_window * 1000:.1f}ms, "
          f"batch<= {opts.max_batch}, queue<= {opts.max_queue}"
          f"{mesh_desc})",
          file=sys.stderr, flush=True)
    if ready is not None:
        ready.set()
    def _stop_device_trace():
        if device_trace is not None:
            try:
                device_trace.stop_trace()
            except Exception:  # pragma: no cover - profiler teardown
                pass

    if stop is None:
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            srv.stop()
            _stop_device_trace()
        return 0
    srv.start()
    stop.wait()
    srv.stop()
    _stop_device_trace()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    # the Go-runtime SIGQUIT affordance: kill -USR1 <pid> dumps every
    # thread's stack to stderr (the child log) — the tool of last resort
    # when the daemon wedges hard enough that /debug/pprof can't answer
    import faulthandler
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    return solverd_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
