"""cluster-dns binary — the DNS addon as a standalone server
(ref: cluster/addons/dns: skydns + kube2sky deployment)."""

from __future__ import annotations

import argparse
import sys
import threading
from typing import List, Optional

__all__ = ["dns_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cluster-dns", exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080",
                   help="apiserver URL")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10053)
    p.add_argument("--domain", default="cluster.local")
    return p


def dns_server(argv: List[str],
               ready: Optional[threading.Event] = None,
               stop: Optional[threading.Event] = None) -> int:
    from kubernetes_tpu.addons.dns import DNSServer
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport

    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    client = Client(HTTPTransport(opts.master))
    srv = DNSServer(client, host=opts.address, port=opts.port,
                    domain=opts.domain).start()
    print(f"cluster-dns serving {opts.domain} on udp://{srv.addr[0]}:"
          f"{srv.addr[1]}", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    srv.stop()
    return 0


def main() -> int:
    return dns_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
