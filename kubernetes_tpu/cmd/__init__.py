"""Server binaries (ref: cmd/* — thin flag wrappers around app.Server
structs).

Each module has ``NAME_server(argv) -> int`` runnable via
``python -m kubernetes_tpu.cmd.<name>``; ``hyperkube`` dispatches to any of
them by first argument (ref: cmd/hyperkube), and ``standalone`` runs the
whole control plane plus N kubelets in one process
(ref: cmd/kubernetes/kubernetes.go).
"""
