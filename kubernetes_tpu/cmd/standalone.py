"""Single-process demo cluster (ref: cmd/kubernetes/kubernetes.go:183 —
"a testing binary that runs every component in one process").

Starts: HTTP apiserver + controller manager + scheduler + N kubelets (fake
runtime) with their read-only servers, all against one in-memory store.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["standalone_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kubernetes", exit_on_error=False)
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--nodes", type=int, default=2)
    p.add_argument("--algorithm", default="serial",
                   choices=["serial", "tpu-batch"])
    return p


def standalone_server(argv: List[str],
                      ready: Optional[threading.Event] = None,
                      stop: Optional[threading.Event] = None) -> int:
    from kubernetes_tpu.apiserver.http import APIServer
    from kubernetes_tpu.cluster import Cluster, ClusterConfig

    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    cluster = Cluster(ClusterConfig(
        num_nodes=opts.nodes, kubelet_http=True,
        batch_scheduler=opts.algorithm == "tpu-batch")).start()
    srv = APIServer(cluster.master, host=opts.address, port=opts.port,
                    node_locator=cluster.node_locator).start()
    print(f"kubernetes standalone: apiserver {srv.base_url}, "
          f"{opts.nodes} nodes", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    srv.stop()
    cluster.stop()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return standalone_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
