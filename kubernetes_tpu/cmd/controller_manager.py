"""kube-controller-manager binary
(ref: cmd/kube-controller-manager/app/controllermanager.go:138-187).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["controller_manager_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-controller-manager",
                                exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--cloud-provider", "--cloud_provider", default="")
    p.add_argument("--minion-regexp", "--minion_regexp", default=".*")
    p.add_argument("--machines", default="",
                   help="comma-separated static node names")
    p.add_argument("--node-sync-period", "--node_sync_period",
                   type=float, default=10.0)
    p.add_argument("--pod-eviction-timeout", "--pod_eviction_timeout",
                   type=float, default=300.0)
    p.add_argument("--node-cpu", default="4", help="static node cpu capacity")
    p.add_argument("--node-memory", default="8Gi",
                   help="static node memory capacity")
    return p


def build_manager(opts):
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.quantity import Quantity
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport
    from kubernetes_tpu.cloudprovider import get_provider
    from kubernetes_tpu.controllers.manager import (ControllerManager,
                                                    ControllerManagerConfig)

    if opts.machines and opts.cloud_provider:
        raise ValueError("--machines and --cloud-provider are mutually "
                         "exclusive (static list vs cloud discovery)")
    client = Client(HTTPTransport(opts.master, user_agent="kube-controller-manager"))
    static_nodes = [
        api.Node(metadata=api.ObjectMeta(name=name),
                 spec=api.NodeSpec(capacity={
                     api.ResourceCPU: Quantity(opts.node_cpu),
                     api.ResourceMemory: Quantity(opts.node_memory)}))
        for name in opts.machines.split(",") if name]
    return ControllerManager(client, ControllerManagerConfig(
        node_sync_period=opts.node_sync_period,
        pod_eviction_timeout=opts.pod_eviction_timeout,
        static_nodes=static_nodes,
        cloud=get_provider(opts.cloud_provider) if opts.cloud_provider else None,
        match_re=opts.minion_regexp))


def controller_manager_server(argv: List[str],
                              ready: Optional[threading.Event] = None,
                              stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
        manager = build_manager(opts)
    except (argparse.ArgumentError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    manager.run()
    print("kube-controller-manager running", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    manager.stop()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return controller_manager_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
