"""kubectl binary (ref: cmd/kubectl/kubectl.go — delegates to the cmd
tree)."""

from __future__ import annotations

import sys

from kubernetes_tpu.kubectl.cmd import main as kubectl_main

__all__ = ["main"]


def main() -> int:
    return kubectl_main()


if __name__ == "__main__":
    sys.exit(main())
