"""Generate man pages from the kubectl command tree.

ref: cmd/genman/gen_kubectl_man.go — one groff man page per command,
derived from the same live command tree as gendocs (so flags never
drift).

Usage: python -m kubernetes_tpu.cmd.genman [OUTPUT_DIR]
"""

from __future__ import annotations

import sys
from pathlib import Path

from kubernetes_tpu.cmd.gendocs import _options_block, command_tree
from kubernetes_tpu.version import GIT_VERSION

__all__ = ["man_for", "main"]


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace("-", "\\-")


def man_for(name: str, parser, root) -> str:
    title = f"KUBECTL {name.upper()}" if name else "KUBECTL"
    lines = [
        f'.TH "{title}" "1" "" "kubernetes-tpu {GIT_VERSION}" '
        '"User Manuals"',
        ".SH NAME",
        _esc(f"kubectl {name}".strip()) + r" \- "
        + _esc(parser.description or "controls the cluster manager."),
        ".SH SYNOPSIS",
        ".B " + _esc(f"kubectl {name}".strip()),
        r"[\fIOPTIONS\fR]",
        ".SH OPTIONS",
    ]
    for block, src in (("", parser), (" (inherited)", root)):
        if src is parser and block:
            continue  # root page: don't list the same options twice
        opts = _options_block(src)
        if opts:
            for line in opts.splitlines():
                flags, _, rest = line.strip().partition(":")
                lines += [".TP", r"\fB" + _esc(flags) + r"\fR" + block,
                          _esc(rest.strip())]
    lines += [".SH SEE ALSO", ".BR kubectl (1)", ""]
    return "\n".join(lines)


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    outdir = Path(args[0] if args else "docs/man")
    outdir.mkdir(parents=True, exist_ok=True)
    root, subs = command_tree()
    (outdir / "kubectl.1").write_text(man_for("", root, root))
    for name, sp in subs.items():
        (outdir / f"kubectl-{name}.1").write_text(man_for(name, sp, root))
    print(f"wrote {len(subs) + 1} man pages to {outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
