"""Generate markdown CLI documentation from the kubectl command tree.

ref: cmd/gendocs/gen_kubectl_docs.go — the reference walks the cobra
command tree and writes one markdown file per command (name, synopsis,
options, parent/child links). Here the tree is the argparse parser that
kubectl itself executes (kubectl/cmd.py _build_parser), so the docs can
never drift from the real flags.

Usage: python -m kubernetes_tpu.cmd.gendocs [OUTPUT_DIR]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from kubernetes_tpu.kubectl.cmd import _build_parser

__all__ = ["command_tree", "markdown_for", "main"]


def command_tree():
    """-> (root_parser, {name: subparser}) from the real kubectl tree."""
    root = _build_parser()
    subs = {}
    for action in root._actions:
        if isinstance(action, argparse._SubParsersAction):
            # choices maps aliases too; keep the canonical first names in
            # registration order, folding aliases into one entry
            seen = {}
            for name, sp in action.choices.items():
                if id(sp) not in seen:
                    seen[id(sp)] = (name, sp)
            subs = {name: sp for name, sp in seen.values()}
    return root, subs


def _options_block(parser: argparse.ArgumentParser) -> str:
    lines = []
    for a in parser._actions:
        if isinstance(a, (argparse._HelpAction,
                          argparse._SubParsersAction)):
            continue
        flags = ", ".join(a.option_strings) if a.option_strings \
            else a.dest.upper()
        default = "" if a.default in (None, "", False, argparse.SUPPRESS) \
            else f" (default {a.default!r})"
        lines.append(f"      {flags}: {a.help or ''}{default}")
    return "\n".join(lines)


def markdown_for(name: str, parser: argparse.ArgumentParser,
                 root: argparse.ArgumentParser) -> str:
    out = [f"## kubectl {name}", ""]
    if parser.description:
        out += [parser.description, ""]
    opts = _options_block(parser)
    if opts:
        out += ["### Options", "", "```", opts, "```", ""]
    inherited = _options_block(root)
    if inherited:
        out += ["### Options inherited from parent commands", "",
                "```", inherited, "```", ""]
    out += ["### SEE ALSO", "* [kubectl](kubectl.md)", ""]
    return "\n".join(out)


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    outdir = Path(args[0] if args else "docs/cli")
    outdir.mkdir(parents=True, exist_ok=True)
    root, subs = command_tree()
    index = ["# kubectl", "",
             root.description or "kubectl controls the cluster manager.",
             "", "### Commands", ""]
    for name, sp in subs.items():
        (outdir / f"kubectl_{name}.md").write_text(
            markdown_for(name, sp, root))
        index.append(f"* [kubectl {name}](kubectl_{name}.md)")
    index.append("")
    (outdir / "kubectl.md").write_text("\n".join(index))
    print(f"wrote {len(subs) + 1} files to {outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
