"""kube-descheduler binary — the kube-defrag wave loop as its own process.

Mirrors cmd/scheduler.py's server shape (build_parser -> build ->
server(argv, ready, stop)) so hack/churn_mp.py and the hyperkube-style
launchers drive it identically. The descheduler is strictly off the
scheduler hot path: its own client, its own user-agent (rides the
apiserver's system flow like the scheduler), its own wave-loop thread.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["descheduler_server", "build_descheduler", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-descheduler", exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--period", type=float, default=5.0,
                   help="wave loop tick, seconds")
    p.add_argument("--qps", type=float, default=0.2,
                   help="token-bucket wave rate (waves/second)")
    p.add_argument("--burst", type=int, default=1,
                   help="token-bucket burst (waves a quiet period banks)")
    p.add_argument("--max-moves", "--max_moves", type=int, default=50,
                   help="voluntary migrations per wave (whole source "
                        "nodes at a time; drains are not budget-limited)")
    p.add_argument("--source-max-permille", "--source_max_permille",
                   type=int, default=700,
                   help="only nodes below this summed core-dim "
                        "used-permille may be voluntary sources")
    p.add_argument("--protected-namespaces", "--protected_namespaces",
                   default="kube-system",
                   help="comma-separated namespaces whose pods are never "
                        "moved")
    p.add_argument("--always-defrag", "--always_defrag",
                   action="store_true",
                   help="solve even while unbound pods exist (default: "
                        "decline the wave — the scheduler owns the churn "
                        "budget while work is pending)")
    p.add_argument("--one-shot", "--one_shot", action="store_true",
                   help="run exactly one wave (ignoring the token "
                        "bucket), print its report as JSON, exit")
    p.add_argument("--metrics-port", "--metrics_port", type=int, default=0,
                   help="serve /metrics, /healthz and /debug/* on this "
                        "port (0 disables)")
    p.add_argument("--flightrec", action="store_true",
                   help="kube-flightrec: sample every metric series from "
                        "boot (see cmd/scheduler.py --flightrec)")
    p.add_argument("--flightrec-period", "--flightrec_period", type=float,
                   default=1.0)
    return p


def build_descheduler(opts):
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport
    from kubernetes_tpu.descheduler import Descheduler, DeschedulerConfig
    from kubernetes_tpu.models.defrag import DefragConfig

    client = Client(HTTPTransport(opts.master,
                                  user_agent="kube-descheduler"))
    cfg = DeschedulerConfig(
        period_s=opts.period, qps=opts.qps, burst=opts.burst,
        decline_on_pending=not opts.always_defrag,
        defrag=DefragConfig(
            max_moves=opts.max_moves,
            source_max_permille=opts.source_max_permille,
            protected_namespaces=tuple(
                ns for ns in opts.protected_namespaces.split(",") if ns)))
    return Descheduler(client, cfg)


def _descheduler_health(master: str):
    import urllib.parse

    from kubernetes_tpu import probe

    def health():
        u = urllib.parse.urlparse(master)
        st, msg = probe.probe_http(u.hostname, u.port, "/healthz/ping")
        ok = st == probe.SUCCESS
        return ({"kind": "ComponentStatusList", "healthy": ok,
                 "items": [{"name": "apiserver", "status": st,
                            "message": msg if not ok else
                            f"apiserver {master} reachable"}]}, ok)

    return health


def descheduler_server(argv: List[str],
                       ready: Optional[threading.Event] = None,
                       stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if opts.flightrec:
        from kubernetes_tpu.util import metrics as metrics_pkg
        metrics_pkg.flightrec_arm("descheduler",
                                  period_s=opts.flightrec_period)
    d = build_descheduler(opts)
    if opts.metrics_port:
        from kubernetes_tpu.cmd.scheduler import _serve_debug
        _serve_debug(opts.metrics_port, service="descheduler",
                     health=_descheduler_health(opts.master))
    if opts.one_shot:
        rep = d.run_once(force=True)
        json.dump({"declined": rep.declined, "error": rep.error,
                   "score_before": rep.score_before,
                   "score_mandatory": rep.score_mandatory,
                   "score_after": rep.score_after,
                   "proposed": rep.proposed, "committed": rep.committed,
                   "conflicts": rep.conflicts,
                   "voluntary_dropped": rep.voluntary_dropped,
                   "nodes_drained": rep.nodes_drained,
                   "nodes_emptied": rep.nodes_emptied,
                   "undrainable": rep.undrainable}, sys.stdout)
        sys.stdout.write("\n")
        return 0 if not rep.error else 1
    d.run()
    print("kube-descheduler running", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    d.stop()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return descheduler_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
