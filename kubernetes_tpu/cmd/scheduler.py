"""kube-scheduler binary (ref: plugin/cmd/kube-scheduler/app/server.go:74-102).

``--algorithm tpu-batch`` swaps the serial scheduleOne driver for the TPU
wave scheduler (the framework's flagship path); the default provider keeps
the serial reference semantics.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["scheduler_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-scheduler", exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--algorithm-provider", "--algorithm_provider",
                   default="DefaultProvider")
    p.add_argument("--policy-config-file", "--policy_config_file", default="")
    p.add_argument("--algorithm", default="serial",
                   choices=["serial", "tpu-batch"])
    p.add_argument("--wave-period", type=float, default=0.05,
                   help="tpu-batch: max wait to accumulate a wave")
    p.add_argument("--solver-addr", "--solver_addr", default="",
                   help="tpu-batch: HOST:PORT of a shared kube-solverd "
                        "daemon (cmd/solverd). Waves solve there — many "
                        "scheduler workers share one hot solver runtime — "
                        "with automatic in-process fallback when the "
                        "daemon is absent, busy, or unhealthy. Empty = "
                        "always solve in-process.")
    p.add_argument("--solver-fallback", "--solver_fallback",
                   choices=("inprocess", "requeue"), default="inprocess",
                   help="tpu-batch with --solver-addr: what a wave does "
                        "while the daemon is away. 'inprocess' solves "
                        "locally (correct when nothing will respawn the "
                        "daemon; at full shape the cold compile can stall "
                        "the worker for minutes); 'requeue' fails the "
                        "wave — pods requeue and the next wave retries "
                        "the daemon, which a supervisor (hack/churn_mp "
                        "--chaos, docs/design/ha.md) respawns within "
                        "seconds. CAS-convergent either way.")
    p.add_argument("--pipeline", action="store_true",
                   help="tpu-batch: speculative double-buffered wave "
                        "scheduling — overlap the encode of wave k+1 "
                        "(against the predicted post-commit state) and "
                        "its solve dispatch with the solve/commit of "
                        "wave k. Committed decisions stay bit-identical "
                        "to the causal path: every speculation is "
                        "verified against actual bind outcomes and store "
                        "deltas before wave k+1 may commit, and "
                        "divergence re-encodes first. Composes with "
                        "--solver-addr (the speculative encode overlaps "
                        "the daemon round-trip).")
    p.add_argument("--mesh", choices=("auto", "on", "off"), default="auto",
                   help="tpu-batch: device-mesh solve for in-process waves "
                        "(parallel/mesh.py): auto shards waves above the "
                        "node floor over the attached device mesh when >1 "
                        "device exists (real multi-chip, or CPU sub-meshes "
                        "via XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N). "
                        "Decisions stay bit-identical to the single-device "
                        "path. With --solver-addr the daemon's own --mesh "
                        "governs the shared solve; this flag still covers "
                        "the in-process fallback.")
    p.add_argument("--pods-axis", "--pods_axis", type=int, default=1,
                   help="mesh 'pods' axis length (see kube-solverd "
                        "--pods-axis)")
    p.add_argument("--prewarm", action="store_true",
                   help="kube-slipstream: at boot, compile the wave-size "
                        "bucket ladder implied by the live cluster off "
                        "the wave loop (in-process solve path only; with "
                        "--solver-addr the daemon's own --prewarm covers "
                        "the shared programs). compile_prewarm_ready on "
                        "/metrics flips to 1 when done. The fill-trigger "
                        "prewarm thread runs regardless unless "
                        "KTPU_PREWARM=off.")
    p.add_argument("--event-qps", "--event_qps", type=float, default=50.0,
                   help="client-side event rate limit (successor "
                        "codebases' --event-qps; 0 disables)")
    p.add_argument("--event-burst", "--event_burst", type=int, default=100)
    p.add_argument("--metrics-port", "--metrics_port", type=int, default=0,
                   help="serve /metrics, /healthz and /debug/pprof on this "
                        "port (0 disables; ref: the reference's healthz+"
                        "pprof mounts on every binary, master.go:431-435)")
    p.add_argument("--trace", action="store_true",
                   help="kube-trace: record spans for every wave "
                        "(drain/prepare/encode/solve/commit) into this "
                        "process's ring buffer and propagate trace context "
                        "to the apiserver and kube-solverd; drain via "
                        "GET /debug/trace on --metrics-port. Default OFF — "
                        "the disabled path is a single branch per call "
                        "site (docs/design/observability.md).")
    p.add_argument("--flightrec", action="store_true",
                   help="kube-flightrec: sample every metric series into "
                        "a per-process (monotonic_ns, value) ring from "
                        "boot, served incrementally at GET /debug/vars on "
                        "--metrics-port. Default OFF (the first "
                        "/debug/vars pull arms sampling lazily anyway).")
    p.add_argument("--flightrec-period", "--flightrec_period", type=float,
                   default=1.0,
                   help="flight recorder sample period, seconds")
    return p


def _serve_debug(port: int, service: str = "scheduler",
                 health=None) -> None:
    """Shared observability server for the non-apiserver binaries
    (scheduler, solverd): /metrics, deep /healthz (+ /healthz/ping
    liveness), /debug/pprof, /debug/trace, /debug/vars.

    ``health`` is a zero-arg callable returning componentstatus-style
    ``(payload dict, ok bool)`` — each binary probes ITS dependencies
    (scheduler: master + solverd connectivity; solverd: solver backend +
    mesh devices). None keeps the bare liveness 200."""
    import json
    import urllib.parse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kubernetes_tpu.util import metrics as metrics_pkg
    from kubernetes_tpu.util import pprof as pprof_util
    from kubernetes_tpu.util.metrics import default_registry

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_GET(self):
            ctype = "text/plain; charset=utf-8"
            if self.path.startswith("/debug/pprof"):
                parsed = urllib.parse.urlsplit(self.path)
                which = parsed.path[len("/debug/pprof"):].strip("/")
                q = dict(urllib.parse.parse_qsl(parsed.query))
                body = pprof_util.handle(which, q.get("seconds", ""),
                                         q.get("format", ""))
                code = 200 if body is not None else 404
                body = body if body is not None else "not found"
            elif self.path == "/healthz/ping":
                code, body = 200, "ok"  # liveness: process up, serving
            elif self.path.startswith("/healthz"):
                if health is None:
                    code, body = 200, "ok"
                else:
                    try:
                        payload, ok = health()
                    except Exception as e:
                        payload, ok = {"healthy": False,
                                       "error": repr(e)}, False
                    code = 200 if ok else 503
                    body, ctype = json.dumps(payload), "application/json"
            elif self.path == "/metrics":
                code, body = 200, default_registry().render_text()
            elif self.path.startswith("/debug/vars"):
                # kube-flightrec shard: incremental metric time-series
                # past the ?since=<ns> cursor; the first pull arms the
                # sampler (lazy, like the kube-trace span ring)
                q = dict(urllib.parse.parse_qsl(
                    urllib.parse.urlsplit(self.path).query))
                if not metrics_pkg.flightrec_armed():
                    metrics_pkg.flightrec_arm(service)
                try:
                    since = int(q.get("since", "0") or "0")
                except ValueError:
                    since = 0
                code = 200
                body = json.dumps(metrics_pkg.flightrec_vars(since))
                ctype = "application/json"
            elif self.path.startswith("/debug/trace"):
                # kube-trace shard drain (?peek=1 reads without resetting
                # the cursor) — the churn harness merges every process's
                # shard into one Perfetto-loadable file
                from kubernetes_tpu.util import tracing
                q = dict(urllib.parse.parse_qsl(
                    urllib.parse.urlsplit(self.path).query))
                code = 200
                body = json.dumps(tracing.drain(
                    reset=q.get("peek") not in ("1", "true")))
            else:
                code, body = 404, "not found"
            raw = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

    srv = ThreadingHTTPServer(("127.0.0.1", port), H)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name=f"{service}-debug-http").start()


def _scheduler_health(master: str, solver_addr: str):
    """Deep-health probe set for the scheduler binary: can it reach the
    binder (the apiserver it commits waves to) and — when configured —
    the shared solver daemon. componentstatus-style payload, non-200
    handled by the caller."""
    import urllib.parse

    from kubernetes_tpu import probe

    def health():
        items = []
        ok = True
        u = urllib.parse.urlparse(master)
        st, msg = probe.probe_http(u.hostname, u.port, "/healthz/ping")
        items.append({"name": "binder", "status": st,
                      "message": msg if st != probe.SUCCESS else
                      f"apiserver {master} reachable"})
        ok &= st == probe.SUCCESS
        if solver_addr:
            host, _, sport = solver_addr.partition(":")
            st, msg = probe.probe_tcp(host or "127.0.0.1", int(sport))
            items.append({"name": "solver", "status": st,
                          "message": msg if st != probe.SUCCESS else
                          f"kube-solverd {solver_addr} reachable"})
            # a dead daemon is DEGRADED, not down: RemoteSolver falls
            # back to in-process solves, so it does not fail liveness
        return ({"kind": "ComponentStatusList", "healthy": bool(ok),
                 "items": items}, bool(ok))

    return health


def build_scheduler(opts):
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport
    from kubernetes_tpu.client.record import AsyncEventRecorder, EventRecorder
    from kubernetes_tpu.scheduler import plugins as schedplugins
    from kubernetes_tpu.scheduler.driver import ConfigFactory, Scheduler

    # the user-agent is the fairshed credential: scheduler traffic
    # (reflector list/watch + the wave commit leg) rides the apiserver's
    # system flow, structurally isolated from workload create floods
    client = Client(HTTPTransport(opts.master, user_agent="kube-scheduler"))
    # async like the reference's StartRecording goroutine (event.go:53):
    # recording must never stall scheduleOne/wave loops on an API write
    recorder = AsyncEventRecorder(
        EventRecorder(client, api.EventSource(
            component=api.DefaultSchedulerName)),
        qps=getattr(opts, "event_qps", 50.0),
        burst=getattr(opts, "event_burst", 100))
    factory = ConfigFactory(client)

    policy = None
    if opts.policy_config_file:
        with open(opts.policy_config_file) as f:
            policy = schedplugins.load_policy(f.read())
    config = factory.create(provider=opts.algorithm_provider,
                            policy=policy, recorder=recorder,
                            solver_addr=getattr(opts, "solver_addr", ""),
                            pipeline=getattr(opts, "pipeline", False),
                            mesh=getattr(opts, "mesh", "auto"),
                            pods_axis=getattr(opts, "pods_axis", 1),
                            solver_fallback=getattr(
                                opts, "solver_fallback", "inprocess"),
                            prewarm=getattr(opts, "prewarm", False))
    if getattr(opts, "pipeline", False) and opts.algorithm != "tpu-batch":
        print("kube-scheduler: --pipeline requires --algorithm tpu-batch; "
              "ignoring", file=sys.stderr)
    if opts.algorithm == "tpu-batch":
        from kubernetes_tpu.models.policy import (UnsupportedPolicy,
                                                  batch_policy_from)
        from kubernetes_tpu.scheduler.tpu_batch import BatchScheduler
        from kubernetes_tpu.util import warmstart
        # a restarted scheduler reuses compiled wave programs and router
        # calibrations instead of re-paying shape_setup_s/compile_s
        warmstart.enable()
        try:
            batch_policy = batch_policy_from(opts.algorithm_provider, policy)
        except UnsupportedPolicy as e:
            # never silently solve a different problem than configured:
            # fall back to the serial driver, which runs the plugin
            # functions directly
            print(f"kube-scheduler: tpu-batch cannot model this "
                  f"configuration ({e}); falling back to serial",
                  file=sys.stderr)
            return factory, Scheduler(config)
        return factory, BatchScheduler(config, factory, client,
                                       wave_linger_s=opts.wave_period,
                                       batch_policy=batch_policy)
    return factory, Scheduler(config)


def scheduler_server(argv: List[str],
                     ready: Optional[threading.Event] = None,
                     stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if getattr(opts, "trace", False):
        from kubernetes_tpu.util import tracing
        tracing.enable("scheduler")
    if getattr(opts, "flightrec", False):
        from kubernetes_tpu.util import metrics as metrics_pkg
        metrics_pkg.flightrec_arm(
            "scheduler", period_s=getattr(opts, "flightrec_period", 1.0))
    factory, sched = build_scheduler(opts)
    if getattr(opts, "metrics_port", 0):
        _serve_debug(opts.metrics_port, service="scheduler",
                     health=_scheduler_health(
                         opts.master, getattr(opts, "solver_addr", "")))
    sched.run()
    print(f"kube-scheduler running ({opts.algorithm})", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    sched.stop()
    factory.stop()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return scheduler_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
