"""kube-apiserver binary (ref: cmd/kube-apiserver/app/server.go:107-153).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["apiserver_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kube-apiserver", exit_on_error=False)
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--portal-net", "--portal_net", default="10.0.0.0/24")
    # default shared with apiserver.master.DEFAULT_ADMISSION — a plugin
    # added to the in-process default (PriorityDefault was the incident:
    # priorityClassName silently unresolved in the multi-process
    # topology) must ship in the binary's default too
    from kubernetes_tpu.apiserver.master import DEFAULT_ADMISSION
    p.add_argument("--admission-control", "--admission_control",
                   default=",".join(DEFAULT_ADMISSION))
    p.add_argument("--token-auth-file", "--token_auth_file", default="")
    p.add_argument("--basic-auth-file", "--basic_auth_file", default="")
    p.add_argument("--authorization-policy-file",
                   "--authorization_policy_file", default="")
    p.add_argument("--cloud-provider", "--cloud_provider", default="")
    p.add_argument("--event-ttl", "--event_ttl", type=float, default=3600.0)
    p.add_argument("--kubelet-port", "--kubelet_port", type=int, default=10250)
    p.add_argument("--data-dir", "--data_dir", default="",
                   help="persist cluster state here (WAL + snapshots); "
                        "empty = in-memory only (the etcd_servers analog: "
                        "ref cmd/kube-apiserver/app/server.go etcd flags)")
    p.add_argument("--store-server", "--store_server", default="",
                   help="HOST:PORT of a kube-store process to use instead "
                        "of an in-process store (the --etcd_servers "
                        "analog); lets several apiserver workers share one "
                        "store")
    p.add_argument("--store-shards", "--store_shards", type=int, default=1,
                   help="kube-stripe: shard the in-process store's "
                        "keyspace by namespace hash into this many shards "
                        "(power of two). Ignored with --store-server (the "
                        "kube-store process takes --shards itself); 1 = "
                        "the unsharded twin.")
    p.add_argument("--allow-privileged", "--allow_privileged",
                   action="store_true",
                   help="if set, allow containers to request privileged "
                        "mode (ref: the reference's --allow_privileged)")
    p.add_argument("--cors-allowed-origins", "--cors_allowed_origins",
                   default="",
                   help="comma-separated allowed CORS origins; each entry "
                        "is a regular expression matched against the ENTIRE "
                        "Origin header (anchored fullmatch — "
                        "'https://example\\.com' does NOT admit "
                        "'https://example.com.evil.net'; use an explicit "
                        "'.*\\.example\\.com' style pattern for subdomains). "
                        "Empty disables CORS (ref: the reference's "
                        "--cors_allowed_origins)")
    p.add_argument("--read-only-port", "--read_only_port", type=int,
                   default=0,
                   help="serve a GET-only, unauthenticated, rate-limited "
                        "companion port (the kubernetes-ro backend; the "
                        "reference defaults it to 7080). 0 disables.")
    p.add_argument("--api-rate", "--api_rate", type=float, default=10.0,
                   help="read-only port rate limit, QPS")
    p.add_argument("--api-burst", "--api_burst", type=int, default=200,
                   help="read-only port burst size")
    p.add_argument("--reuse-port", "--reuse_port", action="store_true",
                   help="bind with SO_REUSEPORT so several apiserver "
                        "worker processes share one listen port")
    p.add_argument("--watch-lag-limit", "--watch_lag_limit", type=int,
                   default=65536,
                   help="per-watch-connection event queue bound: a "
                        "watcher lagging past it is dropped to resync "
                        "(410 ERROR frame; the client re-lists). "
                        "0 disables.")
    p.add_argument("--fairshed", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="kube-fairshed flow-classified admission "
                        "(docs/design/apiserver-hotpath.md): every "
                        "request rides an isolated per-flow inflight "
                        "budget (system / workload / best-effort) and "
                        "excess sheds with 429 + a measured-drain "
                        "Retry-After. Default budgets are generous "
                        "enough to be invisible below overload; "
                        "--no-fairshed disables the layer entirely.")
    p.add_argument("--fairshed-backlog", "--fairshed_backlog", type=int,
                   default=0,
                   help="workload backlog governor: shed pod creates "
                        "once created-but-unbound pods exceed this, "
                        "with Retry-After derived from the measured "
                        "bind drain rate — bounds the invisible e2e "
                        "backlog queue under overload. 0 disables. "
                        "Exact at one worker by construction; an "
                        "SO_REUSEPORT fleet stays exact through the "
                        "--share-seg cross-worker ledger.")
    p.add_argument("--share-seg", "--share_seg", default="",
                   help="path to a kube-share segment file "
                        "(apiserver/share.py), created by the parent/"
                        "harness with one block per worker: cross-"
                        "process frame-cache seeding + the cross-worker "
                        "fairshed backlog ledger. Empty disables.")
    p.add_argument("--share-worker", "--share_worker", type=int, default=-1,
                   help="this worker's block index in --share-seg "
                        "(0-based; required with --share-seg)")
    p.add_argument("--trace", action="store_true",
                   help="kube-trace: record handler/store spans for "
                        "requests carrying an X-KTPU-Trace header (a "
                        "scheduler wave's commit leg); drain via "
                        "GET /debug/trace. Default OFF — untraced "
                        "requests never record.")
    p.add_argument("--flightrec", action="store_true",
                   help="kube-flightrec: sample every metric series into "
                        "the per-process (monotonic_ns, value) ring from "
                        "boot, served incrementally at GET /debug/vars. "
                        "Default OFF (lazy: the first /debug/vars pull "
                        "arms sampling anyway; this flag just makes the "
                        "rings span the whole run).")
    p.add_argument("--flightrec-period", "--flightrec_period", type=float,
                   default=1.0, help="flight recorder sample period, "
                        "seconds")
    return p


def build_server(opts, ready_event: Optional[threading.Event] = None):
    from kubernetes_tpu.apiserver.http import APIServer
    from kubernetes_tpu.apiserver.master import Master, MasterConfig
    from kubernetes_tpu.cloudprovider import get_provider

    from kubernetes_tpu import auth as authpkg
    from kubernetes_tpu import capabilities

    # per-binary capability gate (ref: cmd server.go:186 + capabilities.go):
    # validation consults it when admitting privileged containers
    capabilities.setup(getattr(opts, "allow_privileged", False))

    authenticators = []
    if opts.token_auth_file:
        with open(opts.token_auth_file) as f:
            authenticators.append(authpkg.load_token_file(f.read()))
    if opts.basic_auth_file:
        with open(opts.basic_auth_file) as f:
            authenticators.append(authpkg.BasicAuthAuthenticator(
                authpkg.load_password_file(f.read())))
    authenticator = (authpkg.UnionAuthenticator(*authenticators)
                     if authenticators else None)
    authorizer = None
    if opts.authorization_policy_file:
        from kubernetes_tpu.auth.abac import ABACAuthorizer
        with open(opts.authorization_policy_file) as f:
            authorizer = ABACAuthorizer.from_text(f.read())

    store = None
    store_shards = getattr(opts, "store_shards", 1)
    if getattr(opts, "store_server", ""):
        from kubernetes_tpu.storage.remote import RemoteStore
        store = RemoteStore(opts.store_server)
    elif getattr(opts, "data_dir", ""):
        if store_shards > 1:
            from kubernetes_tpu.storage.stripestore import DurableStripedStore
            store = DurableStripedStore(opts.data_dir, shards=store_shards)
        else:
            from kubernetes_tpu.storage.durable import DurableStore
            store = DurableStore(opts.data_dir)
    elif store_shards > 1:
        from kubernetes_tpu.storage.stripestore import StripedStore
        store = StripedStore(shards=store_shards)

    master = Master(MasterConfig(
        store=store,
        portal_net=opts.portal_net,
        admission_control=tuple(
            x for x in opts.admission_control.split(",") if x),
        authorizer=authorizer,
        event_ttl_seconds=opts.event_ttl,
        cloud=get_provider(opts.cloud_provider) if opts.cloud_provider else None,
    ))
    cors = [o for o in
            getattr(opts, "cors_allowed_origins", "").split(",") if o]
    share = ledger = None
    if getattr(opts, "share_seg", ""):
        from kubernetes_tpu.apiserver.share import ShareSegment, SharedLedger
        share = ShareSegment(opts.share_seg,
                             worker_index=getattr(opts, "share_worker", -1))
        ledger = SharedLedger(share)
    fs = None
    if getattr(opts, "fairshed", True):
        from kubernetes_tpu.apiserver.fairshed import FairShed
        fs = FairShed(backlog_limit=getattr(opts, "fairshed_backlog", 0),
                      ledger=ledger)
    srv = APIServer(master, host=opts.address, port=opts.port,
                    authenticator=authenticator,
                    kubelet_port=opts.kubelet_port,
                    reuse_port=getattr(opts, "reuse_port", False),
                    cors_allowed_origins=cors,
                    watch_lag_limit=getattr(opts, "watch_lag_limit", 65536),
                    fairshed=fs, share=share)
    ro_port = getattr(opts, "read_only_port", 0)
    if ro_port:
        # the kubernetes-ro companion (ref: cmd server.go:267-276):
        # GET-only, unauthenticated, token-bucket throttled, same master
        from kubernetes_tpu.util.throttle import TokenBucketRateLimiter
        srv.read_only_server = APIServer(
            master, host=opts.address, port=ro_port,
            kubelet_port=opts.kubelet_port,
            cors_allowed_origins=cors,
            reuse_port=getattr(opts, "reuse_port", False),
            read_only=True,
            rate_limiter=TokenBucketRateLimiter(opts.api_rate,
                                                opts.api_burst))
    return srv


def apiserver_server(argv: List[str],
                     ready: Optional[threading.Event] = None,
                     stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if getattr(opts, "trace", False):
        from kubernetes_tpu.util import tracing
        tracing.enable("apiserver")
    srv = build_server(opts)
    if getattr(opts, "flightrec", False):
        from kubernetes_tpu.util import metrics as metrics_pkg
        metrics_pkg.flightrec_arm(
            "apiserver", period_s=getattr(opts, "flightrec_period", 1.0))
        metrics_pkg.flightrec_watch(srv.metrics_registry)
    srv.start()
    print(f"kube-apiserver listening on {srv.base_url}", file=sys.stderr)
    ro = getattr(srv, "read_only_server", None)
    if ro is not None:
        ro.start()
        print(f"read-only (kubernetes-ro) listening on {ro.base_url}",
              file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    if ro is not None:
        ro.stop()
    srv.stop()
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return apiserver_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
