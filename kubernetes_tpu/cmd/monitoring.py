"""cluster-monitoring binary — the heapster-analog aggregator
(ref: cluster/addons/cluster-monitoring deployment), grown into the
kube-flightrec control-plane aggregator: with ``--flightrec-target``
it also pulls every named process's /debug/vars metric time-series
shard, merges them on the shared monotonic axis, evaluates the churn
SLO rule set live, and serves the merged timeline + alarm transitions
at /api/v1/timeline and /api/v1/alarms."""

from __future__ import annotations

import argparse
import sys
import threading
from typing import List, Optional

__all__ = ["monitoring_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cluster-monitoring",
                                exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080",
                   help="apiserver URL")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10251)
    p.add_argument("--kubelet-port", "--kubelet_port", type=int,
                   default=10250)
    p.add_argument("--period", type=float, default=5.0,
                   help="scrape period seconds")
    p.add_argument("--flightrec-target", "--flightrec_target",
                   action="append", default=[],
                   help="NAME=URL[,WORKERS] of a control-plane process "
                        "debug server to pull /debug/vars from "
                        "(repeatable; WORKERS>1 = SO_REUSEPORT worker "
                        "processes sharing the URL's port, each poll "
                        "drains until all pids answered). E.g. "
                        "apiserver=http://127.0.0.1:8080,4")
    p.add_argument("--flightrec-period", "--flightrec_period", type=float,
                   default=2.0, help="flightrec pull period seconds")
    return p


def parse_flightrec_targets(specs: List[str]) -> List[dict]:
    out = []
    for spec in specs:
        name, _, rest = spec.partition("=")
        url, _, workers = rest.partition(",")
        if not name or not url:
            raise ValueError(f"bad --flightrec-target {spec!r} "
                             "(want NAME=URL[,WORKERS])")
        out.append({"name": name, "url": url,
                    "workers": int(workers) if workers else 1})
    return out


def monitoring_server(argv: List[str],
                      ready: Optional[threading.Event] = None,
                      stop: Optional[threading.Event] = None) -> int:
    from kubernetes_tpu.addons.monitoring import (
        Monitoring,
        http_kubelet_fetcher,
    )
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport

    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    client = Client(HTTPTransport(opts.master))
    mon = Monitoring(client, fetch=http_kubelet_fetcher(opts.kubelet_port),
                     period_s=opts.period, host=opts.address,
                     port=opts.port)
    flight = None
    if opts.flightrec_target:
        from kubernetes_tpu.addons.monitoring import FlightAggregator
        try:
            targets = parse_flightrec_targets(opts.flightrec_target)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        flight = FlightAggregator(targets,
                                  period_s=opts.flightrec_period).start()
        mon.flight = flight
    mon.start()
    print(f"cluster-monitoring on http://{opts.address}:{mon.port} "
          f"(/metrics, /api/v1/model"
          + (", /api/v1/timeline, /api/v1/alarms" if flight else "")
          + ")", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    if flight is not None:
        flight.stop(final_poll=False)
    mon.stop()
    return 0


def main() -> int:
    return monitoring_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
