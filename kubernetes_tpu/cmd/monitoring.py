"""cluster-monitoring binary — the heapster-analog aggregator
(ref: cluster/addons/cluster-monitoring deployment)."""

from __future__ import annotations

import argparse
import sys
import threading
from typing import List, Optional

__all__ = ["monitoring_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cluster-monitoring",
                                exit_on_error=False)
    p.add_argument("--master", default="http://127.0.0.1:8080",
                   help="apiserver URL")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10251)
    p.add_argument("--kubelet-port", "--kubelet_port", type=int,
                   default=10250)
    p.add_argument("--period", type=float, default=5.0,
                   help="scrape period seconds")
    return p


def monitoring_server(argv: List[str],
                      ready: Optional[threading.Event] = None,
                      stop: Optional[threading.Event] = None) -> int:
    from kubernetes_tpu.addons.monitoring import (
        Monitoring,
        http_kubelet_fetcher,
    )
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport

    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    client = Client(HTTPTransport(opts.master))
    mon = Monitoring(client, fetch=http_kubelet_fetcher(opts.kubelet_port),
                     period_s=opts.period, host=opts.address,
                     port=opts.port).start()
    print(f"cluster-monitoring on http://{opts.address}:{mon.port} "
          f"(/metrics, /api/v1/model)", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    mon.stop()
    return 0


def main() -> int:
    return monitoring_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
