"""kubelet binary (ref: cmd/kubelet/app/server.go RunKubelet:324).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

__all__ = ["kubelet_server", "main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kubelet", exit_on_error=False)
    p.add_argument("--api-servers", "--api_servers",
                   default="http://127.0.0.1:8080")
    p.add_argument("--hostname-override", "--hostname_override", default="")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10250)
    p.add_argument("--root-dir", "--root_dir", default="/var/lib/kubelet")
    p.add_argument("--config", default="",
                   help="static pod manifest dir (file source)")
    p.add_argument("--manifest-url", "--manifest_url", default="")
    p.add_argument("--sync-frequency", "--sync_frequency",
                   type=float, default=10.0)
    p.add_argument("--register-node", "--register_node", action="store_true",
                   help="create our Node object on startup")
    p.add_argument("--node-cpu", default="4")
    p.add_argument("--node-memory", default="8Gi")
    p.add_argument("--allow-privileged", "--allow_privileged",
                   action="store_true",
                   help="if set, allow containers to request privileged "
                        "mode (ref: the reference's --allow_privileged)")
    p.add_argument("--container-runtime", "--container_runtime",
                   default="process", choices=["process", "fake"],
                   help="process = real local process groups with the "
                        "native pause sandbox; fake = in-memory double")
    return p


def build_kubelet(opts):
    import socket

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport
    from kubernetes_tpu.client.record import AsyncEventRecorder, EventRecorder
    from kubernetes_tpu.kubelet.config import (ApiserverSource, FileSource,
                                               HTTPSource, PodConfig)
    from kubernetes_tpu.kubelet.kubelet import Kubelet
    from kubernetes_tpu.kubelet.runtime import FakeRuntime
    from kubernetes_tpu.kubelet.server import KubeletServer
    from kubernetes_tpu.volume.plugins import (ExecMounter,
                                               RefusingDiskManager,
                                               new_default_plugin_mgr)

    from kubernetes_tpu import capabilities

    # ref: cmd/kubelet/app/server.go:333 SetupCapabilities
    capabilities.setup(getattr(opts, "allow_privileged", False))

    hostname = opts.hostname_override or socket.gethostname()
    client = Client(HTTPTransport(opts.api_servers, user_agent="kubelet"))
    # async like the scheduler (and the reference's StartRecording
    # goroutine, event.go:53): the sync loop was posting events
    # SYNCHRONOUSLY, stalling pod lifecycle on an apiserver round-trip
    # per event — a slow apiserver turned every container start into a
    # blocking write. Bounded queue + background worker; drops are
    # counted (event_recorder_dropped_total), never a stalled sync loop.
    recorder = AsyncEventRecorder(
        EventRecorder(client, api.EventSource(component="kubelet",
                                              host=hostname)),
        qps=50.0, burst=100)
    # the runtime seam (ref: dockertools): ProcessRuntime runs pods as real
    # local process groups with the native pause sandbox; FakeRuntime is
    # the in-memory double for tests/demos
    if opts.container_runtime == "process":
        from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime

        runtime = ProcessRuntime(opts.root_dir)
    else:
        runtime = FakeRuntime()
    # real mounter so NFS mounts actually happen (or fail loudly); PD attach
    # refuses outright — there is no cloud disk backend on this host — so
    # such pods get a mount error instead of an empty dir
    volume_mgr = new_default_plugin_mgr(opts.root_dir, kubelet_client=client,
                                        mounter=ExecMounter(),
                                        disk_manager=RefusingDiskManager())
    # service env var injection (ref: cmd/kubelet/app/server.go wiring a
    # cache.NewListWatchFromClient("services") into kl.serviceLister):
    # a reflector-backed cache so pod starts never block on the apiserver
    from kubernetes_tpu.client.cache import Reflector, Store

    svc_store = Store()
    Reflector(client.services(api.NamespaceAll).list_watch(), svc_store,
              name="kubelet-services").run()

    kubelet = Kubelet(hostname, runtime, client=client, recorder=recorder,
                      resync_period=opts.sync_frequency,
                      volume_mgr=volume_mgr, service_lister=svc_store.list)

    pod_config = PodConfig()
    sources = [ApiserverSource(pod_config, client, hostname)]
    if opts.config:
        sources.append(FileSource(pod_config, opts.config, hostname,
                                  period=opts.sync_frequency))
    if opts.manifest_url:
        sources.append(HTTPSource(pod_config, opts.manifest_url, hostname,
                                  period=opts.sync_frequency))

    if opts.register_node:
        from kubernetes_tpu.api import errors
        from kubernetes_tpu.api.quantity import Quantity

        def register():
            node = api.Node(
                metadata=api.ObjectMeta(name=hostname),
                spec=api.NodeSpec(capacity={
                    api.ResourceCPU: Quantity(opts.node_cpu),
                    api.ResourceMemory: Quantity(opts.node_memory)}))
            # keep retrying: the apiserver routinely comes up after the
            # kubelet in a multi-process boot (ref: NodeController
            # RegisterNodes retry loop)
            import time as _time
            while True:
                try:
                    client.nodes().create(node)
                    return
                except errors.StatusError as e:
                    if errors.is_already_exists(e):
                        return
                    print(f"kubelet: node registration rejected: {e}",
                          file=sys.stderr)
                except Exception as e:
                    print(f"kubelet: apiserver unreachable, retrying "
                          f"registration: {e}", file=sys.stderr)
                _time.sleep(1.0)

        threading.Thread(target=register, daemon=True,
                         name="kubelet-register").start()

    stats = None
    if opts.container_runtime == "process":
        # per-container /proc accounting: each container is a real process
        from kubernetes_tpu.kubelet.stats import ProcessRuntimeStatsProvider
        stats = ProcessRuntimeStatsProvider(runtime)
    server = KubeletServer(kubelet, host=opts.address, port=opts.port,
                           stats=stats)
    return kubelet, pod_config, sources, server


def kubelet_server(argv: List[str],
                   ready: Optional[threading.Event] = None,
                   stop: Optional[threading.Event] = None) -> int:
    try:
        opts = build_parser().parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    kubelet, pod_config, sources, server = build_kubelet(opts)
    for src in sources:
        src.run()
    kubelet.run(pod_config)
    server.start()
    print(f"kubelet {kubelet.hostname} serving on "
          f"{opts.address}:{server.port}", file=sys.stderr)
    if ready is not None:
        ready.set()
    stop = stop or threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    server.stop()
    for src in sources:
        src.stop()
    kubelet.stop()
    rec = getattr(kubelet, "recorder", None)
    if rec is not None and hasattr(rec, "stop"):
        rec.stop()  # drain + join the async posting worker
    return 0


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    return kubelet_server(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
