"""kube-version-change — convert API objects between wire versions
(ref: cmd/kube-version-change/version_change.go: reads an object in any
registered version, writes it in the requested one).

Usage: python -m kubernetes_tpu.cmd.version_change -i in.yaml -o out.json \
           --version v1beta1
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

import yaml

__all__ = ["version_change", "main"]


def version_change(argv: List[str],
                   stdin=None, stdout=None) -> int:
    from kubernetes_tpu.api.latest import VERSIONS, scheme

    p = argparse.ArgumentParser(prog="kube-version-change",
                                exit_on_error=False)
    p.add_argument("--input", "-i", default="-")
    p.add_argument("--output", "-o", default="-")
    p.add_argument("--version", "-v", default=scheme.default_version,
                   choices=list(VERSIONS))
    p.add_argument("--format", choices=["json", "yaml"], default="json")
    try:
        opts = p.parse_args(argv)
    except argparse.ArgumentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    try:
        if opts.input == "-":
            text = stdin.read()
        else:
            with open(opts.input, "r", encoding="utf-8") as f:
                text = f.read()
        data = yaml.safe_load(text)
        if not isinstance(data, dict):
            raise ValueError("input is not an object manifest")
        wire = scheme.convert_wire(data, data.get("apiVersion", ""),
                                   opts.version)
    except Exception as e:
        print(f"error: unable to convert: {e}", file=sys.stderr)
        return 1
    out = json.dumps(wire, indent=2, sort_keys=True) + "\n" \
        if opts.format == "json" else yaml.safe_dump(wire, sort_keys=True)
    if opts.output == "-":
        stdout.write(out)
    else:
        with open(opts.output, "w", encoding="utf-8") as f:
            f.write(out)
    return 0


def main() -> int:
    return version_change(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
