"""StoreHelper — typed CRUD over the versioned KV.

Rebuild of the reference's EtcdHelper (ref: pkg/tools/etcd_helper.go:36-345 +
etcd_helper_watch.go:64-95): encodes/decodes API objects with the runtime
Scheme, maps the store's modified_index to ObjectMeta.resource_version, and
provides the read-modify-CAS ``atomic_update`` loop every registry and
controller relies on for optimistic concurrency.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from kubernetes_tpu.runtime.clone import deep_clone
from typing import Any, Callable, Optional, Type

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import errors
from kubernetes_tpu.api.meta import accessor
from kubernetes_tpu.storage.memstore import (
    ErrCASConflict,
    ErrIndexOutdated,
    ErrKeyExists,
    ErrKeyNotFound,
    MemStore,
)

__all__ = ["StoreHelper", "parse_watch_resource_version"]


def parse_watch_resource_version(rv: str) -> int:
    """ref: pkg/tools/etcd_helper_watch.go:47-57 ParseWatchResourceVersion —
    '' or '0' means "from now"; otherwise watch resumes after rv."""
    if not rv or rv == "0":
        return 0
    try:
        return int(rv)
    except ValueError:
        raise errors.new_invalid("", rv, [ValueError(f"invalid resourceVersion {rv!r}")])


class StoreHelper:
    # (key, modified_index) -> decoded object. A stored revision is
    # immutable, so its decode is too: lists re-reading a stable cluster
    # and watch pumps fanning one event out to several watchers hit the
    # cache and pay a dict lookup instead of a full codec decode (~170us)
    # — the difference between 250 and 1000 pods/s of churn through the
    # live stack. Bounded FIFO.
    #
    # READ-SHARING CONTRACT: list and watch return the CACHED objects
    # themselves, not copies (the per-read deep_clone was ~13 clones per
    # churned pod — the single largest per-pod CPU item). Safe because
    # bulk/stream consumers only enumerate or encode: the HTTP path
    # serializes to wire bytes, the in-process transport deep-clones both
    # directions (client/client.py InProcessTransport._copy), and
    # controllers build fresh objects from what they read. The only
    # in-tree mutation of a served bulk read is master._stamp_self_links,
    # which writes the same deterministic string every time (idempotent).
    # SINGLE-object reads (extract_obj/delete_obj) stay isolated: the
    # get-mutate-set idiom is legitimate there and they are off the churn
    # hot path. atomic_update isolates before calling update_fn; the
    # DELETED-event resourceVersion rewrite clones explicitly.
    #
    # Sized to hold a full-shape churn working set (50k pods): at 8192 a
    # pod created early in the run was evicted by the time its bind
    # committed, so every batched bind paid a cold decode + the bind
    # event's prev_kv decode — two full codec passes back on the hot
    # path the cache exists to remove.
    _DECODE_CACHE_MAX = 65536

    def __init__(self, store: MemStore, scheme):
        self.store = store
        self.scheme = scheme
        self._decode_cache: "OrderedDict" = OrderedDict()
        self._decode_lock = threading.Lock()
        self._linkers: list = []  # (key prefix, decorate_fn)

    def register_linker(self, prefix: str, fn) -> None:
        """Register a decorator run ONCE per cached revision at decode time
        (the master registers selfLink stamping per resource prefix). With
        shared reads, decoration must happen before the object becomes
        visible — a post-read stamp would mutate an object other readers
        (watch pumps, concurrent lists) already see, making wire output
        order-dependent."""
        self._linkers.append((prefix if prefix.endswith("/") else prefix + "/",
                              fn))

    # -- encode/decode ------------------------------------------------------
    def _decode(self, kv, isolate: bool = False) -> Any:
        ck = (kv.key, kv.modified_index)
        with self._decode_lock:
            cached = self._decode_cache.get(ck)
        if cached is None:
            cached = self.scheme.decode(kv.value)
            accessor.set_resource_version(cached, str(kv.modified_index))
            for prefix, fn in self._linkers:
                if kv.key.startswith(prefix):
                    fn(cached)
                    break
            with self._decode_lock:
                self._decode_cache[ck] = cached
                while len(self._decode_cache) > self._DECODE_CACHE_MAX:
                    self._decode_cache.popitem(last=False)
        return deep_clone(cached) if isolate else cached

    def _encode(self, obj) -> str:
        # resourceVersion is storage metadata, not payload: clear before
        # encoding, like the reference (etcd_helper.go:236 Versioner).
        rv = accessor.resource_version(obj)
        accessor.set_resource_version(obj, "")
        try:
            return self.scheme.encode(obj)
        finally:
            accessor.set_resource_version(obj, rv)

    # -- CRUD ---------------------------------------------------------------
    def create_obj(self, key: str, obj: Any, ttl: Optional[float] = None) -> Any:
        """ref: etcd_helper.go:205 CreateObj."""
        try:
            kv = self.store.create(key, self._encode(obj), ttl=ttl)
        except ErrKeyExists:
            raise errors.new_already_exists(accessor.kind(obj), accessor.name(obj))
        # decorate the caller's object in place, like the reference
        # (etcd_helper.go CreateObj leaves the passed runtime.Object as
        # the result); nothing stored aliases it — the store holds bytes
        accessor.set_resource_version(obj, str(kv.modified_index))
        return obj

    def set_obj(self, key: str, obj: Any, ttl: Optional[float] = None) -> Any:
        """Write; CAS on the object's resourceVersion when set
        (ref: etcd_helper.go:236 SetObj)."""
        rv = accessor.resource_version(obj)
        try:
            if rv:
                kv = self.store.compare_and_swap(key, self._encode(obj), int(rv), ttl=ttl)
            else:
                kv = self.store.set(key, self._encode(obj), ttl=ttl)
        except ErrCASConflict:
            raise errors.new_conflict(accessor.kind(obj), accessor.name(obj))
        except ErrKeyNotFound:
            raise errors.new_not_found(accessor.kind(obj), accessor.name(obj))
        accessor.set_resource_version(obj, str(kv.modified_index))
        return obj

    def extract_obj(self, key: str, kind: str = "", name: str = "") -> Any:
        """ref: etcd_helper.go:144 ExtractObj."""
        try:
            kv = self.store.get(key)
        except ErrKeyNotFound:
            raise errors.new_not_found(kind or "resource", name or key)
        return self._decode(kv, isolate=True)

    def extract_to_list(self, prefix: str, list_type: Type) -> Any:
        """ref: etcd_helper.go:78 ExtractToList — items + list resourceVersion."""
        kvs, index = self.store.list(prefix)
        lst = list_type()
        lst.items = [self._decode(kv) for kv in kvs]
        lst.metadata.resource_version = str(index)
        return lst

    def delete_obj(self, key: str, kind: str = "", name: str = "") -> Any:
        try:
            prev = self.store.delete(key)
        except ErrKeyNotFound:
            raise errors.new_not_found(kind or "resource", name or key)
        return self._decode(prev, isolate=True)

    def atomic_update(self, key: str, obj_type: Type,
                      update_fn: Callable[[Any], Any],
                      ignore_not_found: bool = False,
                      ttl: Optional[float] = None,
                      max_retries: int = 100) -> Any:
        """Read-modify-CAS loop (ref: etcd_helper.go:311-345 AtomicUpdate).

        ``update_fn`` receives the current object (or a fresh ``obj_type()``
        when absent and ignore_not_found) and returns the desired object; on
        CAS conflict the loop re-reads and retries. This is THE concurrency
        primitive: the scheduler's bind path, status updates, and quota
        decrements all go through it.
        """
        for _ in range(max_retries):
            try:
                kv = self.store.get(key)
                # isolate: update_fn mutates what it is handed
                current = self._decode(kv, isolate=True)
                prev_index: Optional[int] = kv.modified_index
            except ErrKeyNotFound:
                if not ignore_not_found:
                    raise errors.new_not_found(obj_type.__name__, key)
                current = obj_type()
                prev_index = None
            desired = update_fn(current)
            encoded = self._encode(desired)
            try:
                if prev_index is None:
                    kv = self.store.create(key, encoded, ttl=ttl)
                else:
                    kv = self.store.compare_and_swap(key, encoded, prev_index, ttl=ttl)
            except (ErrCASConflict, ErrKeyExists, ErrKeyNotFound):
                continue  # re-read and retry
            # desired is already private (isolated decode above)
            accessor.set_resource_version(desired, str(kv.modified_index))
            return desired
        raise errors.new_conflict(obj_type.__name__, key, "too many CAS retries")

    def atomic_update_many(self, obj_type: Type,
                           updates: "list[tuple[str, Callable[[Any], Any]]]",
                           max_retries: int = 100) -> list:
        """Batched read-modify-CAS over many keys — the wave-commit path
        (SURVEY §7 hard part (e)): one get_many + one compare_and_swap_many
        per round instead of two store round-trips per object. Each key is
        independent (no all-or-nothing): the result list carries, per slot,
        the updated object or the errors.StatusError that update raised /
        the key's terminal store error. CAS-conflicted slots re-read and
        retry, exactly like atomic_update, without holding back the rest.
        """
        results: list = [None] * len(updates)
        live = list(range(len(updates)))
        for _ in range(max_retries):
            if not live:
                return results
            kvs = self.store.get_many([updates[i][0] for i in live])
            batch = []            # (slot, key, encoded, prev_index)
            for i, kv in zip(live, kvs):
                key, fn = updates[i]
                if kv is None:
                    results[i] = errors.new_not_found(
                        obj_type.__name__, key.rsplit("/", 1)[-1])
                    continue
                try:
                    desired = fn(self._decode(kv, isolate=True))
                except errors.StatusError as e:
                    results[i] = e
                    continue
                batch.append((i, key, self._encode(desired), desired,
                              kv.modified_index))
            outcomes = self.store.compare_and_swap_many(
                [(key, enc, prev) for _, key, enc, _, prev in batch])
            live = []
            for (i, key, _enc, desired, _prev), oc in zip(batch, outcomes):
                if isinstance(oc, ErrCASConflict):
                    live.append(i)        # lost a race: re-read and retry
                elif isinstance(oc, ErrKeyNotFound):
                    results[i] = errors.new_not_found(
                        obj_type.__name__, key.rsplit("/", 1)[-1])
                elif isinstance(oc, Exception):
                    results[i] = errors.new_internal_error(str(oc))
                else:
                    accessor.set_resource_version(desired,
                                                  str(oc.modified_index))
                    results[i] = desired
        for i in live:
            results[i] = errors.new_conflict(obj_type.__name__, updates[i][0],
                                             "too many CAS retries")
        return results

    def atomic_bind_evict_many(self, obj_type: Type,
                               items: "list[tuple]",
                               max_retries: int = 100) -> list:
        """kube-preempt's commit primitive: per item, delete every victim
        AND apply the pod update in ONE store transaction (MemStore
        .txn_many) — all-or-nothing per item, items independent. Each
        item is ``(pod_key, update_fn, victims)`` with victims a list of
        ``(victim_key, expected_uid)``; a victim whose uid no longer
        matches is a 409 (the world moved — the caller must re-solve),
        while an already-absent victim counts as evicted. CAS conflicts
        re-read and retry like atomic_update_many."""
        results: list = [None] * len(items)
        live = list(range(len(items)))
        for _ in range(max_retries):
            if not live:
                return results
            txn = []       # (slot, cas_ops, delete_ops, desired)
            for i in live:
                pod_key, fn, victims = items[i]
                try:
                    kv = self.store.get(pod_key)
                except ErrKeyNotFound:
                    results[i] = errors.new_not_found(
                        obj_type.__name__, pod_key.rsplit("/", 1)[-1])
                    continue
                try:
                    desired = fn(self._decode(kv, isolate=True))
                except errors.StatusError as e:
                    results[i] = e
                    continue
                vkeys = [vk for vk, _uid in victims]
                vkvs = self.store.get_many(vkeys)
                deletes = []
                bad = None
                for (vk, want_uid), vkv in zip(victims, vkvs):
                    if vkv is None:
                        continue  # already gone: eviction's goal state
                    if want_uid:
                        have = accessor.uid(self._decode(vkv))
                        if have != want_uid:
                            bad = errors.new_conflict(
                                obj_type.__name__,
                                vk.rsplit("/", 1)[-1],
                                f"victim {vk.rsplit('/', 1)[-1]} uid "
                                f"changed (have {have!r}, want "
                                f"{want_uid!r}) — re-solve required")
                            break
                    deletes.append((vk, vkv.modified_index))
                if bad is not None:
                    results[i] = bad
                    continue
                txn.append((i, [(pod_key, self._encode(desired),
                                 kv.modified_index)], deletes, desired))
            if not txn:
                live = []
                return results
            outcomes = self.store.txn_many(
                [(cas, dels) for _i, cas, dels, _d in txn])
            live = []
            for (i, _cas, _dels, desired), oc in zip(txn, outcomes):
                if isinstance(oc, (ErrCASConflict, ErrKeyNotFound)):
                    live.append(i)   # raced: re-read and retry
                elif isinstance(oc, Exception):
                    results[i] = errors.new_internal_error(str(oc))
                else:
                    accessor.set_resource_version(
                        desired, str(oc[0].modified_index))
                    results[i] = desired
        for i in live:
            results[i] = errors.new_conflict(obj_type.__name__,
                                             items[i][0],
                                             "too many CAS retries")
        return results

    # -- watch --------------------------------------------------------------
    def watch_raw(self, prefix: str, resource_version: str = "",
                  recursive: bool = True,
                  lag_limit: Optional[int] = None) -> watchpkg.Watcher:
        """Raw StoreEvent watch — the encode-once fan-out seam. The HTTP
        layer pulls StoreEvents on its OWN connection thread and maps each
        through translate_event + the apiserver's frame-bytes cache, so
        fanning one store mutation to N watchers costs one decode + one
        encode total instead of a pump thread and a re-encode per watcher.
        ``lag_limit`` bounds the per-watcher queue (see MemStore.watch)."""
        from_index = parse_watch_resource_version(resource_version)
        try:
            return self.store.watch(prefix, from_index=from_index,
                                    recursive=recursive, lag_limit=lag_limit)
        except ErrIndexOutdated as e:
            # Surface as an API-level 410 so clients above the store boundary
            # (Reflector, HTTP clients) share one expired-watch contract.
            raise errors.new_expired(str(e))

    def translate_event_fast(self, ev: watchpkg.Event):
        """Unfiltered translate: ``(event type, resourceVersion, obj_thunk)``
        with NO decode at all — the event type falls out of the store
        action, the resourceVersion out of the store index, and the
        object is only materialized (via the shared decode cache) if the
        apiserver's frame cache actually misses. This is the observer
        fan-out fast path: a cache-hit delivery touches no codec."""
        sev = ev.object
        a = sev.action
        if a == "create":
            return (watchpkg.ADDED, str(sev.kv.modified_index),
                    lambda: self._decode(sev.kv))
        if a in ("set", "compareAndSwap"):
            t = watchpkg.MODIFIED if sev.prev_kv is not None else watchpkg.ADDED
            return (t, str(sev.kv.modified_index),
                    lambda: self._decode(sev.kv))
        if a in ("delete", "expire"):
            if sev.prev_kv is None:
                return None

            def thunk():
                prev_out = deep_clone(self._decode(sev.prev_kv))
                # deleted object carries the deletion resourceVersion
                accessor.set_resource_version(prev_out, str(sev.index))
                return prev_out

            return (watchpkg.DELETED, str(sev.index), thunk)
        return None

    def translate_event(self, ev: watchpkg.Event,
                        filter_fn: Optional[Callable[[Any], bool]] = None
                        ) -> Optional[watchpkg.Event]:
        """Map one raw store Event to its API-level watch Event, or None
        when the object is outside ``filter_fn``. Factored from the watch
        pump so the HTTP byte-writer path and the threaded pump share one
        translation (and one decode cache). Like the reference's
        etcdWatcher filter, an object transitioning out of the filter
        emits DELETED and into it emits ADDED. Raises on undecodable
        payloads — callers surface an ERROR event and keep going."""
        sev = ev.object
        cur = self._decode(sev.kv) if sev.kv else None
        prev = self._decode(sev.prev_kv) if sev.prev_kv else None
        cur_ok = cur is not None and (filter_fn is None or filter_fn(cur))
        prev_ok = prev is not None and (filter_fn is None or filter_fn(prev))
        if sev.action in ("create",):
            if cur_ok:
                return watchpkg.Event(watchpkg.ADDED, cur)
        elif sev.action in ("set", "compareAndSwap"):
            if cur_ok and prev_ok:
                return watchpkg.Event(watchpkg.MODIFIED, cur)
            if cur_ok:
                return watchpkg.Event(watchpkg.ADDED, cur)
            if prev_ok:
                # fell out of the filter: deliver the *new* state like
                # the reference (etcd_helper_watch.go sendModify)
                return watchpkg.Event(watchpkg.DELETED, cur)
        elif sev.action in ("delete", "expire"):
            if prev_ok:
                # clone: the deletion-rv rewrite below must not
                # mutate the shared cached revision
                prev_out = deep_clone(prev)
                # deleted object carries the deletion resourceVersion
                accessor.set_resource_version(prev_out, str(sev.index))
                return watchpkg.Event(watchpkg.DELETED, prev_out)
        return None

    def watch(self, prefix: str, resource_version: str = "",
              filter_fn: Optional[Callable[[Any], bool]] = None,
              recursive: bool = True,
              lag_limit: Optional[int] = None) -> watchpkg.Watcher:
        """Decoded object watch (ref: etcd_helper_watch.go:64-95 WatchList).

        Store events become ADDED/MODIFIED/DELETED watch.Events carrying API
        objects (translate_event). A bounded watcher that lags out delivers
        one ERROR Event carrying a 410 Expired Status, then ends — the
        Reflector re-lists.
        """
        src = self.watch_raw(prefix, resource_version, recursive=recursive,
                             lag_limit=lag_limit)
        out = watchpkg.Watcher(on_stop=lambda _w: src.stop())

        def pump():
            for ev in src:
                if ev.type == watchpkg.ERROR and ev.object is None:
                    # bounded-lag drop-to-resync marker from the store
                    out.send(watchpkg.Event(
                        watchpkg.ERROR,
                        errors.new_expired("watch lag bound exceeded; "
                                           "re-list required").status))
                    break
                try:
                    tev = self.translate_event(ev, filter_fn)
                except Exception as e:  # undecodable payload: surface, keep going
                    out.send(watchpkg.Event(
                        watchpkg.ERROR, errors.new_internal_error(str(e)).status))
                    continue
                if tev is not None:
                    out.send(tev)
            out.close()

        t = threading.Thread(target=pump, daemon=True, name=f"watch-{prefix}")
        t.start()
        return out
