"""MemStore — the versioned KV at the bottom of the stack.

Rebuild of the reference's persistence layer contract (etcd v2 as used by
pkg/tools/etcd_helper.go): a key/value tree with

- a single monotonically increasing **index**; every mutation gets one and
  stamps the key's ``modified_index`` (etcd ModifiedIndex — the basis of all
  resourceVersion semantics, ref: pkg/tools/etcd_helper_watch.go:47-57);
- **compare-and-swap** on that index (ref: etcd CompareAndSwap, used by
  EtcdHelper.AtomicUpdate, pkg/tools/etcd_helper.go:311-345);
- **watch from an index**, recursively over a prefix, served from a bounded
  in-memory event history (etcd keeps a 1000-event window; same here), with
  "index outdated" errors past the window;
- **TTL** per key (events use it, ref: pkg/registry/event seconds-to-live).

It is deliberately also the test double: like the reference's FakeEtcdClient
(pkg/tools/fake_etcd_client.go:42-67) it supports scriptable error injection
per (op, key) so registry/controller tests can exercise failure paths.

The store is process-local and thread-safe. A networked deployment puts the
apiserver in front of it (components never share the store directly —
DESIGN.md:40's invariant), so single-process ownership is the same model the
reference has: only the apiserver talks to etcd.
"""

from __future__ import annotations

import bisect
import heapq
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu import watch as watchpkg

__all__ = ["MemStore", "KV", "StoreEvent", "StoreError", "ErrKeyExists",
           "ErrKeyNotFound", "ErrCASConflict", "ErrIndexOutdated",
           "ErrInjected", "ErrTooManyRequests"]


class StoreError(Exception):
    pass


class ErrKeyExists(StoreError):
    pass


class ErrKeyNotFound(StoreError):
    pass


class ErrCASConflict(StoreError):
    pass


class ErrIndexOutdated(StoreError):
    """Watch index fell out of the history window (etcd error 401)."""


class ErrInjected(StoreError):
    """Raised by scripted error injection in tests."""


class ErrTooManyRequests(StoreError):
    """The store server SHED this op before executing it (kube-fairshed:
    StoreServer max_inflight overload valve). ``retry_after_s`` is the
    server's measured-drain hint; a resend can never double-apply —
    nothing ran. RemoteStore honors the hint transparently."""

    def __init__(self, message: str = "store overloaded",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclass
class KV:
    key: str
    value: str
    created_index: int
    modified_index: int
    expiration: Optional[float] = None  # monotonic deadline

    @property
    def resource_version(self) -> int:
        return self.modified_index


@dataclass
class StoreEvent:
    """One mutation, as seen by watchers (etcd watch response analog)."""

    action: str  # "create" | "set" | "compareAndSwap" | "delete" | "expire"
    key: str
    index: int
    kv: Optional[KV] = None       # post-state (None for delete/expire)
    prev_kv: Optional[KV] = None  # pre-state (None for create)


class MemStore:
    HISTORY_WINDOW = 1000

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Condition()
        self._data: Dict[str, KV] = {}
        # sorted key index: list(prefix) is a bisect range scan instead of
        # an O(cluster) sort+filter — at 50k pods a per-create admission
        # LIST otherwise dominates the apiserver's create path
        self._keys: List[str] = []
        # expiry heap: only TTL'd keys are swept, so the common no-TTL op
        # costs O(1) instead of a full-store scan (entries may be stale
        # after rewrites; validated against the live KV when popped)
        self._ttl_heap: List[Tuple[float, str]] = []
        # Index 0 is RESERVED as the "from now" watch token (rv '0'/'' —
        # parse_watch_resource_version). Starting the store at 1 means an
        # empty-store LIST returns 1, a true resume token: watch(1)
        # replays any write that raced between the list and the watch
        # registration. Starting at 0 had a lost-event window at cluster
        # bootstrap — list on the fresh store returned 0, watch(0) meant
        # "from now", and a write landing between them vanished (found by
        # hack/test.sh --race: the reflector-into-FIFO probe timing out
        # with the pump parked on an empty raw queue).
        self._index = 1
        self._history: List[StoreEvent] = []
        self._clock = clock
        # test error injection: (op, key) -> exception to raise, one-shot list
        self._inject: Dict[Tuple[str, str], List[Exception]] = {}
        self._watchers: List[Tuple[str, bool, watchpkg.Watcher]] = []

    # -- error injection (FakeEtcdClient analog) ---------------------------
    def inject_error(self, op: str, key: str, exc: Exception, times: int = 1) -> None:
        self._inject.setdefault((op, key), []).extend([exc] * times)

    def _maybe_raise(self, op: str, key: str) -> None:
        q = self._inject.get((op, key))
        if q:
            raise q.pop(0)

    # -- internals ---------------------------------------------------------
    def _expired(self, kv: KV) -> bool:
        return kv.expiration is not None and self._clock() >= kv.expiration

    def _insert_key_locked(self, key: str) -> None:
        if key not in self._data:
            bisect.insort(self._keys, key)

    def _remove_key_locked(self, key: str) -> None:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            del self._keys[i]

    def _sweep_locked(self) -> None:
        if not self._ttl_heap:
            return
        now = self._clock()
        while self._ttl_heap and self._ttl_heap[0][0] <= now:
            _, k = heapq.heappop(self._ttl_heap)
            kv = self._data.get(k)
            if kv is None or kv.expiration is None or kv.expiration > now:
                continue  # rewritten since this heap entry; still alive
            self._remove_key_locked(k)
            del self._data[k]
            self._index += 1
            self._record_locked(StoreEvent("expire", k, self._index, None, kv))

    def _record_locked(self, ev: StoreEvent) -> None:
        self._history.append(ev)
        if len(self._history) > self.HISTORY_WINDOW:
            del self._history[: len(self._history) - self.HISTORY_WINDOW]
        for prefix, recursive, w in list(self._watchers):
            if w.stopped:
                self._watchers.remove((prefix, recursive, w))
                continue
            if _match(ev.key, prefix, recursive):
                w.send(watchpkg.Event(ev.action, ev))

    # -- transaction/group persistence hooks --------------------------------
    # No-ops here; DurableStore overrides them to group-commit the WAL.
    # The batched verbs bracket their apply phases so a persistent store
    # can (a) make each txn item ATOMIC on disk — every op of one
    # evict+bind lands in ONE WAL record, so a crash can never resurrect
    # half a transaction on replay — and (b) write the whole call's
    # records in one append+flush instead of one flush per op (the
    # N-fsyncs-per-wave group commit).

    def _txn_begin_locked(self) -> None:
        """A batched verb's apply phase begins (lock held)."""

    def _txn_boundary_locked(self) -> None:
        """One atomic unit's ops are complete (lock held): everything
        recorded since the last boundary must persist all-or-nothing."""

    def _txn_commit_locked(self) -> None:
        """The batched verb is done (lock held): persist every sealed
        unit with one physical write+flush."""

    # -- reads -------------------------------------------------------------
    @property
    def index(self) -> int:
        with self._lock:
            return self._index

    def get(self, key: str) -> KV:
        with self._lock:
            self._maybe_raise("get", key)
            self._sweep_locked()
            kv = self._data.get(key)
            if kv is None:
                raise ErrKeyNotFound(key)
            return kv

    def get_many(self, keys: List[str]) -> List[Optional[KV]]:
        """Read several keys under one lock acquisition (None = absent).
        Honors the same scripted "get" fault injection as get() so chaos
        tests exercise the batched path identically."""
        with self._lock:
            for k in keys:
                self._maybe_raise("get", k)
            self._sweep_locked()
            return [self._data.get(k) for k in keys]

    def list(self, prefix: str) -> Tuple[List[KV], int]:
        """All KVs under prefix (recursive) + the store index at read time."""
        with self._lock:
            self._maybe_raise("list", prefix)
            self._sweep_locked()
            if prefix and not prefix.endswith("/"):
                prefix = prefix + "/"
            i = bisect.bisect_left(self._keys, prefix)
            out = []
            keys = self._keys
            while i < len(keys) and keys[i].startswith(prefix):
                out.append(self._data[keys[i]])
                i += 1
            return out, self._index

    # -- writes ------------------------------------------------------------
    def create(self, key: str, value: str, ttl: Optional[float] = None) -> KV:
        with self._lock:
            self._maybe_raise("create", key)
            self._sweep_locked()
            if key in self._data:
                raise ErrKeyExists(key)
            self._index += 1
            kv = KV(key, value, self._index, self._index,
                    self._clock() + ttl if ttl else None)
            self._insert_key_locked(key)
            self._data[key] = kv
            if kv.expiration is not None:
                heapq.heappush(self._ttl_heap, (kv.expiration, key))
            self._record_locked(StoreEvent("create", key, self._index, kv, None))
            return kv

    def set(self, key: str, value: str, ttl: Optional[float] = None) -> KV:
        """Unconditional write (create or replace)."""
        with self._lock:
            self._maybe_raise("set", key)
            self._sweep_locked()
            prev = self._data.get(key)
            self._index += 1
            kv = KV(key, value, prev.created_index if prev else self._index,
                    self._index, self._clock() + ttl if ttl else None)
            self._insert_key_locked(key)
            self._data[key] = kv
            if kv.expiration is not None:
                heapq.heappush(self._ttl_heap, (kv.expiration, key))
            self._record_locked(
                StoreEvent("set" if prev else "create", key, self._index, kv, prev))
            return kv

    def compare_and_swap(self, key: str, value: str, prev_index: int,
                         ttl: Optional[float] = None) -> KV:
        """Write iff the key's modified_index is exactly prev_index
        (ref: etcd CompareAndSwap; pkg/tools/etcd_helper.go:330)."""
        with self._lock:
            self._maybe_raise("compare_and_swap", key)
            self._sweep_locked()
            prev = self._data.get(key)
            if prev is None:
                raise ErrKeyNotFound(key)
            if prev.modified_index != prev_index:
                raise ErrCASConflict(
                    f"{key}: index mismatch (have {prev.modified_index}, want {prev_index})")
            self._index += 1
            kv = KV(key, value, prev.created_index, self._index,
                    self._clock() + ttl if ttl else None)
            self._data[key] = kv
            if kv.expiration is not None:
                heapq.heappush(self._ttl_heap, (kv.expiration, key))
            self._record_locked(StoreEvent("compareAndSwap", key, self._index, kv, prev))
            return kv

    def compare_and_swap_many(self, items: List[Tuple[str, str, int]]
                              ) -> List[object]:
        """Batched CAS: each (key, value, prev_index) is applied
        independently under ONE lock acquisition — the wave-commit
        primitive (SURVEY §7 hard part (e): 10k binds landing in one wave
        must not pay 10k lock round-trips). Per-item outcomes are returned
        positionally (KV on success, StoreError on conflict/missing) so a
        lost race invalidates only that item, exactly as the serial CAS
        would; every success gets its own index + watch event in order."""
        out: List[object] = []
        with self._lock:
            self._sweep_locked()
            self._txn_begin_locked()
            try:
                for key, value, prev_index in items:
                    try:
                        self._maybe_raise("compare_and_swap", key)
                    except StoreError as e:
                        out.append(e)
                        continue
                    prev = self._data.get(key)
                    if prev is None:
                        out.append(ErrKeyNotFound(key))
                        continue
                    if prev.modified_index != prev_index:
                        out.append(ErrCASConflict(
                            f"{key}: index mismatch (have "
                            f"{prev.modified_index}, want {prev_index})"))
                        continue
                    self._index += 1
                    kv = KV(key, value, prev.created_index, self._index, None)
                    self._data[key] = kv
                    self._record_locked(StoreEvent(
                        "compareAndSwap", key, self._index, kv, prev))
                    # each CAS is its own atomic unit (per-op records on
                    # disk, exactly as the serial verb writes them); the
                    # commit below still flushes the wave ONCE
                    self._txn_boundary_locked()
                    out.append(kv)
            finally:
                self._txn_commit_locked()
        return out

    def txn_many(self, items: List[Tuple[List[Tuple[str, str, int]],
                                         List[Tuple[str, int]]]]
                 ) -> List[object]:
        """Per-item all-or-nothing transactions under ONE lock acquisition
        — the evict+bind commit primitive (kube-preempt). Each item is
        ``(cas_ops, delete_ops)``: cas_ops are (key, value, prev_index)
        writes, delete_ops are (key, prev_index) compare-and-deletes.
        EVERY guard in an item is validated before ANY of its ops apply;
        the first failing guard aborts the whole item (its outcome is the
        StoreError) and later items still run independently. Outcomes are
        positional: the list of written KVs on success (cas order then
        delete order carries no KVs — deletes return nothing), a
        StoreError otherwise. Watch events are recorded per applied op in
        order, exactly as the serial verbs would."""
        out: List[object] = []
        with self._lock:
            self._sweep_locked()
            self._txn_begin_locked()
            try:
                self._txn_many_locked(items, out)
            finally:
                self._txn_commit_locked()
        return out

    def _txn_many_locked(self, items, out: List[object]) -> None:
        for cas_ops, delete_ops in items:
            err: Optional[StoreError] = None
            for key, _value, prev_index in cas_ops:
                try:
                    self._maybe_raise("compare_and_swap", key)
                except StoreError as e:
                    err = e
                    break
                prev = self._data.get(key)
                if prev is None:
                    err = ErrKeyNotFound(key)
                    break
                if prev.modified_index != prev_index:
                    err = ErrCASConflict(
                        f"{key}: index mismatch (have "
                        f"{prev.modified_index}, want {prev_index})")
                    break
            if err is None:
                for key, prev_index in delete_ops:
                    try:
                        self._maybe_raise("delete", key)
                    except StoreError as e:
                        err = e
                        break
                    prev = self._data.get(key)
                    if prev is None:
                        err = ErrKeyNotFound(key)
                        break
                    if prev.modified_index != prev_index:
                        err = ErrCASConflict(
                            f"{key}: index mismatch (have "
                            f"{prev.modified_index}, want {prev_index})")
                        break
            if err is not None:
                out.append(err)
                continue
            written: List[KV] = []
            for key, value, _prev_index in cas_ops:
                prev = self._data[key]
                self._index += 1
                kv = KV(key, value, prev.created_index, self._index,
                        None)
                self._data[key] = kv
                self._record_locked(StoreEvent(
                    "compareAndSwap", key, self._index, kv, prev))
                written.append(kv)
            for key, _prev_index in delete_ops:
                prev = self._data[key]
                del self._data[key]
                self._remove_key_locked(key)
                self._index += 1
                self._record_locked(StoreEvent(
                    "delete", key, self._index, None, prev))
            out.append(written)
            # seal the item: its ops persist as ONE atomic WAL record
            self._txn_boundary_locked()

    def delete(self, key: str, prev_index: Optional[int] = None) -> KV:
        with self._lock:
            self._maybe_raise("delete", key)
            self._sweep_locked()
            prev = self._data.get(key)
            if prev is None:
                raise ErrKeyNotFound(key)
            if prev_index is not None and prev.modified_index != prev_index:
                raise ErrCASConflict(
                    f"{key}: index mismatch (have {prev.modified_index}, want {prev_index})")
            del self._data[key]
            self._remove_key_locked(key)
            self._index += 1
            self._record_locked(StoreEvent("delete", key, self._index, None, prev))
            return prev

    # -- watch -------------------------------------------------------------
    def watch(self, prefix: str, from_index: int = 0,
              recursive: bool = True,
              lag_limit: Optional[int] = None) -> watchpkg.Watcher:
        """Stream StoreEvents for keys under prefix with index > from_index.

        from_index == 0 means "from now" (ref: ParseWatchResourceVersion,
        pkg/tools/etcd_helper_watch.go:47-57: rv 0 watches from current state;
        rv N resumes after N). History replay past the window raises
        ErrIndexOutdated, which clients handle by relisting (the Reflector
        contract, ref: pkg/client/cache/reflector.go:83).

        ``lag_limit`` bounds how far a consumer may fall behind: past the
        bound, modify events for one key coalesce (latest state still
        delivered) and anything uncoalescible drops the watcher to resync
        — one ERROR event, then end-of-stream (see watch.Watcher). The
        default (None) keeps the historical unbounded queue for
        in-process consumers that are trusted to drain.
        """
        with self._lock:
            self._maybe_raise("watch", prefix)
            if from_index:
                oldest_replayable = self._history[0].index if self._history else self._index + 1
                if from_index + 1 < oldest_replayable and from_index < self._index:
                    # asked to replay events that are gone
                    raise ErrIndexOutdated(
                        f"requested index {from_index} is outside the history window")
            w = watchpkg.Watcher(
                lag_limit=lag_limit,
                coalesce=_coalesce_store_events if lag_limit else None)
            if from_index:
                for ev in self._history:
                    if ev.index > from_index and _match(ev.key, prefix, recursive):
                        w.send(watchpkg.Event(ev.action, ev))
            self._watchers.append((prefix, recursive, w))
            return w


def _coalesce_store_events(old: watchpkg.Event,
                           new: watchpkg.Event) -> Optional[watchpkg.Event]:
    """Merge two queued mutations of ONE key into a single modify event
    preserving the prev->cur chain: (v1->v2) + (v2->v3) becomes (v1->v3),
    proven contiguous by the store indices, so filter-transition logic
    downstream (helper.translate_event) still sees the true endpoints.
    Creates/deletes never merge — their presence transitions must be
    delivered (or the watcher resyncs)."""
    osev, nsev = old.object, new.object
    if not isinstance(osev, StoreEvent) or not isinstance(nsev, StoreEvent):
        return None
    if (osev.key != nsev.key
            or osev.action not in ("set", "compareAndSwap")
            or nsev.action not in ("set", "compareAndSwap")):
        return None
    if osev.kv is None or nsev.prev_kv is None \
            or osev.kv.modified_index != nsev.prev_kv.modified_index:
        return None  # not contiguous (interleaved delete/recreate)
    return watchpkg.Event(nsev.action, StoreEvent(
        nsev.action, nsev.key, nsev.index, nsev.kv, osev.prev_kv))


def _match(key: str, prefix: str, recursive: bool) -> bool:
    if not recursive:
        return key == prefix
    if prefix and not prefix.endswith("/"):
        prefix = prefix + "/"
    return key.startswith(prefix) or key == prefix.rstrip("/")
