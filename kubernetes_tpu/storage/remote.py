"""Remote store: the cluster store as its own process.

The reference keeps ALL state in etcd, a separate process every apiserver
talks to over a socket (ref: pkg/tools/etcd_helper.go over the etcd v2
HTTP client; DESIGN.md:17-40 — components share state only through the
store). The in-process MemStore/DurableStore gave this rebuild its
FakeEtcdClient-style test backend; this module completes the topology
parity: ``StoreServer`` serves any MemStore-compatible store over a local
TCP socket, and ``RemoteStore`` is a drop-in MemStore replacement so
SEVERAL apiserver worker processes can share one consistent store — the
horizontal-scaling shape the reference gets from Go threads inside one
apiserver, recovered here across Python processes (one GIL each).

Protocol: length-prefixed JSON frames (4-byte big-endian size + UTF-8
body). Values are already JSON strings (StoreHelper encodes before
storing, like EtcdHelper), so the framing cost is one small dict per op.
Request/response on a pooled connection; ``watch`` upgrades its
connection to a one-way event stream, exactly like an etcd watch. Store
errors travel as {"err": <class name>, "msg": ...} and are re-raised as
the same StoreError classes clients of MemStore already handle.
"""

from __future__ import annotations

import json
import select
import socket
import struct
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.util import chaos
from kubernetes_tpu.util.retry import Backoff
from kubernetes_tpu.storage.memstore import (
    KV,
    ErrCASConflict,
    ErrIndexOutdated,
    ErrKeyExists,
    ErrKeyNotFound,
    ErrTooManyRequests,
    MemStore,
    StoreError,
    StoreEvent,
)

__all__ = ["StoreServer", "RemoteStore"]

_ERRORS = {
    "ErrKeyExists": ErrKeyExists,
    "ErrKeyNotFound": ErrKeyNotFound,
    "ErrCASConflict": ErrCASConflict,
    "ErrIndexOutdated": ErrIndexOutdated,
    "ErrTooManyRequests": ErrTooManyRequests,
    "StoreError": StoreError,
}


# -- framing -----------------------------------------------------------------

def _send_frame(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (size,) = struct.unpack(">I", head)
    body = _recv_exact(sock, size)
    if body is None:
        return None
    return json.loads(body)


def _kv_out(kv: Optional[KV]) -> Optional[dict]:
    if kv is None:
        return None
    return {"k": kv.key, "v": kv.value, "c": kv.created_index,
            "m": kv.modified_index, "e": kv.expiration}


def _kv_in(d: Optional[dict]) -> Optional[KV]:
    if d is None:
        return None
    return KV(d["k"], d["v"], d["c"], d["m"], d.get("e"))


def _err_out(e: Exception) -> dict:
    out = {"err": type(e).__name__, "msg": str(e)}
    ra = getattr(e, "retry_after_s", None)
    if ra is not None:
        # the throttle hint travels the wire so RemoteStore can honor
        # the server's measured drain, not guess
        out["retry_after"] = ra
    return out


def _raise_err(d: dict) -> None:
    cls = _ERRORS.get(d.get("err", ""), StoreError)
    if cls is ErrTooManyRequests:
        raise ErrTooManyRequests(d.get("msg", ""),
                                 retry_after_s=float(
                                     d.get("retry_after", 1.0) or 1.0))
    raise cls(d.get("msg", ""))


# -- server ------------------------------------------------------------------

class StoreServer:
    """Serves a MemStore-compatible store over TCP (the etcd process
    analog). One thread per connection; watch connections stream."""

    def __init__(self, store: Optional[MemStore] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 reuse_port: bool = False, max_inflight: int = 0):
        self.store = store if store is not None else MemStore()
        # kube-fairshed overload valve (0 disables): ops past
        # max_inflight concurrent dispatches are SHED with
        # ErrTooManyRequests + a measured-drain retry_after hint
        # instead of queueing unboundedly on the store lock — the store
        # analog of the apiserver's 429 + Retry-After
        self.max_inflight = int(max_inflight)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._op_done: "deque" = deque(maxlen=512)  # completion stamps
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port and hasattr(socket, "SO_REUSEPORT"):
            # OPT-IN only (in-process kill+respawn tests, embedded
            # deployments that re-listen while pre-crash client sockets
            # drain FIN_WAIT): two live kube-store processes sharing a
            # port would split clients across divergent stores, so the
            # production binary never sets it — a real process death
            # frees the port on its own
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns_lock = threading.Lock()
        self._conns: set = set()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    @property
    def address(self) -> str:
        host, port = self._sock.getsockname()[:2]
        return f"{host}:{port}"

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="store-accept")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # close live per-connection sockets too — a real process death
        # does, and leaving them open both leaks conn threads and keeps
        # the port EADDRINUSE against an in-process respawn (the
        # kill+respawn tests restart a StoreServer on the same port).
        # shutdown() first: the conn thread is blocked in recv, which
        # defers the fd close — shutdown sends the FIN immediately and
        # wakes the reader regardless (the http watch on_stop pattern).
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        self._accept_loop()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="store-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_frame(conn)
                if req is None:
                    return
                # kube-chaos seams (util/chaos, armed by tests only):
                # a mid-stream connection reset is exactly what a killed
                # server produces; a delay is a wedged-but-alive one
                chaos.delay_if_armed("store.serve.delay")
                if chaos.take_flag("store.serve.reset"):
                    return
                op = req.get("op", "")
                if op == "watch":
                    self._serve_watch(conn, req)
                    return  # the connection is consumed by the stream
                try:
                    chaos.error_if_armed("store.serve.error")
                    if not self._admit():
                        resp = _err_out(ErrTooManyRequests(
                            "store over max-inflight",
                            retry_after_s=self._throttle_hint()))
                    else:
                        try:
                            # seam INSIDE the admitted slot: tests hold
                            # a slot occupied for an exact duration
                            chaos.delay_if_armed("store.serve.busy")
                            resp = self._dispatch(op, req)
                        finally:
                            self._op_complete()
                except StoreError as e:
                    resp = _err_out(e)
                _send_frame(conn, resp)
        except (OSError, ValueError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _admit(self) -> bool:
        if not self.max_inflight:
            return True
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            return True

    def _op_complete(self) -> None:
        if not self.max_inflight:
            return
        with self._inflight_lock:
            self._inflight = max(0, self._inflight - 1)
            self._op_done.append(time.monotonic())

    def _throttle_hint(self) -> float:
        """Retry-after from the measured op completion rate — time for
        one inflight's worth of ops to drain, clamped [0.05, 5] s."""
        with self._inflight_lock:
            done = list(self._op_done)
        now = time.monotonic()
        recent = [t for t in done if t > now - 5.0]
        if len(recent) < 2:
            return 0.2
        rate = len(recent) / max(1e-3, now - recent[0])
        return min(5.0, max(0.05, self.max_inflight / rate))

    def _dispatch(self, op: str, req: dict) -> dict:
        s = self.store
        if op == "get":
            return {"ok": _kv_out(s.get(req["key"]))}
        if op == "get_many":
            return {"ok": [_kv_out(kv) for kv in s.get_many(req["keys"])]}
        if op == "list":
            kvs, index = s.list(req["prefix"])
            return {"ok": {"kvs": [_kv_out(kv) for kv in kvs],
                           "index": index}}
        if op == "create":
            return {"ok": _kv_out(s.create(req["key"], req["value"],
                                           ttl=req.get("ttl")))}
        if op == "set":
            return {"ok": _kv_out(s.set(req["key"], req["value"],
                                        ttl=req.get("ttl")))}
        if op == "cas":
            return {"ok": _kv_out(s.compare_and_swap(
                req["key"], req["value"], req["prev_index"],
                ttl=req.get("ttl")))}
        if op == "cas_many":
            outcomes = s.compare_and_swap_many(
                [(k, v, p) for k, v, p in req["items"]])
            return {"ok": [_err_out(oc) if isinstance(oc, Exception)
                           else {"kv": _kv_out(oc)} for oc in outcomes]}
        if op == "txn_many":
            outcomes = s.txn_many(
                [([(k, v, p) for k, v, p in cas],
                  [(k, p) for k, p in dels])
                 for cas, dels in req["items"]])
            return {"ok": [_err_out(oc) if isinstance(oc, Exception)
                           else {"kvs": [_kv_out(kv) for kv in oc]}
                           for oc in outcomes]}
        if op == "delete":
            return {"ok": _kv_out(s.delete(req["key"],
                                           prev_index=req.get("prev_index")))}
        if op == "index":
            return {"ok": s.index}
        raise StoreError(f"unknown op {op!r}")

    def _serve_watch(self, conn: socket.socket, req: dict) -> None:
        try:
            src = self.store.watch(req.get("prefix", ""),
                                   from_index=req.get("from_index", 0),
                                   recursive=req.get("recursive", True),
                                   lag_limit=req.get("lag_limit"))
        except StoreError as e:
            _send_frame(conn, _err_out(e))
            return
        _send_frame(conn, {"ok": True})

        # reader side: an EOF/garbage from the client stops the watch, so
        # a dropped apiserver worker releases its server-side watcher
        def reap():
            try:
                conn.recv(1)
            except OSError:
                pass
            src.stop()

        threading.Thread(target=reap, daemon=True,
                         name="store-watch-reap").start()
        try:
            for ev in src:
                if ev.type == watchpkg.ERROR and ev.object is None:
                    # bounded-lag drop-to-resync marker: forward, then the
                    # stream ends (the client re-lists)
                    _send_frame(conn, {"lagged": True})
                    break
                sev: StoreEvent = ev.object
                _send_frame(conn, {"ev": {
                    "action": sev.action, "key": sev.key, "index": sev.index,
                    "kv": _kv_out(sev.kv), "prev_kv": _kv_out(sev.prev_kv)}})
        except (OSError, ValueError):
            pass
        finally:
            src.stop()
            try:
                conn.close()
            except OSError:
                pass


# -- client ------------------------------------------------------------------

class RemoteStore:
    """Drop-in MemStore replacement speaking to a StoreServer.

    One pooled connection per thread (apiserver handler threads are
    long-lived); watches open a dedicated streaming connection each, and
    stopping the client-side Watcher closes it, which the server notices.

    Restart transparency (docs/design/ha.md): a kube-store respawn must
    look like latency, not errors. Three mechanisms compose:

    - a zero-timeout readability probe evicts pooled connections the
      restarted server half-closed BEFORE a request lands on them (the
      Go http.Transport background-read idiom client/http uses) — the
      common post-restart path never even sees an error;
    - refused/failed CONNECTS retry with capped exponential backoff +
      jitter for up to ``reconnect_window_s`` (nothing was sent, always
      safe; jitter keeps N handler threads from reconnecting in
      lockstep);
    - a connection that dies MID-CALL retries through the same window
      for idempotent reads; writes still raise (the op may have applied
      — the callers' CAS/409 discipline owns that ambiguity, same as
      client/http._open for non-idempotent methods).
    """

    def __init__(self, address: str, call_timeout_s: float = 30.0,
                 reconnect_window_s: float = 20.0):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._call_timeout_s = call_timeout_s
        self._reconnect_window_s = reconnect_window_s
        self._local = threading.local()
        self.throttled = 0   # ErrTooManyRequests answers ridden out

    # -- plumbing ----------------------------------------------------------
    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr,
                                        timeout=self._call_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    @staticmethod
    def _stale(sock: socket.socket) -> bool:
        """True when an idle pooled connection is unusable: any pending
        byte/EOF on an idle request/response connection means the server
        closed or desynced (a restarted kube-store RSTs every pre-crash
        socket). poll(2), not select(2) — fd>=1024 must not false-flag."""
        try:
            p = select.poll()
            p.register(sock, select.POLLIN | select.POLLHUP | select.POLLERR)
            return bool(p.poll(0))
        except (OSError, ValueError):
            return True

    def _drop_sock(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            self._local.sock = None
            try:
                sock.close()
            except OSError:
                pass

    def _connect_with_backoff(self, deadline: float) -> socket.socket:
        """Dial until ``deadline``; OSError past it surfaces as
        StoreError (the caller's per-op failure)."""
        backoff = Backoff(base=0.05, cap=1.0)
        while True:
            try:
                return self._connect()
            except OSError as e:
                if time.monotonic() + backoff.peek() >= deadline:
                    raise StoreError(
                        f"store at {self._addr[0]}:{self._addr[1]} "
                        f"unreachable for {self._reconnect_window_s:.0f}s: "
                        f"{e}") from None
                backoff.sleep_next()

    def _call(self, req: dict, idempotent: bool = False):
        deadline = time.monotonic() + self._reconnect_window_s
        retry_backoff = Backoff(base=0.02, cap=0.5)
        throttle_backoff = Backoff(base=0.05, cap=1.0)
        while True:
            sock = getattr(self._local, "sock", None)
            if sock is not None and self._stale(sock):
                self._drop_sock()
                sock = None
            if sock is None:
                sock = self._local.sock = \
                    self._connect_with_backoff(deadline)
            sent = False
            recv_err: Optional[Exception] = None
            resp = None
            try:
                _send_frame(sock, req)
                sent = True
                resp = _recv_frame(sock)
            except OSError as e:
                recv_err = e
            if resp is None:
                self._drop_sock()
                if not sent:
                    # the request never went out: reconnect and resend
                    # (always safe) — but bounded by the SAME window as
                    # everything else, with a small backoff: a store in
                    # a fast crash loop accepts connects and resets the
                    # send, which would otherwise busy-spin here forever
                    if time.monotonic() >= deadline:
                        raise StoreError(
                            f"store at {self._addr[0]}:{self._addr[1]} "
                            f"resetting sends for "
                            f"{self._reconnect_window_s:.0f}s: {recv_err}")
                    retry_backoff.sleep_next()
                    continue
                # the server died between send and response. Reads are
                # idempotent — retry through the window (a restarted
                # kube-store serves them from recovered state). Writes
                # are NOT retried: the op may have applied (same
                # discipline as client/http._open for non-idempotent
                # methods).
                if idempotent and time.monotonic() < deadline:
                    continue
                raise StoreError("store connection "
                                 + (f"failed mid-call: {recv_err}"
                                    if recv_err else "closed mid-call"))
            if "err" in resp:
                if resp.get("err") == "ErrTooManyRequests":
                    # kube-fairshed: the server SHED this op before
                    # executing it, so a resend can never double-apply
                    # (reads AND writes) — honor its measured
                    # retry_after hint (capped exponential + jitter
                    # when the server sent none) inside the same window
                    # every other transient shares, then surface
                    hint = float(resp.get("retry_after", 0) or 0) \
                        or throttle_backoff.next()
                    if time.monotonic() + hint < deadline:
                        self.throttled += 1
                        time.sleep(hint)
                        continue
                _raise_err(resp)
            return resp["ok"]

    # -- MemStore surface --------------------------------------------------
    @property
    def index(self) -> int:
        return self._call({"op": "index"}, idempotent=True)

    def get(self, key: str) -> KV:
        return _kv_in(self._call({"op": "get", "key": key},
                              idempotent=True))

    def get_many(self, keys: List[str]) -> List[Optional[KV]]:
        return [_kv_in(d) for d in
                self._call({"op": "get_many", "keys": list(keys)},
                           idempotent=True)]

    def list(self, prefix: str) -> Tuple[List[KV], int]:
        out = self._call({"op": "list", "prefix": prefix},
                         idempotent=True)
        return [_kv_in(d) for d in out["kvs"]], out["index"]

    def create(self, key: str, value: str,
               ttl: Optional[float] = None) -> KV:
        return _kv_in(self._call({"op": "create", "key": key,
                                  "value": value, "ttl": ttl}))

    def set(self, key: str, value: str, ttl: Optional[float] = None) -> KV:
        return _kv_in(self._call({"op": "set", "key": key, "value": value,
                                  "ttl": ttl}))

    def compare_and_swap(self, key: str, value: str, prev_index: int,
                         ttl: Optional[float] = None) -> KV:
        return _kv_in(self._call({"op": "cas", "key": key, "value": value,
                                  "prev_index": prev_index, "ttl": ttl}))

    def compare_and_swap_many(self, items: List[Tuple[str, str, int]]
                              ) -> List[object]:
        out = self._call({"op": "cas_many",
                          "items": [list(i) for i in items]})
        results: List[object] = []
        for d in out:
            if "err" in d:
                results.append(_ERRORS.get(d["err"], StoreError)(d["msg"]))
            else:
                results.append(_kv_in(d["kv"]))
        return results

    def txn_many(self, items) -> List[object]:
        """Per-item all-or-nothing CAS+delete transactions (the evict+bind
        commit primitive); wire mirror of MemStore.txn_many."""
        out = self._call({"op": "txn_many",
                          "items": [[[list(c) for c in cas],
                                     [list(d) for d in dels]]
                                    for cas, dels in items]})
        results: List[object] = []
        for d in out:
            if "err" in d:
                results.append(_ERRORS.get(d["err"], StoreError)(d["msg"]))
            else:
                results.append([_kv_in(kv) for kv in d["kvs"]])
        return results

    def delete(self, key: str, prev_index: Optional[int] = None) -> KV:
        return _kv_in(self._call({"op": "delete", "key": key,
                                  "prev_index": prev_index}))

    def watch(self, prefix: str, from_index: int = 0,
              recursive: bool = True,
              lag_limit: Optional[int] = None) -> watchpkg.Watcher:
        # the open handshake is read-only: ride a store respawn with the
        # same backoff window the request/response ops use
        sock = self._connect_with_backoff(
            time.monotonic() + self._reconnect_window_s)
        # the open handshake stays under the connect timeout (a wedged
        # store must fail watch() in bounded time) ...
        _send_frame(sock, {"op": "watch", "prefix": prefix,
                           "from_index": from_index, "recursive": recursive,
                           "lag_limit": lag_limit})
        resp = _recv_frame(sock)
        if resp is None:
            raise StoreError("store connection closed opening watch")
        if "err" in resp:
            _raise_err(resp)
        # ... but the STREAM must carry no timeout: a watch over a quiet
        # prefix legitimately sees nothing for minutes, and a timed-out
        # recv would silently end every downstream watcher
        sock.settimeout(None)

        def on_stop(_w):
            try:
                sock.close()
            except OSError:
                pass

        w = watchpkg.Watcher(on_stop=on_stop)

        def pump():
            try:
                while True:
                    frame = _recv_frame(sock)
                    if frame is None:
                        break
                    if frame.get("lagged"):
                        # server-side lag bound tripped: replay the
                        # drop-to-resync locally (ERROR + end-of-stream)
                        w.drop_to_resync()
                        break
                    if "ev" not in frame:
                        break
                    d = frame["ev"]
                    w.send(watchpkg.Event(d["action"], StoreEvent(
                        d["action"], d["key"], d["index"],
                        _kv_in(d.get("kv")), _kv_in(d.get("prev_kv")))))
            except (OSError, ValueError):
                pass
            finally:
                w.close()

        threading.Thread(target=pump, daemon=True,
                         name=f"remote-watch-{prefix}").start()
        return w
