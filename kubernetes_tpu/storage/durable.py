"""DurableStore — MemStore persisted by an append-only WAL + snapshots.

The reference keeps every byte of cluster state in etcd and all components
are stateless resumers (ref: pkg/tools/etcd_helper.go:36-345;
etcd_helper_watch.go:47-57 resourceVersion semantics); MemStore alone is
process-RAM, so killing the apiserver loses the cluster. DurableStore is
the persistence option behind the SAME contract:

- every mutation (create/set/compareAndSwap/delete/expire) funnels through
  ``_record_locked`` — the single choke point — and is appended to
  ``wal.log`` as one JSON line under the store lock, so the WAL order IS
  the index order;
- the batched verbs GROUP-COMMIT (docs/design/ha.md): ``txn_many`` seals
  every op of one atomic evict+bind item into ONE WAL record
  (``{"txn": [op, ...]}``), so a crash can never resurrect half a
  transaction on replay, and the whole call's records land in one
  write+flush(+fsync) — one durability syscall per wave instead of one
  per op; ``compare_and_swap_many`` keeps per-op records but shares the
  single flush;
- ``snapshot.json`` is written atomically (tmp + rename) every
  ``compact_every`` WAL records, then the WAL restarts; a crash between
  the two is safe because replay skips entries at or below the snapshot
  index;
- recovery = load snapshot, replay WAL: the global index, every key's
  created/modified index (the resourceVersion), TTL deadlines (persisted
  as wall-clock, rebased to the store clock on load), and the bounded
  watch-history window all come back — so reflectors resume from their
  pre-crash resourceVersion without relisting, and CAS against a
  pre-crash resourceVersion behaves identically. A torn final record (a
  crash mid-append) is truncated and disclosed, never a crash loop;
- recovery is DISCLOSED, not silent: ``self.recovery`` carries replayed
  record/op counts, snapshot age, torn-tail bytes, and the recovery wall
  time; the same numbers ride the ``store_wal_*`` / ``store_recovery_*``
  metric families (util/metrics.StoreWalMetrics) so kube-store's
  /healthz and the chaos churn record can prove "bounded recovery"
  instead of asserting it;
- durability level: flush-per-group-commit by default (survives process
  kill); ``fsync=True`` for media-crash durability at a syscall per
  group.

Wire-in: ``Master(MasterConfig(store=DurableStore(dir)))`` — nothing else
in the stack knows persistence exists.
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import time
from typing import Callable, List, Optional

from kubernetes_tpu.storage.memstore import KV, MemStore, StoreEvent
from kubernetes_tpu.util import chaos
from kubernetes_tpu.util import metrics as metrics_pkg

__all__ = ["DurableStore"]

_log = logging.getLogger("kubernetes_tpu.storage.durable")


def _parses(line: bytes) -> bool:
    try:
        json.loads(line)
        return True
    except ValueError:
        return False

_SNAP = "snapshot.json"
_WAL = "wal.log"


class DurableStore(MemStore):
    def __init__(self, directory: str,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time,
                 fsync: bool = False, compact_every: int = 10_000):
        super().__init__(clock)
        self._dir = directory
        self._wall = wall_clock
        self._fsync = fsync
        self._compact_every = compact_every
        self._wal_records = 0
        self._wal_bytes = 0
        self._wal_f = None  # set after recovery; _record_locked no-ops until
        # group-commit state: None outside a batched verb; a list of op
        # entries for the item being applied while inside one
        self._txn_buf: Optional[List[dict]] = None
        self._txn_lines: List[str] = []
        self._txn_ops = 0
        self._mx = metrics_pkg.store_wal_metrics()
        os.makedirs(directory, exist_ok=True)
        self._recover()
        self._wal_f = open(os.path.join(directory, _WAL), "a",
                           encoding="utf-8")
        self._wal_bytes = os.path.getsize(os.path.join(directory, _WAL))
        self._mx.wal_size.set(self._wal_bytes)
        # carry the replayed record count into the compaction budget (and
        # compact now if the inherited WAL already exceeds it): otherwise a
        # frequently-restarted server never snapshots and the WAL — and
        # recovery time — grow without bound across restart cycles
        self._wal_records = self._recovered_records
        if self._wal_records >= self._compact_every:
            with self._lock:
                self._compact_locked()

    # -- persistence hooks --------------------------------------------------
    def _exp_to_wall(self, exp_mono: Optional[float]) -> Optional[float]:
        if exp_mono is None:
            return None
        return self._wall() + (exp_mono - self._clock())

    def _exp_from_wall(self, exp_wall: Optional[float]) -> Optional[float]:
        if exp_wall is None:
            return None
        return self._clock() + (exp_wall - self._wall())

    def _entry_of(self, ev: StoreEvent) -> dict:
        entry = {"a": ev.action, "k": ev.key, "i": ev.index}
        if ev.kv is not None:
            entry["v"] = ev.kv.value
            entry["c"] = ev.kv.created_index
            if ev.kv.expiration is not None:
                entry["e"] = self._exp_to_wall(ev.kv.expiration)
        return entry

    def _record_locked(self, ev: StoreEvent) -> None:
        super()._record_locked(ev)  # watchers + history first
        if self._wal_f is None:
            return  # replaying recovery
        entry = self._entry_of(ev)
        if self._txn_buf is not None:
            # inside a batched verb: buffer; the boundary seals the item
            # into one record and the commit writes the whole call once
            self._txn_buf.append(entry)
            self._txn_ops += 1
            return
        self._wal_append_locked([json.dumps(entry)], ops=1)

    # -- group commit (the batched-verb hooks) ------------------------------
    def _txn_begin_locked(self) -> None:
        if self._wal_f is None:
            return
        self._txn_buf = []
        self._txn_lines = []
        self._txn_ops = 0

    def _txn_boundary_locked(self) -> None:
        buf = self._txn_buf
        if not buf:
            return  # outside a batch, or the item recorded nothing
        # one line per atomic unit: a single-op unit keeps the serial
        # verbs' record format (replay-compatible with pre-group WALs);
        # a multi-op unit becomes a txn record — all-or-nothing by
        # construction, because a JSON line either parses or is torn
        line = json.dumps(buf[0]) if len(buf) == 1 \
            else json.dumps({"txn": buf})
        self._txn_lines.append(line)
        self._txn_buf = []

    def _txn_commit_locked(self) -> None:
        if self._wal_f is None:
            self._txn_buf = None
            return
        self._txn_boundary_locked()  # seal a dangling unit defensively
        lines, ops = self._txn_lines, self._txn_ops
        self._txn_buf = None
        self._txn_lines = []
        self._txn_ops = 0
        if lines:
            self._wal_append_locked(lines, ops=ops)

    def _wal_append_locked(self, lines: List[str], ops: int) -> None:
        """The ONLY writer of WAL bytes: one write+flush(+fsync) per
        call — per op for the serial verbs, per wave for the batched
        ones. The chaos crash points bracket the physical append so the
        WAL atomicity tests can kill the store exactly where SIGKILL
        would land (before the append: nothing durable; after: every
        sealed record durable — never a fraction of one)."""
        chaos.crash_if_armed("durable.wal_append.pre")
        data = "\n".join(lines) + "\n"
        self._wal_f.write(data)
        self._wal_f.flush()
        if self._fsync:
            os.fsync(self._wal_f.fileno())
            self._mx.fsyncs.inc()
        chaos.crash_if_armed("durable.wal_append.post")
        self._wal_records += len(lines)
        self._wal_bytes += len(data)
        self._mx.records.inc(by=len(lines))
        self._mx.ops.inc(by=ops)
        self._mx.group_commits.inc()
        self._mx.bytes_written.inc(by=len(data))
        self._mx.wal_size.set(self._wal_bytes)
        if self._wal_records >= self._compact_every:
            self._compact_locked()

    def _kv_dict(self, kv: Optional[KV]) -> Optional[dict]:
        if kv is None:
            return None
        d = {"k": kv.key, "v": kv.value, "c": kv.created_index,
             "m": kv.modified_index}
        if kv.expiration is not None:
            d["e"] = self._exp_to_wall(kv.expiration)
        return d

    def _kv_from_dict(self, d: Optional[dict]) -> Optional[KV]:
        if d is None:
            return None
        return KV(d["k"], d["v"], d["c"], d["m"],
                  self._exp_from_wall(d.get("e")))

    # -- snapshot / compaction ---------------------------------------------
    def _compact_locked(self) -> None:
        snap = {
            "index": self._index,
            "kvs": [self._kv_dict(self._data[k]) for k in self._keys],
            # the watch window survives restart so reflectors can resume
            # from a pre-crash resourceVersion without relisting; prev_kv
            # is persisted too — delete replay delivers the prior object
            # and set replay needs it to pick ADDED vs MODIFIED
            "history": [
                {"a": ev.action, "k": ev.key, "i": ev.index,
                 "kv": self._kv_dict(ev.kv), "pv": self._kv_dict(ev.prev_kv)}
                for ev in self._history
            ],
        }
        tmp = os.path.join(self._dir, _SNAP + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, _SNAP))
        self._wal_f.close()
        self._wal_f = open(os.path.join(self._dir, _WAL), "w",
                           encoding="utf-8")
        self._wal_records = 0
        self._wal_bytes = 0
        self._mx.compactions.inc()
        self._mx.wal_size.set(0)
        self._mx.snapshot_size.set(
            os.path.getsize(os.path.join(self._dir, _SNAP)))

    def compact(self) -> None:
        """Force a snapshot + WAL truncation (tests, shutdown hooks)."""
        with self._lock:
            self._compact_locked()

    # -- recovery -----------------------------------------------------------
    def _entry_kv(self, d: dict, modified: int) -> KV:
        return KV(d["k"], d.get("v", ""), d.get("c", modified), modified,
                  self._exp_from_wall(d.get("e")))

    def _apply_entry(self, d: dict) -> None:
        idx = d["i"]
        key = d["k"]
        action = d["a"]
        prev = self._data.get(key)
        if action in ("delete", "expire"):
            if prev is not None:
                self._remove_key_locked(key)
                del self._data[key]
            kv = None
        else:
            kv = self._entry_kv(d, idx)
            self._insert_key_locked(key)
            self._data[key] = kv
            if kv.expiration is not None:
                heapq.heappush(self._ttl_heap, (kv.expiration, key))
        self._index = max(self._index, idx)
        self._history.append(StoreEvent(action, key, idx, kv, prev))
        if len(self._history) > self.HISTORY_WINDOW:
            del self._history[: len(self._history) - self.HISTORY_WINDOW]

    def _replay_record(self, d: dict) -> int:
        """Apply one WAL record (a serial op, or a txn group whose ops
        land all together — the record parsed, so the whole item is
        here). Returns the op count."""
        if "txn" in d:
            ops = 0
            for e in d["txn"]:
                if e["i"] <= self._snap_index_guard:
                    continue  # pre-snapshot entry (crash mid-compact)
                self._apply_entry(e)
                ops += 1
            return ops
        if d["i"] <= self._snap_index_guard:
            return 0
        self._apply_entry(d)
        return 1

    def _recover(self) -> None:
        t0 = time.perf_counter()
        self._snap_index_guard = 0
        self._recovered_records = 0
        recovered_ops = 0
        snapshot_age_s = 0.0
        torn_bytes = 0
        snap_path = os.path.join(self._dir, _SNAP)
        if os.path.exists(snap_path):
            snapshot_age_s = max(0.0, self._wall()
                                 - os.path.getmtime(snap_path))
            self._mx.snapshot_size.set(os.path.getsize(snap_path))
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            # clamp to the base-1 floor: a snapshot written by a pre-base-1
            # tree while empty carries index 0, which would reinstate the
            # bootstrap lost-event window (index 0 is the reserved
            # "from now" watch token — see MemStore.__init__)
            self._index = max(1, snap["index"])
            self._snap_index_guard = snap["index"]
            for d in snap["kvs"]:
                kv = KV(d["k"], d["v"], d["c"], d["m"],
                        self._exp_from_wall(d.get("e")))
                self._insert_key_locked(d["k"])
                self._data[d["k"]] = kv
                if kv.expiration is not None:
                    heapq.heappush(self._ttl_heap, (kv.expiration, d["k"]))
            for d in snap.get("history", []):
                self._history.append(StoreEvent(
                    d["a"], d["k"], d["i"],
                    self._kv_from_dict(d.get("kv")),
                    self._kv_from_dict(d.get("pv"))))
        wal_path = os.path.join(self._dir, _WAL)
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                data = f.read()
            good_end = 0
            bad_at = None
            pos = 0
            for raw in data.splitlines(keepends=True):
                line = raw.strip()
                pos += len(raw)
                if not line:
                    good_end = pos
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    bad_at = pos - len(raw)
                    break  # torn/corrupt record: stop replay at the last good one
                good_end = pos
                self._recovered_records += 1
                recovered_ops += self._replay_record(d)
            if bad_at is not None:
                # Truncate to the last good record: reopening in append mode
                # would otherwise weld the next write onto the torn fragment,
                # and the NEXT restart would discard that merged line plus
                # everything after it (silent data loss + index regression).
                discarded = len(data) - good_end
                torn_bytes = discarded
                tail = data[good_end:]
                # a parseable line after the bad one means mid-file corruption,
                # not a crash-torn tail — surface it loudly either way
                midfile = any(_parses(l) for l in tail.splitlines()[1:])
                _log.error(
                    "WAL %s: unparseable record at byte %d; discarding %d "
                    "trailing bytes (%s) and truncating to last good record",
                    wal_path, bad_at, discarded,
                    "MID-FILE CORRUPTION — parseable records were lost"
                    if midfile else "torn tail from a crash")
                with open(wal_path, "r+b") as f:
                    f.truncate(good_end)
        recovery_s = time.perf_counter() - t0
        # the disclosure contract (docs/design/ha.md): what recovery did,
        # visible to /healthz (kube-store, apiserver) and the chaos churn
        # record — a store that silently replayed for 40 s is a wall, not
        # an implementation detail
        self.recovery = {
            "replayed_records": self._recovered_records,
            "replayed_ops": recovered_ops,
            "snapshot": os.path.exists(snap_path),
            "snapshot_age_s": round(snapshot_age_s, 3),
            "torn_bytes": torn_bytes,
            "recovery_s": round(recovery_s, 4),
            "index": self._index,
        }
        self._mx.recovery_s.observe(recovery_s)
        self._mx.replayed.set(self._recovered_records)
        self._mx.snapshot_age.set(snapshot_age_s)
        if torn_bytes:
            self._mx.torn_bytes.inc(by=torn_bytes)
