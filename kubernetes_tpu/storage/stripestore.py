"""StripedStore — the keyspace-sharded MemStore twin (kube-stripe).

Every write in the cluster used to serialize behind MemStore's single
global lock, and watch fan-out to every subscribed watcher ran INSIDE
that critical section (memstore.py `_record_locked`) — the etcd-shaped
wall ROADMAP item 2 names. StripedStore splits the hot host-side state
into S shards (default 8, power of two) while keeping the ONE invariant
everything above the store depends on: a single, dense, totally-ordered
revision counter.

Shard map
    shard(key) = crc32(namespace component) & (S - 1)

where the namespace component of ``/registry/pods/default/web-1`` is
segment 2 (``default``) — so a per-namespace ``txn_many`` evict+bind
batch, and every key one 3+-segment prefix can match, stays on ONE
shard. Keys with fewer than three segments hash their last segment.

Each shard owns its lock, sorted key index, TTL heap, bounded history
ring, and watcher list. The revision counter lives under a separate
``_rev_lock`` acquired INSIDE a shard lock; because every event is
assigned its index, appended to its shard's history ring, persisted
(durable subclass), and delivered to root-prefix watchers under that one
lock, ``_index`` remains a total order across shards — watch resume
tokens, the frame cache's ``(rv, version)`` keys, and share.py seeding
are untouched. Per-shard watcher lists mean a pod storm fans out under
its own shard's lock only; watchers of unrelated namespaces never wait.

Lock discipline (the canonical order — docs/design/invariants.md):

    shard[i].lock (ascending shard id) -> _rev_lock -> watcher queues

Cross-shard ops (root-prefix LIST/watch, cross-namespace txn_many)
acquire every involved shard lock in ascending shard id, then the rev
lock per event. locksmith must record zero cycles; under KTPU_RACE the
shard locks are locksmith-named per shard id so the measured edge table
shows the ascending discipline instead of hiding same-site edges.

Deliberate, documented divergences from MemStore (everything else is
gated bit-identical by tests/test_storeshard.py):

- TTL sweep is per-shard: an op sweeps the shard(s) it touches, so a
  TTL'd key on an untouched shard expires when that shard is next
  touched (MemStore sweeps the world on every op). Expiry was always
  clock-dependent; no client observes order beyond the revision stamp.
- History retention is per-shard (S rings of HISTORY_WINDOW), so a
  resume token may be replayable striped where the global window had
  already evicted it — staleness is still enforced per shard: a
  ``watch(rv)`` with rv below a shard's evicted floor raises
  ErrIndexOutdated (the 410 Expired/re-list contract), never a silent
  gap.
"""

from __future__ import annotations

import bisect
import heapq
import json
import logging
import os
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.storage.memstore import (
    KV, StoreEvent, StoreError, ErrKeyExists, ErrKeyNotFound,
    ErrCASConflict, ErrIndexOutdated, _coalesce_store_events, _match)
from kubernetes_tpu.storage.durable import _parses, _SNAP, _WAL
from kubernetes_tpu.util import chaos
from kubernetes_tpu.util import locksmith
from kubernetes_tpu.util import metrics as metrics_pkg

__all__ = ["StripedStore", "DurableStripedStore", "shard_of_key"]

_log = logging.getLogger("kubernetes_tpu.storage.stripestore")


def _ns_token(key: str) -> str:
    """The shard-stable component: segment 2 of a registry key
    (``/registry/pods/<ns>/<name>`` -> ``<ns>``), else the last
    segment — chosen so every key a 3+-segment prefix can match shares
    the token with the prefix itself."""
    parts = [p for p in key.split("/") if p]
    if len(parts) >= 3:
        return parts[2]
    return parts[-1] if parts else ""


def shard_of_key(key: str, shards: int) -> int:
    return zlib.crc32(_ns_token(key).encode("utf-8")) & (shards - 1)


def _new_lock(name: str):
    # Under locksmith arming, threading.Lock() would be tracked anyway —
    # but every shard lock would share ONE creation site, and edges()
    # excludes same-site pairs, hiding exactly the shard[i] -> shard[j]
    # edges the race round must measure. Name each lock explicitly.
    if locksmith.armed():
        return locksmith.wrap(name)
    return threading.Lock()


class _Shard:
    __slots__ = ("sid", "lock", "data", "keys", "ttl_heap", "history",
                 "evicted_through", "watchers")

    def __init__(self, sid: int):
        self.sid = sid
        self.lock = _new_lock(f"stripestore.shard[{sid}]")
        self.data: Dict[str, KV] = {}
        self.keys: List[str] = []
        self.ttl_heap: List[Tuple[float, str]] = []
        self.history: List[StoreEvent] = []
        # newest revision known to be trimmed out of this shard's ring:
        # a resume token below this floor has lost events -> 410
        self.evicted_through = 0
        self.watchers: List[Tuple[str, bool, watchpkg.Watcher]] = []


class StripedStore:
    """Keyspace-sharded store, bit-identical to MemStore as its S=1
    twin (revision sequence, per-watcher frame order, list results) —
    the contract tests/test_storeshard.py enforces."""

    HISTORY_WINDOW = 1000

    def __init__(self, shards: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        if shards < 1 or (shards & (shards - 1)) != 0:
            raise ValueError(f"shards must be a power of two, got {shards}")
        self.shards = shards
        self._mask = shards - 1
        self._shards = [_Shard(i) for i in range(shards)]
        self._rev_lock = _new_lock("stripestore.rev")
        # Index 0 is RESERVED as the "from now" watch token; starting at
        # 1 keeps the empty-store LIST a true resume token (memstore.py
        # bootstrap lost-event note). Guarded by _rev_lock.
        self._index = 1
        # oldest-coverage floor for staleness when rings alone can't
        # answer (snapshot recovery without full history)
        self._replay_floor = 0
        self._root_watchers: List[Tuple[str, bool, watchpkg.Watcher]] = []
        self._clock = clock
        self._inject: Dict[Tuple[str, str], List[Exception]] = {}
        self._mx = metrics_pkg.store_shard_metrics()
        self._mx.shard_count.set(shards)

    # -- error injection (FakeEtcdClient analog) ---------------------------
    def inject_error(self, op: str, key: str, exc: Exception,
                     times: int = 1) -> None:
        self._inject.setdefault((op, key), []).extend([exc] * times)

    def _maybe_raise(self, op: str, key: str) -> None:
        # callers always hold the key's shard lock (or the rev lock for
        # root-prefix watch), so per-key consumption is serialized
        q = self._inject.get((op, key))
        if q:
            raise q.pop(0)

    # -- shard resolution --------------------------------------------------
    def _sid_of(self, key: str) -> int:
        return zlib.crc32(_ns_token(key).encode("utf-8")) & self._mask

    def _shard_of(self, key: str) -> _Shard:
        return self._shards[self._sid_of(key)]

    def _sids_for_prefix(self, prefix: str,
                         recursive: bool) -> Optional[List[int]]:
        """Shard ids a prefix can touch; None means every shard (root).
        A 3+-segment recursive prefix pins the namespace token, so every
        matching key shares its shard."""
        if not recursive:
            return [self._sid_of(prefix)]
        parts = [p for p in prefix.split("/") if p]
        if len(parts) >= 3:
            return [zlib.crc32(parts[2].encode("utf-8")) & self._mask]
        return None

    def _acquire(self, sids) -> None:
        # THE lock discipline: ascending shard id, always
        for sid in sids:
            self._shards[sid].lock.acquire()

    def _release(self, sids) -> None:
        for sid in reversed(sids):
            self._shards[sid].lock.release()

    # -- internals (caller holds the shard's lock) -------------------------
    def _insert_key_shard_locked(self, sh: _Shard, key: str) -> None:
        if key not in sh.data:
            bisect.insort(sh.keys, key)

    def _remove_key_shard_locked(self, sh: _Shard, key: str) -> None:
        i = bisect.bisect_left(sh.keys, key)
        if i < len(sh.keys) and sh.keys[i] == key:
            del sh.keys[i]

    def _commit_shard_locked(self, sh: _Shard, action: str, key: str,
                             prev: Optional[KV], build
                             ) -> Tuple[Optional[KV], StoreEvent]:
        """Assign the next revision, build the KV at that revision
        (``build(rev) -> KV``, or None for delete/expire), record into
        the shard's ring, persist, and fan out to root watchers — ONE
        rev-lock critical section, which is what keeps ``_index`` a
        total order across shards AND keeps root-watcher frames in
        revision order (assignment and delivery can never interleave
        between two writers). The caller — still holding the shard
        lock — then mutates shard data and delivers to the shard's own
        watchers via _deliver_shard_locked."""
        with self._rev_lock:
            self._index += 1
            kv = build(self._index) if build is not None else None
            ev = StoreEvent(action, key, self._index, kv, prev)
            sh.history.append(ev)
            if len(sh.history) > self.HISTORY_WINDOW:
                drop = len(sh.history) - self.HISTORY_WINDOW
                sh.evicted_through = sh.history[drop - 1].index
                del sh.history[:drop]
            self._persist_rev_locked(ev, sh.sid)
            for ent in list(self._root_watchers):
                prefix, recursive, w = ent
                if w.stopped:
                    self._root_watchers.remove(ent)
                    continue
                if _match(key, prefix, recursive):
                    w.send(watchpkg.Event(ev.action, ev))
        return kv, ev

    def _deliver_shard_locked(self, sh: _Shard, ev: StoreEvent) -> None:
        """Fan out to this shard's own watchers — under the shard lock
        only, never the rev lock: a pod storm here blocks its own
        namespace shard, not the cluster."""
        for ent in list(sh.watchers):
            prefix, recursive, w = ent
            if w.stopped:
                sh.watchers.remove(ent)
                continue
            if _match(ev.key, prefix, recursive):
                w.send(watchpkg.Event(ev.action, ev))

    def _sweep_shard_locked(self, sh: _Shard) -> None:
        if not sh.ttl_heap:
            return
        now = self._clock()
        while sh.ttl_heap and sh.ttl_heap[0][0] <= now:
            _, k = heapq.heappop(sh.ttl_heap)
            kv = sh.data.get(k)
            if kv is None or kv.expiration is None or kv.expiration > now:
                continue  # rewritten since this heap entry; still alive
            self._remove_key_shard_locked(sh, k)
            del sh.data[k]
            _, ev = self._commit_shard_locked(sh, "expire", k, kv, None)
            self._deliver_shard_locked(sh, ev)

    # -- persistence / txn hooks (DurableStripedStore overrides) -----------
    def _persist_rev_locked(self, ev: StoreEvent, sid: int) -> None:
        """Called under the rev lock for every event, in index order."""

    def _txn_begin(self) -> None:
        """A batched verb's apply phase begins (its shard locks held)."""

    def _txn_boundary(self) -> None:
        """One atomic unit's ops are complete: everything persisted
        since the last boundary must land all-or-nothing."""

    def _txn_commit(self) -> None:
        """The batched verb is done: flush every sealed unit once."""

    def _after_op(self) -> None:
        """Post-verb hook, called with NO locks held (lazy compaction
        in the durable subclass — compaction needs every shard lock, so
        it can never run inside a partially-locked write path)."""

    # -- reads -------------------------------------------------------------
    @property
    def index(self) -> int:
        with self._rev_lock:
            return self._index

    def get(self, key: str) -> KV:
        sh = self._shard_of(key)
        with sh.lock:
            self._maybe_raise("get", key)
            self._sweep_shard_locked(sh)
            kv = sh.data.get(key)
            if kv is None:
                raise ErrKeyNotFound(key)
        self._after_op()
        return kv

    def get_many(self, keys: List[str]) -> List[Optional[KV]]:
        sids = sorted({self._sid_of(k) for k in keys})
        self._acquire(sids)
        try:
            for k in keys:
                self._maybe_raise("get", k)
            for sid in sids:
                self._sweep_shard_locked(self._shards[sid])
            out = [self._shard_of(k).data.get(k) for k in keys]
        finally:
            self._release(sids)
        self._after_op()
        return out

    def list(self, prefix: str) -> Tuple[List[KV], int]:
        """All KVs under prefix (recursive), key-ascending exactly like
        MemStore (list bytes are part of the bit-identity gate), + the
        store index at read time. A 3+-segment prefix scans one shard;
        a root prefix scans all shards (ascending) and merges by key."""
        sids = self._sids_for_prefix(prefix, True)
        if sids is None:
            sids = list(range(self.shards))
        norm = prefix + "/" if prefix and not prefix.endswith("/") else prefix
        self._acquire(sids)
        try:
            self._maybe_raise("list", prefix)
            runs: List[List[KV]] = []
            for sid in sids:
                sh = self._shards[sid]
                self._sweep_shard_locked(sh)
                i = bisect.bisect_left(sh.keys, norm)
                run: List[KV] = []
                keys = sh.keys
                while i < len(keys) and keys[i].startswith(norm):
                    run.append(sh.data[keys[i]])
                    i += 1
                if run:
                    runs.append(run)
            with self._rev_lock:
                idx = self._index
        finally:
            self._release(sids)
        self._after_op()
        if len(runs) == 1:
            return runs[0], idx
        return list(heapq.merge(*runs, key=lambda kv: kv.key)), idx

    # -- writes ------------------------------------------------------------
    def create(self, key: str, value: str, ttl: Optional[float] = None) -> KV:
        sh = self._shard_of(key)
        with sh.lock:
            self._maybe_raise("create", key)
            self._sweep_shard_locked(sh)
            if key in sh.data:
                raise ErrKeyExists(key)
            exp = self._clock() + ttl if ttl else None
            kv, ev = self._commit_shard_locked(
                sh, "create", key, None,
                lambda rev: KV(key, value, rev, rev, exp))
            self._insert_key_shard_locked(sh, key)
            sh.data[key] = kv
            if exp is not None:
                heapq.heappush(sh.ttl_heap, (exp, key))
            self._deliver_shard_locked(sh, ev)
        self._count(sh.sid, 1)
        self._after_op()
        return kv

    def set(self, key: str, value: str, ttl: Optional[float] = None) -> KV:
        """Unconditional write (create or replace)."""
        sh = self._shard_of(key)
        with sh.lock:
            self._maybe_raise("set", key)
            self._sweep_shard_locked(sh)
            prev = sh.data.get(key)
            exp = self._clock() + ttl if ttl else None
            kv, ev = self._commit_shard_locked(
                sh, "set" if prev else "create", key, prev,
                lambda rev: KV(key, value,
                               prev.created_index if prev else rev,
                               rev, exp))
            self._insert_key_shard_locked(sh, key)
            sh.data[key] = kv
            if exp is not None:
                heapq.heappush(sh.ttl_heap, (exp, key))
            self._deliver_shard_locked(sh, ev)
        self._count(sh.sid, 1)
        self._after_op()
        return kv

    def compare_and_swap(self, key: str, value: str, prev_index: int,
                         ttl: Optional[float] = None) -> KV:
        sh = self._shard_of(key)
        with sh.lock:
            self._maybe_raise("compare_and_swap", key)
            self._sweep_shard_locked(sh)
            prev = sh.data.get(key)
            if prev is None:
                raise ErrKeyNotFound(key)
            if prev.modified_index != prev_index:
                raise ErrCASConflict(
                    f"{key}: index mismatch (have {prev.modified_index}, "
                    f"want {prev_index})")
            exp = self._clock() + ttl if ttl else None
            kv, ev = self._commit_shard_locked(
                sh, "compareAndSwap", key, prev,
                lambda rev: KV(key, value, prev.created_index, rev, exp))
            sh.data[key] = kv
            if exp is not None:
                heapq.heappush(sh.ttl_heap, (exp, key))
            self._deliver_shard_locked(sh, ev)
        self._count(sh.sid, 1)
        self._after_op()
        return kv

    def delete(self, key: str, prev_index: Optional[int] = None) -> KV:
        sh = self._shard_of(key)
        with sh.lock:
            self._maybe_raise("delete", key)
            self._sweep_shard_locked(sh)
            prev = sh.data.get(key)
            if prev is None:
                raise ErrKeyNotFound(key)
            if prev_index is not None and prev.modified_index != prev_index:
                raise ErrCASConflict(
                    f"{key}: index mismatch (have {prev.modified_index}, "
                    f"want {prev_index})")
            del sh.data[key]
            self._remove_key_shard_locked(sh, key)
            _, ev = self._commit_shard_locked(sh, "delete", key, prev, None)
            self._deliver_shard_locked(sh, ev)
        self._count(sh.sid, 1)
        self._after_op()
        return prev

    def compare_and_swap_many(self, items: List[Tuple[str, str, int]]
                              ) -> List[object]:
        """Batched CAS under ONE acquisition of every involved shard
        lock (ascending): per-item outcomes positional, every success
        its own revision + watch event in order — the wave-commit
        primitive, semantics identical to MemStore's."""
        out: List[object] = []
        sids = sorted({self._sid_of(k) for k, _v, _p in items})
        self._acquire(sids)
        try:
            for sid in sids:
                self._sweep_shard_locked(self._shards[sid])
            self._txn_begin()
            try:
                for key, value, prev_index in items:
                    sh = self._shard_of(key)
                    try:
                        self._maybe_raise("compare_and_swap", key)
                    except StoreError as e:
                        out.append(e)
                        continue
                    prev = sh.data.get(key)
                    if prev is None:
                        out.append(ErrKeyNotFound(key))
                        continue
                    if prev.modified_index != prev_index:
                        out.append(ErrCASConflict(
                            f"{key}: index mismatch (have "
                            f"{prev.modified_index}, want {prev_index})"))
                        continue
                    kv, ev = self._commit_shard_locked(
                        sh, "compareAndSwap", key, prev,
                        lambda rev, k=key, v=value, p=prev: KV(
                            k, v, p.created_index, rev, None))
                    sh.data[key] = kv
                    self._deliver_shard_locked(sh, ev)
                    self._txn_boundary()
                    out.append(kv)
            finally:
                self._txn_commit()
        finally:
            self._release(sids)
        self._count(sids[0] if len(sids) == 1 else -1, len(items))
        self._after_op()
        return out

    def txn_many(self, items: List[Tuple[List[Tuple[str, str, int]],
                                         List[Tuple[str, int]]]]
                 ) -> List[object]:
        """Per-item all-or-nothing transactions (the evict+bind commit
        primitive) under ONE acquisition of every involved shard lock,
        ascending. Cross-shard items stay atomic: every guard of an item
        is validated while ALL its shards are held, so no concurrent
        writer can invalidate a guard between validation and apply."""
        out: List[object] = []
        sids = set()
        for cas_ops, delete_ops in items:
            for key, _v, _p in cas_ops:
                sids.add(self._sid_of(key))
            for key, _p in delete_ops:
                sids.add(self._sid_of(key))
        sids = sorted(sids)
        self._acquire(sids)
        try:
            for sid in sids:
                self._sweep_shard_locked(self._shards[sid])
            self._txn_begin()
            try:
                self._txn_many_shards_locked(items, out)
            finally:
                self._txn_commit()
        finally:
            self._release(sids)
        self._count(sids[0] if len(sids) == 1 else -1, len(items))
        self._after_op()
        return out

    def _txn_many_shards_locked(self, items, out: List[object]) -> None:
        for cas_ops, delete_ops in items:
            err: Optional[StoreError] = None
            for key, _value, prev_index in cas_ops:
                try:
                    self._maybe_raise("compare_and_swap", key)
                except StoreError as e:
                    err = e
                    break
                prev = self._shard_of(key).data.get(key)
                if prev is None:
                    err = ErrKeyNotFound(key)
                    break
                if prev.modified_index != prev_index:
                    err = ErrCASConflict(
                        f"{key}: index mismatch (have "
                        f"{prev.modified_index}, want {prev_index})")
                    break
            if err is None:
                for key, prev_index in delete_ops:
                    try:
                        self._maybe_raise("delete", key)
                    except StoreError as e:
                        err = e
                        break
                    prev = self._shard_of(key).data.get(key)
                    if prev is None:
                        err = ErrKeyNotFound(key)
                        break
                    if prev.modified_index != prev_index:
                        err = ErrCASConflict(
                            f"{key}: index mismatch (have "
                            f"{prev.modified_index}, want {prev_index})")
                        break
            if err is not None:
                out.append(err)
                continue
            written: List[KV] = []
            for key, value, _prev_index in cas_ops:
                sh = self._shard_of(key)
                prev = sh.data[key]
                kv, ev = self._commit_shard_locked(
                    sh, "compareAndSwap", key, prev,
                    lambda rev, k=key, v=value, p=prev: KV(
                        k, v, p.created_index, rev, None))
                sh.data[key] = kv
                self._deliver_shard_locked(sh, ev)
                written.append(kv)
            for key, _prev_index in delete_ops:
                sh = self._shard_of(key)
                prev = sh.data[key]
                del sh.data[key]
                self._remove_key_shard_locked(sh, key)
                _, ev = self._commit_shard_locked(
                    sh, "delete", key, prev, None)
                self._deliver_shard_locked(sh, ev)
            out.append(written)
            # seal the item: its ops persist as ONE atomic WAL record
            self._txn_boundary()

    # -- watch -------------------------------------------------------------
    def watch(self, prefix: str, from_index: int = 0,
              recursive: bool = True,
              lag_limit: Optional[int] = None) -> watchpkg.Watcher:
        """Stream StoreEvents for keys under prefix with index >
        from_index — MemStore's contract, enforced per shard:

        - a 3+-segment (or non-recursive) prefix registers on its ONE
          shard; replay and staleness come from that shard's ring, and
          live fan-out runs under that shard's lock only;
        - a root prefix registers on the global list; replay is the
          revision-ordered merge of every shard's ring, staleness is
          checked against EVERY shard's evicted floor (a gap in any
          shard the prefix spans is a gap in the merged stream), and
          live fan-out runs under the rev lock — which is exactly what
          makes the merged stream revision-ordered.

        A resume token below a relevant shard's evicted floor raises
        ErrIndexOutdated -> the 410 Expired/re-list Reflector path,
        never a silent skip.
        """
        sids = self._sids_for_prefix(prefix, recursive)
        if sids is not None and len(sids) == 1:
            sh = self._shards[sids[0]]
            with sh.lock:
                self._maybe_raise("watch", prefix)
                if from_index:
                    floor = max(sh.evicted_through, self._replay_floor)
                    if from_index < floor:
                        raise ErrIndexOutdated(
                            f"requested index {from_index} is outside the "
                            f"history window of shard {sh.sid}")
                w = watchpkg.Watcher(
                    lag_limit=lag_limit,
                    coalesce=_coalesce_store_events if lag_limit else None)
                if from_index:
                    for ev in sh.history:
                        if ev.index > from_index and _match(
                                ev.key, prefix, recursive):
                            w.send(watchpkg.Event(ev.action, ev))
                sh.watchers.append((prefix, recursive, w))
                return w
        # root prefix: register under the rev lock — ring appends happen
        # under it too, so replay-then-register has no lost-event gap
        with self._rev_lock:
            self._maybe_raise("watch", prefix)
            if from_index:
                floor = max([self._replay_floor]
                            + [sh.evicted_through for sh in self._shards])
                if from_index < floor and from_index < self._index:
                    raise ErrIndexOutdated(
                        f"requested index {from_index} is outside the "
                        f"history window")
            w = watchpkg.Watcher(
                lag_limit=lag_limit,
                coalesce=_coalesce_store_events if lag_limit else None)
            if from_index:
                for ev in heapq.merge(
                        *(sh.history for sh in self._shards),
                        key=lambda e: e.index):
                    if ev.index > from_index and _match(
                            ev.key, prefix, recursive):
                        w.send(watchpkg.Event(ev.action, ev))
            self._root_watchers.append((prefix, recursive, w))
            return w

    # -- disclosure --------------------------------------------------------
    def shard_stats(self) -> dict:
        """Per-shard occupancy for records/healthz (locks taken briefly,
        ascending)."""
        keys, watchers = [], []
        for sh in self._shards:
            with sh.lock:
                keys.append(len(sh.keys))
                watchers.append(len(sh.watchers))
        with self._rev_lock:
            root = len(self._root_watchers)
            idx = self._index
        return {"shards": self.shards, "index": idx, "keys": keys,
                "shard_watchers": watchers, "root_watchers": root}

    def _count(self, sid: int, n: int) -> None:
        # metrics OUTSIDE every store lock: the counter has its own
        # mutex and must never appear inside the shard/rev sections
        self._mx.ops.inc("cross" if sid < 0 else str(sid), by=n)


class DurableStripedStore(StripedStore):
    """StripedStore persisted by the SAME WAL + snapshot format as
    DurableStore (storage/durable.py) — byte-compatible both ways, plus
    a shard tag (``"s"``) on each WAL entry so replay tooling can
    attribute records without rehashing keys.

    The rev lock serializes every WAL append, so WAL order is revision
    order for serial verbs; a batched verb buffers its item's entries
    thread-locally (other shards' writers keep appending their own
    records meanwhile) and seals each atomic item into ONE record at the
    boundary, flushing the whole call once at commit — group commit,
    unchanged. Replay is order-insensitive across interleaved records
    because a batch holds all its shard locks for its whole apply phase:
    no interleaved record can touch a batch's keys, and per-key index
    order is preserved.

    Compaction needs every shard lock (snapshot = merged global state),
    so it can't run inside `_wal_append_rev_locked` like DurableStore's;
    the append marks compaction pending and `_after_op` — called with no
    locks held — takes all shard locks ascending + the rev lock and
    compacts there.
    """

    def __init__(self, directory: str, shards: int = 8,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time,
                 fsync: bool = False, compact_every: int = 10_000):
        super().__init__(shards=shards, clock=clock)
        self._dir = directory
        self._wall = wall_clock
        self._fsync = fsync
        self._compact_every = compact_every
        self._compact_pending = False
        self._wal_records = 0
        self._wal_bytes = 0
        self._wal_f = None  # set after recovery; persist no-ops until
        self._txn_tls = threading.local()
        self._wmx = metrics_pkg.store_wal_metrics()
        os.makedirs(directory, exist_ok=True)
        self._recover()
        self._wal_f = open(os.path.join(directory, _WAL), "a",
                           encoding="utf-8")
        self._wal_bytes = os.path.getsize(os.path.join(directory, _WAL))
        self._wmx.wal_size.set(self._wal_bytes)
        self._wal_records = self._recovered_records
        if self._wal_records >= self._compact_every:
            self.compact()

    # -- wall-clock TTL rebasing (DurableStore contract) -------------------
    def _exp_to_wall(self, exp_mono: Optional[float]) -> Optional[float]:
        if exp_mono is None:
            return None
        return self._wall() + (exp_mono - self._clock())

    def _exp_from_wall(self, exp_wall: Optional[float]) -> Optional[float]:
        if exp_wall is None:
            return None
        return self._clock() + (exp_wall - self._wall())

    def _entry_of(self, ev: StoreEvent, sid: int) -> dict:
        entry = {"a": ev.action, "k": ev.key, "i": ev.index, "s": sid}
        if ev.kv is not None:
            entry["v"] = ev.kv.value
            entry["c"] = ev.kv.created_index
            if ev.kv.expiration is not None:
                entry["e"] = self._exp_to_wall(ev.kv.expiration)
        return entry

    # -- persistence hooks --------------------------------------------------
    def _persist_rev_locked(self, ev: StoreEvent, sid: int) -> None:
        if self._wal_f is None:
            return  # replaying recovery
        entry = self._entry_of(ev, sid)
        buf = getattr(self._txn_tls, "buf", None)
        if buf is not None:
            # this thread is inside a batched verb: buffer; the boundary
            # seals the item into one record, the commit flushes once
            buf.append(entry)
            self._txn_tls.ops += 1
            return
        self._wal_append_rev_locked([json.dumps(entry)], ops=1)

    def _txn_begin(self) -> None:
        if self._wal_f is None:
            return
        self._txn_tls.buf = []
        self._txn_tls.lines = []
        self._txn_tls.ops = 0

    def _txn_boundary(self) -> None:
        buf = getattr(self._txn_tls, "buf", None)
        if not buf:
            return  # outside a batch, or the item recorded nothing
        line = json.dumps(buf[0]) if len(buf) == 1 \
            else json.dumps({"txn": buf})
        self._txn_tls.lines.append(line)
        self._txn_tls.buf = []

    def _txn_commit(self) -> None:
        if getattr(self._txn_tls, "buf", None) is None:
            return
        self._txn_boundary()  # seal a dangling unit defensively
        lines, ops = self._txn_tls.lines, self._txn_tls.ops
        self._txn_tls.buf = None
        self._txn_tls.lines = []
        self._txn_tls.ops = 0
        if lines:
            with self._rev_lock:
                self._wal_append_rev_locked(lines, ops=ops)

    def _wal_append_rev_locked(self, lines: List[str], ops: int) -> None:
        """The ONLY writer of WAL bytes, always under the rev lock —
        one write+flush(+fsync) per call. Chaos crash points keep the
        exact seam names DurableStore uses so the WAL atomicity tests
        exercise both stores identically."""
        chaos.crash_if_armed("durable.wal_append.pre")
        data = "\n".join(lines) + "\n"
        self._wal_f.write(data)
        self._wal_f.flush()
        if self._fsync:
            os.fsync(self._wal_f.fileno())
            self._wmx.fsyncs.inc()
        chaos.crash_if_armed("durable.wal_append.post")
        self._wal_records += len(lines)
        self._wal_bytes += len(data)
        self._wmx.records.inc(by=len(lines))
        self._wmx.ops.inc(by=ops)
        self._wmx.group_commits.inc()
        self._wmx.bytes_written.inc(by=len(data))
        self._wmx.wal_size.set(self._wal_bytes)
        if self._wal_records >= self._compact_every:
            self._compact_pending = True

    def _after_op(self) -> None:
        if self._compact_pending and self._wal_f is not None:
            self.compact()

    # -- snapshot / compaction ---------------------------------------------
    def _kv_dict(self, kv: Optional[KV]) -> Optional[dict]:
        if kv is None:
            return None
        d = {"k": kv.key, "v": kv.value, "c": kv.created_index,
             "m": kv.modified_index}
        if kv.expiration is not None:
            d["e"] = self._exp_to_wall(kv.expiration)
        return d

    def _kv_from_dict(self, d: Optional[dict]) -> Optional[KV]:
        if d is None:
            return None
        return KV(d["k"], d["v"], d["c"], d["m"],
                  self._exp_from_wall(d.get("e")))

    def compact(self) -> None:
        """Force a snapshot + WAL truncation. Takes every shard lock
        ascending, then the rev lock — the canonical order."""
        sids = list(range(self.shards))
        self._acquire(sids)
        try:
            with self._rev_lock:
                self._compact_all_locked()
                self._compact_pending = False
        finally:
            self._release(sids)

    def _compact_all_locked(self) -> None:
        """Caller holds every shard lock + the rev lock. The snapshot is
        the merged global state — key-ascending kvs, revision-ordered
        history — so it is byte-compatible with DurableStore's."""
        snap = {
            "index": self._index,
            "kvs": [self._kv_dict(kv) for kv in heapq.merge(
                *([sh.data[k] for k in sh.keys] for sh in self._shards),
                key=lambda kv: kv.key)],
            "history": [
                {"a": ev.action, "k": ev.key, "i": ev.index,
                 "kv": self._kv_dict(ev.kv), "pv": self._kv_dict(ev.prev_kv)}
                for ev in heapq.merge(
                    *(sh.history for sh in self._shards),
                    key=lambda e: e.index)
            ],
        }
        tmp = os.path.join(self._dir, _SNAP + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, _SNAP))
        self._wal_f.close()
        self._wal_f = open(os.path.join(self._dir, _WAL), "w",
                           encoding="utf-8")
        self._wal_records = 0
        self._wal_bytes = 0
        self._wmx.compactions.inc()
        self._wmx.wal_size.set(0)
        self._wmx.snapshot_size.set(
            os.path.getsize(os.path.join(self._dir, _SNAP)))

    # -- recovery -----------------------------------------------------------
    def _apply_entry(self, d: dict) -> None:
        """Recovery-time replay of one WAL/txn entry into its shard
        (constructor context: single-threaded, no locks)."""
        idx = d["i"]
        key = d["k"]
        action = d["a"]
        sh = self._shard_of(key)
        prev = sh.data.get(key)
        if action in ("delete", "expire"):
            if prev is not None:
                self._remove_key_shard_locked(sh, key)
                del sh.data[key]
            kv = None
        else:
            kv = KV(key, d.get("v", ""), d.get("c", idx), idx,
                    self._exp_from_wall(d.get("e")))
            self._insert_key_shard_locked(sh, key)
            sh.data[key] = kv
            if kv.expiration is not None:
                heapq.heappush(sh.ttl_heap, (kv.expiration, key))
        self._index = max(self._index, idx)
        sh.history.append(StoreEvent(action, key, idx, kv, prev))
        if len(sh.history) > self.HISTORY_WINDOW:
            drop = len(sh.history) - self.HISTORY_WINDOW
            sh.evicted_through = sh.history[drop - 1].index
            del sh.history[:drop]

    def _replay_record(self, d: dict) -> int:
        if "txn" in d:
            ops = 0
            for e in d["txn"]:
                if e["i"] <= self._snap_index_guard:
                    continue  # pre-snapshot entry (crash mid-compact)
                self._apply_entry(e)
                ops += 1
            return ops
        if d["i"] <= self._snap_index_guard:
            return 0
        self._apply_entry(d)
        return 1

    def _recover(self) -> None:
        t0 = time.perf_counter()
        self._snap_index_guard = 0
        self._recovered_records = 0
        recovered_ops = 0
        snapshot_age_s = 0.0
        torn_bytes = 0
        snap_path = os.path.join(self._dir, _SNAP)
        if os.path.exists(snap_path):
            snapshot_age_s = max(0.0, self._wall()
                                 - os.path.getmtime(snap_path))
            self._wmx.snapshot_size.set(os.path.getsize(snap_path))
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            self._index = max(1, snap["index"])
            self._snap_index_guard = snap["index"]
            for d in snap["kvs"]:
                kv = self._kv_from_dict(d)
                sh = self._shard_of(kv.key)
                self._insert_key_shard_locked(sh, kv.key)
                sh.data[kv.key] = kv
                if kv.expiration is not None:
                    heapq.heappush(sh.ttl_heap, (kv.expiration, kv.key))
            hist = snap.get("history", [])
            for d in hist:
                sh = self._shard_of(d["k"])
                sh.history.append(StoreEvent(
                    d["a"], d["k"], d["i"],
                    self._kv_from_dict(d.get("kv")),
                    self._kv_from_dict(d.get("pv"))))
            # staleness floor: events below the snapshot's retained
            # window are gone for EVERY shard, whichever ring they
            # would have lived in — resume tokens below it must 410
            self._replay_floor = (hist[0]["i"] - 1) if hist \
                else snap["index"]
        wal_path = os.path.join(self._dir, _WAL)
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                data = f.read()
            good_end = 0
            bad_at = None
            pos = 0
            for raw in data.splitlines(keepends=True):
                line = raw.strip()
                pos += len(raw)
                if not line:
                    good_end = pos
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    bad_at = pos - len(raw)
                    break  # torn/corrupt record: stop at the last good one
                good_end = pos
                self._recovered_records += 1
                recovered_ops += self._replay_record(d)
            if bad_at is not None:
                discarded = len(data) - good_end
                torn_bytes = discarded
                tail = data[good_end:]
                midfile = any(_parses(l) for l in tail.splitlines()[1:])
                _log.error(
                    "WAL %s: unparseable record at byte %d; discarding %d "
                    "trailing bytes (%s) and truncating to last good record",
                    wal_path, bad_at, discarded,
                    "MID-FILE CORRUPTION — parseable records were lost"
                    if midfile else "torn tail from a crash")
                with open(wal_path, "r+b") as f:
                    f.truncate(good_end)
        recovery_s = time.perf_counter() - t0
        self.recovery = {
            "replayed_records": self._recovered_records,
            "replayed_ops": recovered_ops,
            "snapshot": os.path.exists(snap_path),
            "snapshot_age_s": round(snapshot_age_s, 3),
            "torn_bytes": torn_bytes,
            "recovery_s": round(recovery_s, 4),
            "index": self._index,
            "shards": self.shards,
        }
        self._wmx.recovery_s.observe(recovery_s)
        self._wmx.replayed.set(self._recovered_records)
        self._wmx.snapshot_age.set(snapshot_age_s)
        if torn_bytes:
            self._wmx.torn_bytes.inc(by=torn_bytes)
