"""kube-vet — invariant-enforcing static analysis for the control plane.

The reference tree gates every change through govet/golint
(ref: hack/test-go.sh); this package is the project-specific analog. It
does NOT re-implement a general linter: every rule encodes one
hard-won, machine-checkable invariant of THIS codebase, each motivated
by a real incident (the r11 donation heap corruption, the PR 1
f-string that silently muted 13 test modules) or a documented contract
(the read-only-store-objects invariant, the bounded-queue discipline).

Rule table, motivating incidents, and the waiver policy:
docs/design/invariants.md. CLI: ``python hack/vet.py``.
"""

from kubernetes_tpu.analysis.engine import (  # noqa: F401
    FileContext, Rule, Violation, Waiver, all_rules, default_paths,
    format_violation, load_context, run_vet)
from kubernetes_tpu.analysis import rules  # noqa: F401  (registers rules)

__all__ = ["FileContext", "Rule", "Violation", "Waiver", "all_rules",
           "default_paths", "format_violation", "load_context", "run_vet",
           "rules"]
