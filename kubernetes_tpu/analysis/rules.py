"""The kube-vet rule set. Every rule encodes one invariant this repo
already paid for at runtime; docs/design/invariants.md carries the full
table (rule id, invariant, motivating incident, waiver policy).

Rules report against the statement span, so a waiver comment on any
line of the flagged statement (or the line above it) silences exactly
that finding — see engine.py for the waiver grammar.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kubernetes_tpu.analysis.engine import (FileContext, Rule, Violation,
                                            register)

__all__ = ["DonationSafetyRule", "CloneMutationRule", "ThreadDisciplineRule",
           "Py310CompatRule", "MetricsSyncRule", "UnusedNamesRule"]


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing_stmt(node: ast.AST, parents: Dict[ast.AST, ast.AST]):
    cur: Optional[ast.AST] = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = parents.get(cur)
    return cur


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _import_map(tree: ast.AST) -> Dict[str, str]:
    """Local name -> fully dotted origin ('Popen' -> 'subprocess.Popen')."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _resolve(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Fully qualified dotted path of a Name/Attribute, through imports."""
    d = _dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    origin = imports.get(head, head)
    return f"{origin}.{rest}" if rest else origin


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------------------
# donation-safety — the r11 heap-corruption class
# ---------------------------------------------------------------------------

_OWNED_PAT = re.compile(r"donat|owned", re.IGNORECASE)


def _is_empty_donation(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value in (False, None):
        return True
    return isinstance(node, (ast.Tuple, ast.List)) and not node.elts


def _guarded_by_provenance(node: ast.AST) -> bool:
    """True for 'X if <owned-flag> else ()'-shaped donation values and
    for plain references to an ownership-named flag: the decision to
    donate must visibly flow from buffer provenance."""
    if isinstance(node, ast.IfExp):
        safe_else = _is_empty_donation(node.orelse)
        guard_named = any(_OWNED_PAT.search(n) for n in _names_in(node.test))
        return safe_else and guard_named
    d = _dotted(node)
    if d is not None and _OWNED_PAT.search(d):
        return True
    return False


@register
class DonationSafetyRule(Rule):
    """Any ``donate_argnums=``/``donate=`` site that can donate must be
    gated on an ownership flag (``xla_owned``-style provenance).

    Motivating incident: PR 7's ride-along fix — solver/mesh_exec.py
    donated device buffers that on the CPU backend ALIASED host numpy
    (zero-copy ``jax.device_put``); XLA freed memory numpy still owned
    and the daemon died mid-churn with ``malloc(): unsorted double
    linked list corrupted``. An unconditional donation is statically
    indistinguishable from that bug, so it must either be guarded by a
    provenance-named flag or carry a waiver explaining why the buffer
    can never alias host memory.
    """

    id = "donation-safety"
    doc = "donation must be gated on buffer-ownership provenance"

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.tree is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg not in ("donate_argnums", "donate_argnames",
                                  "donate"):
                    continue
                if _is_empty_donation(kw.value) \
                        or _guarded_by_provenance(kw.value):
                    continue
                yield ctx.violation(
                    self.id, node,
                    f"{kw.arg}={ast.unparse(kw.value)}: donation is not "
                    f"provably gated on buffer ownership — a device_put "
                    f"of host numpy may alias it on the CPU backend "
                    f"(the r11 malloc-corruption class); gate on an "
                    f"xla_owned-style flag ('(0,) if xla_owned else ()') "
                    f"or waive with the provenance argument")


# ---------------------------------------------------------------------------
# clone-mutation — the read-only-store-objects invariant
# ---------------------------------------------------------------------------

_MUTATORS = frozenset({"append", "extend", "insert", "remove", "pop",
                       "popitem", "clear", "update", "setdefault", "add",
                       "discard", "sort", "reverse"})
_CTOR_METHODS = frozenset({"__init__", "__new__", "__setstate__",
                           "__deepcopy__", "__copy__", "__post_init__",
                           "__init_subclass__"})
_CLONE_FILE = "kubernetes_tpu/runtime/clone.py"


@register
class CloneMutationRule(Rule):
    """No in-place mutation of objects on ``runtime/clone.py``
    shared-clone paths.

    ``deep_clone`` shares leaves of the ``_ATOMIC`` classes verbatim
    between original and clone, and the codebase-wide invariant says
    store/reflector objects are read-only (mutations go through
    ``deep_clone``; models/snapshot.py keys its ``_ktpu_rows`` cache on
    that promise). Three statically checkable facets:

    1. every repo-local class in ``_ATOMIC`` must be immutable — no
       method outside construction assigns ``self.<attr>``;
    2. after ``x = deep_clone(y)``, the SOURCE ``y`` must not be
       mutated in that function (you cloned because ``y`` is shared;
       mutate the clone);
    3. inside ``deep_clone`` itself, no wholesale ``__dict__`` copy —
       declared fields only, or derived caches ride onto mutable clones.
    """

    id = "clone-mutation"
    doc = "clone-shared objects are read-only; mutate the clone"

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("kubernetes_tpu/")

    def check_tree(self, ctxs: Sequence[FileContext]) -> Iterable[Violation]:
        by_rel = {c.rel: c for c in ctxs}
        clone_ctx = by_rel.get(_CLONE_FILE)
        if clone_ctx is not None and clone_ctx.tree is not None:
            yield from self._check_clone_module(clone_ctx)
            for cls_name in self._atomic_local_classes(clone_ctx):
                yield from self._check_immutable(cls_name, ctxs)
        for ctx in ctxs:
            yield from self._check_source_mutation(ctx)

    # facet 1 ---------------------------------------------------------------
    @staticmethod
    def _atomic_local_classes(clone_ctx: FileContext) -> List[str]:
        """Plain-Name entries of the _ATOMIC frozenset — repo-local
        classes shared verbatim between clone and original (builtins and
        stdlib attributes like datetime.datetime are Attribute/Call
        nodes or well-known immutables, skipped)."""
        out: List[str] = []
        skip = {"str", "int", "float", "bool", "bytes", "complex",
                "frozenset", "tuple", "type"}
        for node in ast.walk(clone_ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "_ATOMIC":
                for call in ast.walk(node.value):
                    if isinstance(call, (ast.Set, ast.Tuple, ast.List)):
                        for elt in call.elts:
                            if isinstance(elt, ast.Name) \
                                    and elt.id not in skip:
                                out.append(elt.id)
        return out

    def _check_immutable(self, cls_name: str,
                         ctxs: Sequence[FileContext]) -> Iterable[Violation]:
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.ClassDef)
                        and node.name == cls_name):
                    continue
                for meth in node.body:
                    if not isinstance(meth, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)) \
                            or meth.name in _CTOR_METHODS:
                        continue
                    for sub in ast.walk(meth):
                        tgt = None
                        if isinstance(sub, (ast.Assign, ast.AugAssign)):
                            tgts = sub.targets if isinstance(
                                sub, ast.Assign) else [sub.target]
                            for t in tgts:
                                if isinstance(t, (ast.Attribute,
                                                  ast.Subscript)) \
                                        and isinstance(
                                            getattr(t, "value", None),
                                            ast.Name) \
                                        and t.value.id == "self":
                                    tgt = t
                        if tgt is not None:
                            yield ctx.violation(
                                self.id, sub,
                                f"{cls_name}.{meth.name} mutates self — "
                                f"{cls_name} is in runtime/clone.py "
                                f"_ATOMIC (shared verbatim between clone "
                                f"and original) and must stay immutable "
                                f"outside construction")
                            break

    # facet 2 ---------------------------------------------------------------
    def _check_source_mutation(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.tree is None or "deep_clone" not in ctx.source:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            sources: List[Tuple[str, int]] = []   # (unparsed expr, line)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    f = node.value.func
                    fname = f.id if isinstance(f, ast.Name) else \
                        (f.attr if isinstance(f, ast.Attribute) else "")
                    if fname == "deep_clone" and node.value.args \
                            and _dotted(node.value.args[0]) is not None:
                        sources.append((ast.unparse(node.value.args[0]),
                                        node.lineno))
            if not sources:
                continue
            for node in ast.walk(fn):
                mutated = self._mutated_expr(node)
                if mutated is None:
                    continue
                for src, line in sources:
                    if node.lineno <= line:
                        continue
                    if mutated == src or mutated.startswith(src + ".") \
                            or mutated.startswith(src + "["):
                        yield ctx.violation(
                            self.id, node,
                            f"in-place mutation of {mutated!r} after "
                            f"deep_clone({src}) at line {line} — the "
                            f"source is the SHARED object (that's why it "
                            f"was cloned); mutate the clone instead")
                        break

    @staticmethod
    def _mutated_expr(node: ast.AST) -> Optional[str]:
        """Unparsed object expression a statement mutates in place."""
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return ast.unparse(t.value)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            return ast.unparse(node.func.value)
        return None

    # facet 3 ---------------------------------------------------------------
    def _check_clone_module(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            bad = False
            if isinstance(node, ast.Call):
                # dict(obj.__dict__) — wholesale copy
                if isinstance(node.func, ast.Name) \
                        and node.func.id == "dict" and node.args \
                        and isinstance(node.args[0], ast.Attribute) \
                        and node.args[0].attr == "__dict__":
                    bad = True
                # new.__dict__.update(...)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "update" \
                        and isinstance(node.func.value, ast.Attribute) \
                        and node.func.value.attr == "__dict__":
                    bad = True
            if bad:
                yield ctx.violation(
                    self.id, node,
                    "wholesale __dict__ copy in runtime/clone.py — "
                    "deep_clone must copy DECLARED dataclass fields only "
                    "(undeclared attrs are derived caches keyed to the "
                    "original's contents, e.g. PodSpec._ktpu_rows)")


# ---------------------------------------------------------------------------
# thread-discipline — threads stoppable, cross-thread queues bounded
# ---------------------------------------------------------------------------

_UNBOUNDED_QUEUES = {
    "queue.Queue": ("maxsize", 0),
    "queue.LifoQueue": ("maxsize", 0),
    "queue.PriorityQueue": ("maxsize", 0),
    "collections.deque": ("maxlen", 1),
}


@register
class ThreadDisciplineRule(Rule):
    """Every ``threading.Thread`` must be daemonized or joined in a
    reachable stop path; every queue/deque in a threaded module must be
    bounded.

    Motivating incidents: the PR 2 backoff-requeue leak (non-daemon
    requeue threads waiting out their backoff past test teardown,
    killing runs with ConnectionRefusedError tracebacks), and the first
    cut of the PR 4 watch fan-out, where per-watcher unbounded queues
    let one stuck watcher buffer unbounded history. A thread nobody can
    stop and a queue nobody bounded are the same bug at different
    speeds.
    """

    id = "thread-discipline"
    doc = "threads daemonized-or-joined; cross-thread queues bounded"

    def applies_to(self, rel: str) -> bool:
        return not rel.startswith("tests/")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.tree is None:
            return
        imports = _import_map(ctx.tree)
        parents = _parent_map(ctx.tree)
        threaded = any(v == "threading" or v.startswith("threading.")
                       or v == "queue" or v.startswith("queue.")
                       for v in imports.values())
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = _resolve(node.func, imports)
            if target == "threading.Thread":
                yield from self._check_thread(ctx, node, parents)
            elif target == "queue.SimpleQueue" and threaded:
                yield ctx.violation(
                    self.id, node,
                    "queue.SimpleQueue is unbounded by construction — "
                    "use queue.Queue(maxsize=N) so a stalled consumer "
                    "backpressures instead of buffering without limit")
            elif target in _UNBOUNDED_QUEUES and threaded:
                yield from self._check_queue(ctx, node, target)

    def _check_thread(self, ctx, node: ast.Call,
                      parents) -> Iterable[Violation]:
        for kw in node.keywords:
            if kw.arg == "daemon":
                if isinstance(kw.value, ast.Constant):
                    if kw.value.value is True:
                        return
                else:
                    return          # dynamic daemon flag: deliberate
        name = self._binding_name(node, parents)
        if name is not None and self._joined_or_daemonized(ctx, name):
            return
        hint = f" (bound to {name!r})" if name else ""
        yield ctx.violation(
            self.id, node,
            f"thread is neither daemon=True nor joined in a reachable "
            f"stop path{hint} — a non-daemon thread nobody joins "
            f"outlives its owner (the PR 2 backoff-requeue leak class)")

    @staticmethod
    def _binding_name(node: ast.Call, parents) -> Optional[str]:
        stmt = _enclosing_stmt(node, parents)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
        if isinstance(stmt, ast.AnnAssign):
            t = stmt.target
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
        return None

    @staticmethod
    def _joined_or_daemonized(ctx: FileContext, name: str) -> bool:
        # `<name>.join(` anywhere in the module counts as a reachable
        # stop path; so does a post-construction `<name>.daemon = True`
        esc = re.escape(name)
        if re.search(rf"\b{esc}\s*\.\s*join\s*\(", ctx.source):
            return True
        if re.search(rf"\b{esc}\s*\.\s*daemon\s*=\s*True", ctx.source):
            return True
        # collection binding: `for t in <name>: t.join()` joins them all
        for m in re.finditer(rf"\bfor\s+(\w+)\s+in\s+{esc}\b", ctx.source):
            if re.search(rf"\b{re.escape(m.group(1))}\s*\.\s*join\s*\(",
                         ctx.source):
                return True
        return False

    def _check_queue(self, ctx, node: ast.Call,
                     target: str) -> Iterable[Violation]:
        kw_name, pos = _UNBOUNDED_QUEUES[target]
        bound = None
        if len(node.args) > pos:
            bound = node.args[pos]
        for kw in node.keywords:
            if kw.arg == kw_name:
                bound = kw.value
        unbounded = bound is None or (
            isinstance(bound, ast.Constant) and bound.value in (None, 0))
        if unbounded:
            yield ctx.violation(
                self.id, node,
                f"{target.rsplit('.', 1)[-1]} without {kw_name}= in a "
                f"threaded module — an unbounded cross-thread queue "
                f"turns a stalled consumer into unbounded memory growth "
                f"(PR 4 sized every watcher queue for exactly this); "
                f"bound it or waive with the reason the producer is "
                f"bounded elsewhere")


# ---------------------------------------------------------------------------
# py310-compat — the PR 1 muted-test-modules class
# ---------------------------------------------------------------------------

# APIs that import/attribute-resolve fine on 3.11+ but crash (or do not
# exist) on the 3.10 interpreter this repo pins. Names are fully dotted
# post-import-resolution.
_PY311_APIS: Dict[str, str] = {
    "datetime.UTC": "3.11 (use datetime.timezone.utc)",
    "enum.StrEnum": "3.11 (use str + Enum mixin)",
    "enum.ReprEnum": "3.11",
    "asyncio.TaskGroup": "3.11",
    "asyncio.Runner": "3.11",
    "asyncio.timeout": "3.11 (use asyncio.wait_for)",
    "asyncio.timeout_at": "3.11",
    "asyncio.Barrier": "3.11",
    "contextlib.chdir": "3.11",
    "typing.Self": "3.11",
    "typing.LiteralString": "3.11",
    "typing.Never": "3.11",
    "typing.assert_never": "3.11",
    "typing.assert_type": "3.11",
    "typing.dataclass_transform": "3.11",
    "typing.Required": "3.11",
    "typing.NotRequired": "3.11",
    "math.cbrt": "3.11",
    "math.exp2": "3.11",
    "operator.call": "3.11",
    "hashlib.file_digest": "3.11",
    "inspect.getmembers_static": "3.11",
    "sys.exception": "3.11",
    "itertools.batched": "3.12",
}
_PY311_MODULES: Dict[str, str] = {"tomllib": "3.11"}
_PY311_BUILTINS: Dict[str, str] = {"ExceptionGroup": "3.11",
                                   "BaseExceptionGroup": "3.11"}
# keyword-only: valid call shape on 3.11+, TypeError on 3.10 — the
# kubelet process-runtime hit exactly this with Popen(process_group=)
_PY311_KWARGS: Dict[str, Tuple[str, ...]] = {
    "process_group": ("subprocess.Popen", "subprocess.run",
                      "subprocess.call", "subprocess.check_call",
                      "subprocess.check_output"),
}


@register
class Py310CompatRule(Rule):
    """The whole tree must parse and run on Python 3.10.

    Motivating incident: PR 1 found (and fixed) an f-string nested-quote
    SyntaxError in util/metrics.py that silently killed COLLECTION of 13
    test modules on py3.10 — the suite went green by not running. A
    second instance of the class: ``Popen(process_group=...)`` is a
    py3.11 keyword that fails only when the spawn path executes.
    ``ast.parse(feature_version=(3, 10))`` catches the syntax half at
    vet time; a denylist of py3.11+-only stdlib APIs catches the
    runtime half.
    """

    id = "py310-compat"
    doc = "tree parses and runs on python 3.10"

    def applies_to(self, rel: str) -> bool:   # tests too: muted test
        return True                           # modules WERE the incident

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        try:
            ast.parse(ctx.source, filename=ctx.rel,
                      feature_version=(3, 10))
        except SyntaxError as e:
            v = Violation(rule=self.id, path=ctx.rel, line=e.lineno or 1,
                          col=(e.offset or 1) - 1,
                          message=f"does not parse as python 3.10: "
                                  f"{e.msg} (the PR 1 class: one "
                                  f"SyntaxError silently mutes every "
                                  f"importer)",
                          span=(e.lineno or 1, e.lineno or 1))
            yield v
            return
        if ctx.tree is None:
            return
        imports = _import_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod = a.name.split(".")[0]
                    if mod in _PY311_MODULES:
                        yield ctx.violation(
                            self.id, node,
                            f"import {a.name}: module requires python "
                            f">= {_PY311_MODULES[mod]}")
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    dotted = f"{node.module}.{a.name}"
                    if dotted in _PY311_APIS:
                        yield ctx.violation(
                            self.id, node,
                            f"from {node.module} import {a.name}: "
                            f"requires python >= {_PY311_APIS[dotted]}")
            elif isinstance(node, ast.Attribute):
                full = _resolve(node, imports)
                ver = _PY311_APIS.get(full or "")
                # flag only when the chain head is a real module (it was
                # imported here, or is a known stdlib module name) — a
                # local variable named `math` must not trip the rule
                head = (full or "").split(".")[0]
                if ver and (head in imports or head in _STDLIB_HEADS):
                    yield ctx.violation(
                        self.id, node,
                        f"{full}: requires python >= {ver}")
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load):
                if node.id in _PY311_BUILTINS and node.id not in imports:
                    yield ctx.violation(
                        self.id, node,
                        f"{node.id}: builtin requires python >= "
                        f"{_PY311_BUILTINS[node.id]}")
                else:
                    full = imports.get(node.id)
                    ver = _PY311_APIS.get(full or "")
                    if ver:
                        yield ctx.violation(
                            self.id, node,
                            f"{full}: requires python >= {ver}")
            elif isinstance(node, ast.Call):
                callee = _resolve(node.func, imports) or ""
                for kw in node.keywords:
                    funcs = _PY311_KWARGS.get(kw.arg or "")
                    if funcs and callee in funcs:
                        yield ctx.violation(
                            self.id, node,
                            f"{callee}({kw.arg}=...): keyword requires "
                            f"python >= 3.11 (use a preexec_fn shim — "
                            f"kubelet/process_runtime._spawn is the "
                            f"in-tree pattern)")


# `math.cbrt` in a file that (unusually) lacks the `import math` line —
# e.g. the module object was passed in — still deserves a flag when the
# chain head is a known stdlib module name.
_STDLIB_HEADS = {d.split(".")[0] for d in _PY311_APIS}


# ---------------------------------------------------------------------------
# metrics-sync — gates must never point at renamed series
# ---------------------------------------------------------------------------

# file -> restrict-to-function (None = whole file). monitoring.py also
# scrapes kubelet cAdvisor-style stats dicts whose keys look like
# series; only its SLO rule set binds to flightrec series names.
_METRIC_REF_FILES: Dict[str, Optional[str]] = {
    "hack/churn_mp.py": None,
    "hack/perfgate.py": None,
    "kubernetes_tpu/addons/monitoring.py": "default_churn_rules",
}
_METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_depth", "_entries")
_METRIC_BUILTIN_REFS = {"process_resident_bytes",
                        "process_cpu_seconds_total",
                        "tracing_spans_dropped"}
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


@register
class MetricsSyncRule(Rule):
    """Every metric series name the gates reference — the churn
    harness's record scrape (hack/churn_mp.py), the SLO rule set
    (addons/monitoring.py default_churn_rules), the perfgate bands —
    must exist in the util/metrics registry universe.

    Motivating invariant: an instrumentation rename must never silently
    turn a gate into "no data". The SLO watchdog treats a missing
    series as neither-fire-nor-resolve and the scrape defaults absent
    counters to 0 — both by design tolerant at runtime, which is
    exactly why the name binding must be checked statically.
    """

    id = "metrics-sync"
    doc = "scraped/SLO/gated series names exist in the metric registry"

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("kubernetes_tpu/") or rel.startswith("hack/")

    def check_tree(self, ctxs: Sequence[FileContext]) -> Iterable[Violation]:
        universe = self._registry_universe(ctxs)
        if not universe:
            return
        for ctx in ctxs:
            if ctx.rel not in _METRIC_REF_FILES or ctx.tree is None:
                continue
            scope: ast.AST = ctx.tree
            fn_name = _METRIC_REF_FILES[ctx.rel]
            if fn_name is not None:
                scope = next(
                    (n for n in ast.walk(ctx.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and n.name == fn_name), ast.Module(body=[],
                                                        type_ignores=[]))
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)):
                    continue
                name = node.value.strip().rstrip("{")
                if not self._looks_like_series(name):
                    continue
                if name in universe:
                    continue
                yield ctx.violation(
                    self.id, node,
                    f"series {name!r} is scraped/gated here but not "
                    f"registered anywhere in the metric registry — a "
                    f"rename on the instrumentation side would turn "
                    f"this gate into 'no data' silently")

    @staticmethod
    def _looks_like_series(name: str) -> bool:
        if name in _METRIC_BUILTIN_REFS:
            return True
        if not _METRIC_NAME_RE.match(name):
            return False
        # series names are multi-segment AND carry a unit/kind suffix;
        # record keys ('transfer_bytes', 'solve_p50_ms') miss one or both
        return name.count("_") >= 2 and name.endswith(_METRIC_SUFFIXES)

    @staticmethod
    def _registry_universe(ctxs: Sequence[FileContext]) -> Set[str]:
        """Names registered via Registry.counter/gauge/histogram (or the
        metric classes directly) anywhere in the tree, plus histogram
        derived series, counter :rate series, and the flight recorder's
        per-process built-ins."""
        out: Set[str] = set()
        for ctx in ctxs:
            if ctx.tree is None \
                    or not ctx.rel.startswith("kubernetes_tpu/"):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                first = node.args[0]
                if not (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    continue
                kind = None
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr in ("counter", "gauge", "histogram"):
                        kind = node.func.attr
                elif isinstance(node.func, ast.Name):
                    if node.func.id in ("Counter", "Gauge", "Histogram"):
                        kind = node.func.id.lower()
                if kind is None:
                    continue
                name = first.value
                out.add(name)
                if kind == "counter":
                    out.add(name + ":rate")
                if kind == "histogram":
                    out.update((name + "_bucket", name + "_sum",
                                name + "_count", name + "_sum:rate",
                                name + "_count:rate"))
            # flight-recorder built-ins: the (name, type, value) tuples
            # _process_samples appends are registrations in spirit
            if ctx.rel == "kubernetes_tpu/util/metrics.py":
                for node in ast.walk(ctx.tree):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and node.name == "_process_samples":
                        for tup in ast.walk(node):
                            if isinstance(tup, ast.Tuple) \
                                    and len(tup.elts) >= 2 \
                                    and isinstance(tup.elts[0],
                                                   ast.Constant) \
                                    and isinstance(tup.elts[0].value, str):
                                bname = tup.elts[0].value
                                out.add(bname)
                                if isinstance(tup.elts[1], ast.Constant) \
                                        and tup.elts[1].value == "counter":
                                    out.add(bname + ":rate")
        return out


# ---------------------------------------------------------------------------
# unused — pyflakes-equivalent hygiene, tree kept at zero
# ---------------------------------------------------------------------------

@register
class UnusedNamesRule(Rule):
    """Unused imports and unreferenced private module-level names.

    Dead imports are where stale dependencies and copy-paste rot hide;
    the PR 1 incident proved this tree cannot afford import-time
    surprises. Public module-level names are API surface (left alone);
    private (``_``-prefixed) ones with no reference in their own file,
    no cross-module import, and no attribute access anywhere are dead
    code. ``__init__.py`` imports are re-exports and exempt.
    """

    id = "unused"
    doc = "no unused imports or dead private module-level names"

    def applies_to(self, rel: str) -> bool:
        return True

    def check_tree(self, ctxs: Sequence[FileContext]) -> Iterable[Violation]:
        # names referenced cross-module anywhere in the tree: imported
        # by name, or accessed as an attribute (module._private)
        externally_used: Set[str] = set()
        # (module dotted path, name) imported elsewhere: an import that
        # other modules re-import FROM here is a deliberate re-export
        imported_from: Set[Tuple[str, str]] = set()
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ImportFrom):
                    externally_used.update(
                        a.asname or a.name for a in node.names)
                    if node.module and node.level == 0:
                        imported_from.update(
                            (node.module, a.name) for a in node.names)
                elif isinstance(node, ast.Attribute):
                    externally_used.add(node.attr)
        for ctx in ctxs:
            yield from self._check_file(ctx, externally_used,
                                        imported_from)

    @staticmethod
    def _module_of(rel: str) -> str:
        mod = rel[:-3] if rel.endswith(".py") else rel
        if mod.endswith("/__init__"):
            mod = mod[:-len("/__init__")]
        return mod.replace("/", ".")

    def _check_file(self, ctx: FileContext, externally_used: Set[str],
                    imported_from: Set[Tuple[str, str]]) -> Iterable[Violation]:
        if ctx.tree is None:
            return
        loads: Dict[str, int] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads[node.id] = loads.get(node.id, 0) + 1
        strings = [node.value for node in ast.walk(ctx.tree)
                   if isinstance(node, ast.Constant)
                   and isinstance(node.value, str)]

        def referenced(name: str) -> bool:
            if loads.get(name):
                return True
            # string annotations, __all__, doctests
            pat = re.compile(rf"\b{re.escape(name)}\b")
            return any(pat.search(s) for s in strings)

        if not ctx.rel.endswith("__init__.py"):
            yield from self._unused_imports(ctx, referenced, imported_from)
        yield from self._dead_privates(ctx, referenced, loads,
                                       externally_used)

    def _unused_imports(self, ctx, referenced,
                        imported_from) -> Iterable[Violation]:
        this_mod = self._module_of(ctx.rel)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if not referenced(name) \
                            and (this_mod, name) not in imported_from:
                        yield ctx.violation(
                            self.id, node,
                            f"import {a.name!r} is never used (waive "
                            f"with the side effect it exists for, if "
                            f"any)")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    if not referenced(name) \
                            and (this_mod, name) not in imported_from:
                        yield ctx.violation(
                            self.id, node,
                            f"'from {node.module or '.'} import "
                            f"{a.name}' is never used")

    def _dead_privates(self, ctx, referenced, loads,
                       externally_used) -> Iterable[Violation]:
        if ctx.rel.startswith("tests/"):
            return       # pytest discovers helpers reflectively
        body = getattr(ctx.tree, "body", [])
        for node in body:
            name = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                name = node.name
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
            if name is None or not name.startswith("_") \
                    or name.startswith("__"):
                continue
            if referenced(name) or name in externally_used:
                continue
            yield ctx.violation(
                self.id, node,
                f"private module-level name {name!r} is never "
                f"referenced (in this file or by any importer) — dead "
                f"code")
