"""kube-vet engine: file loading, rule registry, waiver resolution.

A rule reports :class:`Violation`\\ s anchored to AST nodes. A violation
is silenced only by an explicit, reason-carrying waiver comment on the
flagged statement (or the line directly above it)::

    self._q = deque()  # ktpu-vet: ok thread-discipline — bounded by BUSY check

Waiver grammar: ``# ktpu-vet: ok <rule>[,<rule>...] — <reason>`` (the
separator may be an em-dash, ``--``, or a spaced ``-``). The reason is
REQUIRED: a bare waiver is itself a violation, and so is a waiver
naming a rule that does not exist — silencing must stay reviewable.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Violation", "Waiver", "FileContext", "Rule", "register",
           "all_rules", "default_paths", "load_context", "run_vet",
           "format_violation"]

_WAIVER_RE = re.compile(
    r"#\s*ktpu-vet:\s*ok\s+(?P<rules>[a-z0-9_.,\- ]*?)"
    r"(?:\s+(?:—|--|-)\s+(?P<reason>.*))?$")


@dataclass
class Violation:
    rule: str
    path: str                  # repo-relative
    line: int
    col: int
    message: str
    span: Tuple[int, int] = (0, 0)   # (first, last) line of the statement
    waived: bool = False
    waiver_reason: str = ""

    def key(self) -> Tuple[str, str, int, str]:
        return (self.rule, self.path, self.line, self.message)


@dataclass
class Waiver:
    rules: Tuple[str, ...]
    reason: str
    line: int
    used: bool = False


@dataclass
class FileContext:
    """One parsed source file plus its waivers, shared by every rule."""

    path: str                  # absolute
    rel: str                   # repo-relative (the reporting name)
    source: str
    lines: List[str]
    tree: Optional[ast.AST]
    syntax_error: Optional[SyntaxError] = None
    waivers: List[Waiver] = field(default_factory=list)
    waiver_errors: List[Violation] = field(default_factory=list)

    def violation(self, rule: str, node, message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        end = getattr(node, "end_lineno", None) or line
        return Violation(rule=rule, path=self.rel, line=line, col=col,
                         message=message, span=(line, end))


class Rule:
    """One named invariant. Subclasses set ``id``/``doc`` and implement
    either per-file ``check`` or whole-tree ``check_tree``."""

    id: str = ""
    doc: str = ""

    def applies_to(self, rel: str) -> bool:
        return not rel.startswith("tests/")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        return ()

    def check_tree(self, ctxs: Sequence[FileContext]) -> Iterable[Violation]:
        for ctx in ctxs:
            if self.applies_to(ctx.rel):
                yield from self.check(ctx)


_RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the registry (id must be unique)."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    return dict(_RULES)


def _comment_tokens(source: str):
    """(line, comment text) for every real COMMENT token — docstrings
    and string literals that merely mention the waiver syntax (this
    engine's own documentation, for one) must not parse as waivers."""
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


def _parse_waivers(ctx: FileContext) -> None:
    for i, line in _comment_tokens(ctx.source):
        if "ktpu-vet" not in line:
            continue
        m = _WAIVER_RE.search(line)
        if m is None:
            ctx.waiver_errors.append(Violation(
                rule="waiver", path=ctx.rel, line=i, col=0,
                message="malformed ktpu-vet comment (expected "
                        "'# ktpu-vet: ok <rule> — <reason>')",
                span=(i, i)))
            continue
        rules = tuple(r for r in re.split(r"[\s,]+", m.group("rules"))
                      if r)
        reason = (m.group("reason") or "").strip()
        if not rules or not reason:
            ctx.waiver_errors.append(Violation(
                rule="waiver", path=ctx.rel, line=i, col=0,
                message="waiver must name a rule AND carry a reason: "
                        "'# ktpu-vet: ok <rule> — <reason>'",
                span=(i, i)))
            continue
        unknown = [r for r in rules if r not in _RULES]
        if unknown:
            ctx.waiver_errors.append(Violation(
                rule="waiver", path=ctx.rel, line=i, col=0,
                message=f"waiver names unknown rule(s) "
                        f"{', '.join(sorted(unknown))} (known: "
                        f"{', '.join(sorted(_RULES))})",
                span=(i, i)))
            continue
        ctx.waivers.append(Waiver(rules=rules, reason=reason, line=i))


def load_context(path: str, root: str) -> FileContext:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    tree = None
    err: Optional[SyntaxError] = None
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        err = e
    ctx = FileContext(path=path, rel=rel, source=source,
                      lines=source.splitlines(), tree=tree,
                      syntax_error=err)
    _parse_waivers(ctx)
    return ctx


_SKIP_DIRS = {"__pycache__", ".git", ".ktpu_cache", "www", "node_modules"}
_DEFAULT_TOPS = ("kubernetes_tpu", "hack", "tests", "examples", "native")
_DEFAULT_FILES = ("bench.py",)


def default_paths(root: str) -> List[str]:
    """Every Python file the vet pass owns (the committed tree minus
    generated/vendored assets)."""
    out: List[str] = []
    for top in _DEFAULT_TOPS:
        base = os.path.join(root, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for fn in _DEFAULT_FILES:
        p = os.path.join(root, fn)
        if os.path.isfile(p):
            out.append(p)
    return out


def _covers(ctx: FileContext, w: Waiver, first: int, last: int) -> bool:
    """A waiver covers a statement when it sits on one of its lines, or
    in the contiguous comment block directly above it (a multi-line
    reason reads naturally; a blank line breaks the attachment)."""
    if first <= w.line <= last:
        return True
    if w.line < first:
        between = ctx.lines[w.line:first - 1]
        return all(s.strip().startswith("#") for s in between)
    return False


def _apply_waivers(ctx: FileContext,
                   violations: List[Violation]) -> List[Violation]:
    for v in violations:
        first, last = v.span if v.span != (0, 0) else (v.line, v.line)
        for w in ctx.waivers:
            if v.rule in w.rules and _covers(ctx, w, first, last):
                v.waived = True
                v.waiver_reason = w.reason
                w.used = True
                break
    return violations


def run_vet(paths: Optional[Sequence[str]] = None,
            rule_ids: Optional[Sequence[str]] = None,
            root: Optional[str] = None,
            ) -> Tuple[List[Violation], List[Violation]]:
    """Run the rule set -> (active violations, waived violations).

    ``paths`` defaults to the whole tree under ``root`` (defaults to the
    repo root containing this package).
    """
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    if paths is None:
        paths = default_paths(root)
    # "waiver" is the engine's own hygiene pseudo-rule, not in _RULES
    rules = [_RULES[r] for r in rule_ids if r in _RULES] if rule_ids \
        else list(_RULES.values())
    ctxs = [load_context(p, root) for p in paths]

    active: List[Violation] = []
    waived: List[Violation] = []
    per_file: Dict[str, List[Violation]] = {c.rel: [] for c in ctxs}
    for rule in rules:
        scoped = [c for c in ctxs if rule.applies_to(c.rel)]
        for v in rule.check_tree(scoped):
            per_file.setdefault(v.path, []).append(v)
    by_rel = {c.rel: c for c in ctxs}
    for rel, vs in per_file.items():
        ctx = by_rel.get(rel)
        if ctx is not None:
            _apply_waivers(ctx, vs)
        for v in vs:
            (waived if v.waived else active).append(v)
    # waiver hygiene is unconditional (a broken waiver can't waive itself)
    if rule_ids is None or "waiver" in rule_ids:
        for ctx in ctxs:
            active.extend(ctx.waiver_errors)
    if rule_ids is None:
        # stale-waiver check only when EVERY rule ran: under a rule
        # subset, a waiver for an unselected rule is legitimately idle
        for ctx in ctxs:
            for w in ctx.waivers:
                if not w.used:
                    active.append(Violation(
                        rule="waiver", path=ctx.rel, line=w.line, col=0,
                        message=f"waiver for {', '.join(w.rules)} "
                                f"matches no violation — the finding "
                                f"was fixed or moved; remove the stale "
                                f"waiver", span=(w.line, w.line)))
    active.sort(key=lambda v: (v.path, v.line, v.rule))
    waived.sort(key=lambda v: (v.path, v.line, v.rule))
    return active, waived


def format_violation(v: Violation) -> str:
    tag = f" (waived: {v.waiver_reason})" if v.waived else ""
    return f"{v.path}:{v.line}:{v.col}: [{v.rule}] {v.message}{tag}"
