"""Probe primitives (ref: pkg/probe/{exec,http,tcp}).

Each prober returns one of SUCCESS / FAILURE / UNKNOWN
(ref: pkg/probe/probe.go Result).
"""

from __future__ import annotations

import socket
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

SUCCESS = "success"
FAILURE = "failure"
UNKNOWN = "unknown"

__all__ = ["SUCCESS", "FAILURE", "UNKNOWN", "probe_http", "probe_tcp",
           "probe_exec"]


def probe_http(host: str, port: int, path: str = "/",
               timeout: float = 1.0) -> Tuple[str, str]:
    """ref: pkg/probe/http/http.go — 2xx/3xx is success."""
    path = path if path.startswith("/") else "/" + path
    url = f"http://{host}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            body = resp.read(4096).decode("utf-8", "replace")
            if 200 <= resp.status < 400:
                return SUCCESS, body
            return FAILURE, body
    except urllib.error.HTTPError as e:
        return FAILURE, str(e)
    except Exception as e:
        return FAILURE, str(e)


def probe_tcp(host: str, port: int, timeout: float = 1.0) -> Tuple[str, str]:
    """ref: pkg/probe/tcp/tcp.go — a successful connect is success."""
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return SUCCESS, ""
    except Exception as e:
        return FAILURE, str(e)


def probe_exec(runtime, container_id: str, cmd: List[str]) -> Tuple[str, str]:
    """ref: pkg/probe/exec/exec.go — exit code 0 is success. ``runtime`` is
    the kubelet's ContainerRuntime seam."""
    try:
        code, output = runtime.exec_in_container(container_id, cmd)
    except Exception as e:
        return UNKNOWN, str(e)
    return (SUCCESS if code == 0 else FAILURE), output
