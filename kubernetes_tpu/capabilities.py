"""Process-wide capability switches.

ref: pkg/capabilities/capabilities.go — a once-initialized global that
gates what the system lets pods ask for. v0 has one switch that
matters: AllowPrivileged (the `--allow_privileged` flag on apiserver and
kubelet); validation rejects `privileged: true` containers unless it is
on (validation.go:612-613), and the kubelet refuses to start them.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class Capabilities:
    allow_privileged: bool = False
    # pod sources allowed to use host networking (reference carries this
    # for static pods; kept for parity of the record type)
    host_network_sources: List[str] = dataclasses.field(default_factory=list)


_lock = threading.Lock()
_capabilities: Optional[Capabilities] = None


def initialize(c: Capabilities) -> None:
    """First call wins; later calls are ignored (capabilities.go Initialize
    — per-binary configuration, not per-request)."""
    global _capabilities
    with _lock:
        if _capabilities is None:
            _capabilities = c


def setup(allow_privileged: bool,
          host_network_sources: Optional[List[str]] = None) -> None:
    """ref: kubelet.go SetupCapabilities — flag-wiring convenience."""
    initialize(Capabilities(allow_privileged=allow_privileged,
                            host_network_sources=host_network_sources or []))


def set_for_tests(c: Optional[Capabilities]) -> None:
    """Tests may re-set freely (capabilities.go SetForTests); None returns
    the process to the never-initialized state."""
    global _capabilities
    with _lock:
        _capabilities = c


def get() -> Capabilities:
    with _lock:
        if _capabilities is None:
            return Capabilities()
        return _capabilities
