"""Node agent (ref: pkg/kubelet/).

The kubelet-equivalent: consumes desired pod state from merged config
sources (file / apiserver watch), reconciles the node's container runtime to
match via per-pod workers, probes container health, and pushes PodStatus
back to the API server.

The container runtime sits behind the ``ContainerRuntime`` seam
(ref: dockertools.DockerInterface); ``FakeRuntime`` is the test double
(ref: FakeDockerClient) and ``ProcessRuntime`` runs pods as real local
process groups with the native pause binary as each pod's sandbox.
"""

from kubernetes_tpu.kubelet.runtime import (
    ContainerRecord,
    ContainerRuntime,
    FakeRuntime,
    INFRA_CONTAINER_NAME,
)
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.config import PodConfig, ApiserverSource, FileSource
from kubernetes_tpu.kubelet.pod_workers import PodWorkers
from kubernetes_tpu.kubelet.status import StatusManager

__all__ = [
    "ContainerRecord", "ContainerRuntime", "FakeRuntime", "ProcessRuntime",
    "INFRA_CONTAINER_NAME", "Kubelet", "PodConfig", "ApiserverSource",
    "FileSource", "PodWorkers", "StatusManager",
]
