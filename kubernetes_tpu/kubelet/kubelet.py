"""Kubelet core (ref: pkg/kubelet/kubelet.go).

``run`` consumes the merged PodConfig channel in a select-style loop with a
resync tick (ref: syncLoop:1779-1808). ``sync_pods`` (ref: SyncPods:1566-1680)
re-admits pods against node capacity/ports (ref: handleNotFittingPods:1750-1772,
reusing the scheduler's predicate functions :1717-1746), dispatches per-pod
workers, kills containers of unwanted pods, and garbage-collects. ``sync_pod``
(ref: syncPod:1375+) drives one pod to its desired state: infra ("pause")
container first (ref: createPodInfraContainer:1025), then per-container
create/restart decisions (ref: computePodContainerChanges:1252), liveness
probes, and a status push.
"""

from __future__ import annotations

import dataclasses
import datetime
import queue
import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu import capabilities
from kubernetes_tpu import probe as probe_pkg
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.kubelet import envvars
from kubernetes_tpu.kubelet.config import ConfigSourceAnnotation, PodConfig
from kubernetes_tpu.kubelet.gc import ContainerGC, GCPolicy
from kubernetes_tpu.kubelet.pod_workers import PodWorkers
from kubernetes_tpu.kubelet.runtime import (
    INFRA_CONTAINER_NAME,
    ContainerRecord,
    ContainerRuntime,
)
from kubernetes_tpu.kubelet.status import StatusManager
from kubernetes_tpu.scheduler import predicates as sched_predicates

__all__ = ["Kubelet"]

ConfigMirrorAnnotation = "kubernetes.io/config.mirror"


def _ts(t: float) -> Optional[datetime.datetime]:
    if not t:
        return None
    return datetime.datetime.fromtimestamp(t, datetime.timezone.utc)


class Kubelet:
    def __init__(self, hostname: str, runtime: ContainerRuntime,
                 client=None, recorder=None,
                 resync_period: float = 2.0,
                 gc_policy: Optional[GCPolicy] = None,
                 volume_mgr=None, service_lister=None,
                 master_service_namespace: str = "default"):
        self.hostname = hostname
        self.runtime = runtime
        self.client = client
        self.recorder = recorder
        self.resync_period = resync_period
        # service discovery env vars (ref: kubelet.go makeEnvironmentVariables
        # + pkg/kubelet/envvars): a callable returning every Service, fed by
        # a reflector cache; None disables injection (pure-fake tests)
        self.service_lister = service_lister
        self.master_service_namespace = master_service_namespace
        self.status_manager = StatusManager(client)
        self.pod_workers = PodWorkers(self.sync_pod)
        self.container_gc = ContainerGC(runtime, gc_policy or GCPolicy())
        # volume plugin manager (ref: kubelet.go volumePluginMgr); None
        # means this kubelet runs without a volumes dir (pure-fake tests)
        self.volume_mgr = volume_mgr
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._desired: Dict[str, api.Pod] = {}   # uid -> pod
        self._probe_failures: Dict[tuple, int] = {}

    # ------------------------------------------------------------------
    # the outer loop (ref: syncLoop:1779-1808)
    # ------------------------------------------------------------------
    def run(self, pod_config: PodConfig) -> "Kubelet":
        def loop():
            pods: List[api.Pod] = []
            while not self._stop.is_set():
                try:
                    update = pod_config.updates.get(timeout=self.resync_period)
                    pods = update.pods
                except queue.Empty:
                    pass  # resync tick re-runs the last snapshot
                try:
                    self.sync_pods(pods)
                except Exception:
                    pass
        threading.Thread(target=loop, daemon=True,
                         name=f"kubelet-{self.hostname}").start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.pod_workers.stop()

    # ------------------------------------------------------------------
    # node-side admission (ref: handleNotFittingPods:1750-1772)
    # ------------------------------------------------------------------
    def _filter_fitting(self, pods: List[api.Pod]) -> List[api.Pod]:
        fitting: List[api.Pod] = []
        node = self._get_node()
        for pod in pods:
            # port conflicts against pods already admitted this pass
            # (ref: checkHostPortConflicts:1717 reusing scheduler predicates)
            if not sched_predicates.pod_fits_ports(pod, fitting, self.hostname):
                self._reject(pod, "HostPortConflict",
                             "Pod cannot be started due to host port conflict")
                continue
            if node is not None and not sched_predicates.pod_matches_node_labels(pod, node):
                self._reject(pod, "NodeSelectorMismatching",
                             "Pod cannot be started due to node selector mismatch")
                continue
            if node is not None and node.spec.capacity:
                _, exceeding = sched_predicates.check_pods_exceeding_capacity(
                    fitting + [pod], node.spec.capacity)
                if pod in exceeding:
                    self._reject(pod, "ExceededCapacity",
                                 "Pod cannot be started due to exceeded capacity")
                    continue
            fitting.append(pod)
        return fitting

    def _get_node(self) -> Optional[api.Node]:
        if self.client is None:
            return None
        try:
            return self.client.nodes().get(self.hostname)
        except Exception:
            return None

    def _reject(self, pod: api.Pod, reason: str, message: str) -> None:
        if self.recorder is not None:
            self.recorder.eventf(pod, reason, message)
        self.status_manager.set_pod_status(pod, api.PodStatus(
            phase=api.PodFailed, host=self.hostname, message=message))

    # ------------------------------------------------------------------
    # SyncPods (ref: kubelet.go:1566-1680)
    # ------------------------------------------------------------------
    def sync_pods(self, pods: List[api.Pod]) -> None:
        fitting = self._filter_fitting(pods)
        desired = {p.metadata.uid or p.metadata.name: p for p in fitting}
        with self._lock:
            self._desired = desired

        for pod in fitting:
            self._ensure_mirror_pod(pod)
            self.pod_workers.update_pod(pod)

        # kill containers of pods no longer desired (ref: :1631-1660)
        for record in self.runtime.list_containers():
            parsed = record.parsed
            if parsed is None:
                continue
            if parsed[3] not in desired:
                try:
                    self.runtime.stop_container(record.id)
                except Exception:
                    pass
        self.pod_workers.forget_non_existing(set(desired))
        self.container_gc.collect(live_uids=set(desired))
        # tear down volumes of departed pods (ref: cleanupOrphanedVolumes
        # :1523-1556)
        if self.volume_mgr is not None:
            try:
                self.volume_mgr.cleanup_orphaned_volumes(list(desired))
            except Exception:
                pass  # crash-only: volume gc must not break the sync loop

    # ------------------------------------------------------------------
    # mirror pods for static (file-source) pods (ref: pod_manager.go,
    # mirror_client.go)
    # ------------------------------------------------------------------
    def _ensure_mirror_pod(self, pod: api.Pod) -> None:
        if self.client is None:
            return
        if pod.metadata.annotations.get(ConfigSourceAnnotation) \
                not in ("file", "http"):
            return  # only static pods get mirrors (ref: pod_manager.go)
        ns = pod.metadata.namespace or api.NamespaceDefault
        try:
            self.client.pods(ns).get(pod.metadata.name)
            return
        except errors.StatusError as e:
            if not errors.is_not_found(e):
                return
        mirror = api.Pod(
            metadata=api.ObjectMeta(
                name=pod.metadata.name, namespace=ns,
                labels=dict(pod.metadata.labels),
                annotations={**pod.metadata.annotations,
                             ConfigMirrorAnnotation: "true"}),
            spec=pod.spec)
        try:
            self.client.pods(ns).create(mirror)
            created = self.client.pods(ns).get(pod.metadata.name)
            if not created.spec.host:
                self.client.pods(ns).bind(api.Binding(
                    metadata=api.ObjectMeta(name=pod.metadata.name, namespace=ns),
                    pod_name=pod.metadata.name, host=self.hostname))
        except errors.StatusError:
            pass

    # ------------------------------------------------------------------
    # syncPod (ref: kubelet.go:1375+)
    # ------------------------------------------------------------------
    def sync_pod(self, pod: api.Pod) -> None:
        uid = pod.metadata.uid or pod.metadata.name
        with self._lock:
            if uid not in self._desired:
                return  # deleted while queued
        records = self._pod_records(uid)

        # 1. the infra ("pause") container holds the sandbox (ref: :1025)
        infra = next((r for r in records
                      if r.parsed and r.parsed[0] == INFRA_CONTAINER_NAME
                      and r.running), None)
        if infra is None:
            cid = self.runtime.create_infra_container(pod)
            self.runtime.start_container(cid)
            infra = self.runtime.inspect_container(cid)
            records = self._pod_records(uid)

        # 1.5 mount external volumes before any container starts
        # (ref: kubelet.go mountExternalVolumes call in syncPod :1440)
        if self.volume_mgr is not None and pod.spec.volumes:
            try:
                self.volume_mgr.mount_volumes(pod)
            except Exception as e:
                self._reject(pod, "FailedMount",
                             f"Unable to mount volumes for pod: {e}")
                return

        # 2. per-container reconcile (ref: computePodContainerChanges:1252)
        for container in pod.spec.containers:
            self._sync_container(pod, container, records)

        # 3. status push
        self.status_manager.set_pod_status(pod, self.generate_pod_status(pod))

    def _pod_records(self, uid: str) -> List[ContainerRecord]:
        out = []
        for r in self.runtime.list_containers(include_dead=True):
            p = r.parsed
            if p and p[3] == uid:
                out.append(r)
        return out

    def _sync_container(self, pod: api.Pod, container: api.Container,
                        records: List[ContainerRecord]) -> None:
        mine = [r for r in records
                if r.parsed and r.parsed[0] == container.name]
        running = [r for r in mine if r.running]
        if running:
            record = running[0]
            if self._liveness_failed(pod, container, record):
                # unhealthy: kill; restart policy decides resurrection below
                self.runtime.stop_container(record.id)
                if self.recorder is not None:
                    self.recorder.eventf(pod, "Unhealthy",
                                         "Liveness probe failed for %s",
                                         container.name)
                running = []
            else:
                return  # healthy and running: nothing to do
        # dead or never started: consult restart policy (ref: :1158)
        attempts = max((r.parsed[4] for r in mine), default=-1)
        if mine and not self._should_restart(pod, mine):
            return
        self._start_container(pod, container, attempt=attempts + 1)

    def _should_restart(self, pod: api.Pod, dead: List[ContainerRecord]) -> bool:
        policy = pod.spec.restart_policy
        if policy == api.RestartPolicyAlways:
            return True
        if policy == api.RestartPolicyOnFailure:
            last = max(dead, key=lambda r: r.finished_at)
            return last.exit_code != 0
        return False

    def _start_container(self, pod: api.Pod, container: api.Container,
                         attempt: int) -> None:
        if container.privileged and not capabilities.get().allow_privileged:
            # ref: kubelet.go:797-802 — belt-and-braces behind validation:
            # the node refuses even if an unvalidated source asked. Checked
            # BEFORE the pull so a forbidden pod doesn't re-pull its image
            # on every resync.
            self._reject(pod, "PrivilegedDisallowed",
                         "container requested privileged mode, "
                         "but it is disallowed globally")
            return
        # pull policy (ref: :1101-1120): PullAlways, or IfNotPresent+missing
        policy = container.image_pull_policy or (
            api.PullAlways if container.image.endswith(":latest")
            else api.PullIfNotPresent)
        present = container.image in self.runtime.list_images()
        if policy == api.PullAlways or (
                policy == api.PullIfNotPresent and not present):
            self.runtime.pull_image(container.image)
        elif policy == api.PullNever and not present:
            self._reject(pod, "ErrImageNeverPull",
                         f"image {container.image} not present with PullNever")
            return
        container = self._with_service_env(pod, container)
        cid = self.runtime.create_container(pod, container, attempt)
        self.runtime.start_container(cid)
        if self.recorder is not None:
            self.recorder.eventf(pod, "Started", "Started container %s",
                                 container.name)

    def _with_service_env(self, pod: api.Pod,
                          container: api.Container) -> api.Container:
        """Prepend service-discovery env vars (ref: kubelet.go:896-920
        makeEnvironmentVariables) — the container's own declared env wins
        on name collision, which the runtimes guarantee by applying env
        in order (later entries overwrite)."""
        if self.service_lister is None:
            return container
        try:
            all_svcs = self.service_lister()
        except Exception:
            return container  # discovery must never block a pod start
        visible = envvars.visible_services(
            all_svcs, pod.metadata.namespace or "default",
            master_ns=self.master_service_namespace)
        svc_env = envvars.from_services(visible)
        if not svc_env:
            return container
        return dataclasses.replace(
            container, env=svc_env + list(container.env))

    # ------------------------------------------------------------------
    # probes (ref: probe.go + pkg/probe/)
    # ------------------------------------------------------------------
    def _run_probe(self, p: api.Probe, pod: api.Pod,
                   record: ContainerRecord, pod_ip: str) -> str:
        if p.exec is not None:
            result, _ = probe_pkg.probe_exec(self.runtime, record.id,
                                             p.exec.command)
        elif p.http_get is not None:
            result, _ = probe_pkg.probe_http(
                p.http_get.host or pod_ip or "127.0.0.1", p.http_get.port,
                p.http_get.path, timeout=p.timeout_seconds)
        elif p.tcp_socket is not None:
            result, _ = probe_pkg.probe_tcp(pod_ip or "127.0.0.1",
                                            p.tcp_socket.port,
                                            timeout=p.timeout_seconds)
        else:
            result = probe_pkg.SUCCESS
        return result

    def _liveness_failed(self, pod: api.Pod, container: api.Container,
                         record: ContainerRecord) -> bool:
        p = container.liveness_probe
        if p is None:
            return False
        if time.time() - record.started_at < p.initial_delay_seconds:
            return False
        result = self._run_probe(p, pod, record, self._pod_ip(pod))
        return result == probe_pkg.FAILURE

    def _readiness(self, pod: api.Pod, container: api.Container,
                   record: ContainerRecord) -> bool:
        p = container.readiness_probe
        if p is None:
            return True
        if time.time() - record.started_at < p.initial_delay_seconds:
            return False
        return self._run_probe(p, pod, record, self._pod_ip(pod)) == probe_pkg.SUCCESS

    def _pod_ip(self, pod: api.Pod) -> str:
        uid = pod.metadata.uid or pod.metadata.name
        for r in self._pod_records(uid):
            if r.parsed and r.parsed[0] == INFRA_CONTAINER_NAME and r.running:
                return r.ip
        return ""

    # ------------------------------------------------------------------
    # status generation (ref: GeneratePodStatus + getPodStatus :1300-1370)
    # ------------------------------------------------------------------
    def generate_pod_status(self, pod: api.Pod) -> api.PodStatus:
        uid = pod.metadata.uid or pod.metadata.name
        records = self._pod_records(uid)
        statuses: List[api.ContainerStatus] = []
        all_ready = True
        n_running = n_succeeded = n_failed = 0
        for container in pod.spec.containers:
            mine = sorted((r for r in records
                           if r.parsed and r.parsed[0] == container.name),
                          key=lambda r: r.parsed[4])
            cs = api.ContainerStatus(name=container.name, image=container.image,
                                     restart_count=max(len(mine) - 1, 0))
            if not mine:
                cs.state.waiting = api.ContainerStateWaiting(reason="ContainerCreating")
                all_ready = False
            else:
                latest = mine[-1]
                cs.container_id = latest.id
                if latest.running:
                    cs.state.running = api.ContainerStateRunning(
                        started_at=_ts(latest.started_at))
                    cs.ready = self._readiness(pod, container, latest)
                    all_ready = all_ready and cs.ready
                    n_running += 1
                else:
                    cs.state.termination = api.ContainerStateTerminated(
                        exit_code=latest.exit_code,
                        started_at=_ts(latest.started_at),
                        finished_at=_ts(latest.finished_at))
                    all_ready = False
                    if latest.exit_code == 0:
                        n_succeeded += 1
                    else:
                        n_failed += 1
                if len(mine) > 1:
                    prev = mine[-2]
                    cs.last_termination_state.termination = \
                        api.ContainerStateTerminated(
                            exit_code=prev.exit_code,
                            started_at=_ts(prev.started_at),
                            finished_at=_ts(prev.finished_at))
            statuses.append(cs)

        total = len(pod.spec.containers)
        # phase (ref: getPhase :1310-1360)
        if total == 0 or n_running == total:
            phase = api.PodRunning
        elif n_succeeded == total and \
                pod.spec.restart_policy == api.RestartPolicyNever:
            phase = api.PodSucceeded
        elif n_failed + n_succeeded == total and \
                pod.spec.restart_policy == api.RestartPolicyNever:
            phase = api.PodFailed
        elif n_running + n_succeeded + n_failed == 0:
            phase = api.PodPending
        else:
            phase = api.PodRunning if n_running else api.PodPending

        conditions = []
        if phase == api.PodRunning and all_ready:
            conditions.append(api.PodCondition(type=api.PodReady,
                                               status=api.ConditionTrue))
        else:
            conditions.append(api.PodCondition(type=api.PodReady,
                                               status=api.ConditionFalse))
        return api.PodStatus(
            phase=phase, conditions=conditions, host=self.hostname,
            pod_ip=self._pod_ip(pod), container_statuses=statuses)
