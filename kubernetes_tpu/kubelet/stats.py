"""Node/container stats provider — the cAdvisor seam
(ref: pkg/kubelet/cadvisor/: cadvisor_linux.go real client,
cadvisor_fake.go/cadvisor_mock.go doubles).

The kubelet and its HTTP server consume ``StatsProvider``:
- ``machine_info()``      -> MachineInfo        (ref: /spec/ endpoint)
- ``node_stats()``        -> ContainerStats     (root cgroup equivalent)
- ``container_stats(uid, container)`` -> ContainerStats

``ProcStatsProvider`` reads /proc — a real, dependency-free implementation
standing where cAdvisor's daemon would be. ``FakeStatsProvider`` is the
scriptable double for tests.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["MachineInfo", "ContainerStats", "StatsProvider",
           "ProcStatsProvider", "ProcessRuntimeStatsProvider",
           "FakeStatsProvider"]


@dataclass
class MachineInfo:
    """ref: cadvisor api MachineInfo (NumCores/MemoryCapacity)."""

    num_cores: int = 0
    memory_capacity_bytes: int = 0
    machine_id: str = ""

    def as_dict(self) -> dict:
        return {"num_cores": self.num_cores,
                "memory_capacity": self.memory_capacity_bytes,
                "machine_id": self.machine_id}


@dataclass
class ContainerStats:
    """ref: cadvisor ContainerStats subset the kubelet serves."""

    timestamp: float = 0.0
    cpu_usage_core_seconds: float = 0.0
    memory_usage_bytes: int = 0

    def as_dict(self) -> dict:
        return {"timestamp": self.timestamp,
                "cpu": {"usage_core_seconds": self.cpu_usage_core_seconds},
                "memory": {"usage_bytes": self.memory_usage_bytes}}


class StatsProvider:
    def machine_info(self) -> MachineInfo:
        raise NotImplementedError

    def node_stats(self) -> ContainerStats:
        raise NotImplementedError

    def container_stats(self, pod_uid: str,
                        container_name: str) -> Optional[ContainerStats]:
        raise NotImplementedError


class ProcStatsProvider(StatsProvider):
    """Reads /proc directly — the whole-node numbers cAdvisor would give
    (per-container cgroup accounting needs a real container runtime, which
    the FakeRuntime doesn't have; container_stats returns the node numbers
    scaled to zero the way cadvisor_fake does for unknown containers)."""

    def machine_info(self) -> MachineInfo:
        mem = 0
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        mem = int(line.split()[1]) * 1024
                        break
        except OSError:
            pass
        return MachineInfo(num_cores=os.cpu_count() or 1,
                           memory_capacity_bytes=mem)

    def node_stats(self) -> ContainerStats:
        cpu_seconds = 0.0
        try:
            with open("/proc/stat") as f:
                first = f.readline().split()
            # user+nice+system in USER_HZ (typically 100)
            cpu_seconds = sum(int(x) for x in first[1:4]) / 100.0
        except (OSError, ValueError):
            pass
        mem_used = 0
        try:
            with open("/proc/meminfo") as f:
                info = {}
                for line in f:
                    parts = line.split()
                    info[parts[0].rstrip(":")] = int(parts[1]) * 1024
            mem_used = info.get("MemTotal", 0) - info.get("MemAvailable", 0)
        except (OSError, ValueError, IndexError):
            pass
        return ContainerStats(timestamp=time.time(),
                              cpu_usage_core_seconds=cpu_seconds,
                              memory_usage_bytes=mem_used)

    def container_stats(self, pod_uid, container_name):
        return ContainerStats(timestamp=time.time())


class ProcessRuntimeStatsProvider(ProcStatsProvider):
    """Per-container accounting for the real ProcessRuntime (ref:
    pkg/kubelet/cadvisor + dockertools container stats): the runtime's
    locked ``group_stats`` sums utime+stime and VmRSS over the container's
    whole process group — forked children included — and reports None for
    dead groups so /stats 404s instead of serving zeros. Node-level
    numbers come from ProcStatsProvider."""

    def __init__(self, runtime):
        self.runtime = runtime

    def container_stats(self, pod_uid, container_name):
        for rec in self.runtime.containers_for_pod(pod_uid):
            if rec.parsed and rec.parsed[0] == container_name:
                gs = self.runtime.group_stats(rec.id)
                if gs is None:
                    return None
                cpu, rss = gs
                return ContainerStats(timestamp=time.time(),
                                      cpu_usage_core_seconds=cpu,
                                      memory_usage_bytes=rss)
        return None


class FakeStatsProvider(StatsProvider):
    """Scriptable double (ref: cadvisor_fake.go)."""

    def __init__(self):
        self.machine = MachineInfo(num_cores=4,
                                   memory_capacity_bytes=8 << 30,
                                   machine_id="fake")
        self.node = ContainerStats(timestamp=1.0,
                                   cpu_usage_core_seconds=10.0,
                                   memory_usage_bytes=1 << 30)
        self.containers: Dict[tuple, ContainerStats] = {}

    def machine_info(self) -> MachineInfo:
        return self.machine

    def node_stats(self) -> ContainerStats:
        return self.node

    def container_stats(self, pod_uid, container_name):
        return self.containers.get((pod_uid, container_name))
