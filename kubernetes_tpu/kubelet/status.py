"""Status manager (ref: pkg/kubelet/status_manager.go).

Deduplicates and pushes PodStatus to the API server: SetPodStatus records
the computed status and syncs it only when it differs from the last pushed
version, so a steady-state node generates no API writes.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from kubernetes_tpu.api import types as api

__all__ = ["StatusManager"]


def _status_equal(a: api.PodStatus, b: api.PodStatus) -> bool:
    return a == b  # dataclass equality covers nested container statuses


class StatusManager:
    def __init__(self, client):
        self.client = client
        self._lock = threading.Lock()
        self._statuses: Dict[str, api.PodStatus] = {}  # pod key -> last pushed

    def set_pod_status(self, pod: api.Pod, status: api.PodStatus) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            old = self._statuses.get(key)
            if old is not None and _status_equal(old, status):
                return
            self._statuses[key] = status
        if self.client is None:
            return
        try:
            fresh = api.Pod(metadata=pod.metadata, spec=pod.spec, status=status)
            self.client.pods(pod.metadata.namespace).update_status(fresh)
        except Exception:
            # drop the cache entry so the next sync retries the push
            with self._lock:
                self._statuses.pop(key, None)

    def get_pod_status(self, pod: api.Pod) -> Optional[api.PodStatus]:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            return self._statuses.get(key)

    def delete_pod_status(self, pod_key: str) -> None:
        with self._lock:
            self._statuses.pop(pod_key, None)
