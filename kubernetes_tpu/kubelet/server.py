"""Kubelet read-only HTTP server (ref: pkg/kubelet/server.go:118-134).

Endpoints (parity with server.go InstallDefaultHandlers/InstallDebuggingHandlers):
  GET  /healthz                                  -> "ok"
  GET  /pods                                     -> bound pods + statuses
  GET  /podInfo?podID=&podNamespace=             -> one pod's status
  GET  /spec/                                    -> machine info (cadvisor seam)
  GET  /stats/                                   -> node stats
  GET  /stats/<ns>/<pod>/<uid>/<container>       -> container stats
  GET  /logs/...                                 -> files under the log dir
  GET  /containerLogs/<ns>/<pod>/<container>     -> container output (?tail=N)
  GET/POST /run/<ns>/<pod>/<container>?cmd=      -> exec, returns output
  GET  /exec/<ns>/<pod>/<container>?command=     -> exec (same transport)
  POST /portForward/<ns>/<pod>?port=N            -> raw byte tunnel after a
       101 upgrade — the httpstream/spdy equivalent (ref:
       pkg/util/httpstream/spdy/upgrade.go) without the SPDY framing
  GET  /metrics                                  -> Prometheus text
"""

from __future__ import annotations

import json
import os
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.latest import scheme as default_scheme
from kubernetes_tpu.kubelet.stats import ProcStatsProvider, StatsProvider
from kubernetes_tpu.runtime.serialize import to_wire
from kubernetes_tpu.util.stream import relay_bidirectional
from kubernetes_tpu.util import metrics as metricspkg

__all__ = ["KubeletServer"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    server_version = "kubelet-tpu"

    def log_message(self, fmt, *args):
        pass

    # -- helpers -----------------------------------------------------------
    @property
    def ks(self) -> "KubeletServer":
        return self.server.kubelet_server  # type: ignore[attr-defined]

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, indent=2).encode())

    def _send_text(self, code: int, text: str) -> None:
        self._send(code, text.encode(), "text/plain; charset=utf-8")

    def _drain(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    # -- dispatch ----------------------------------------------------------
    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def _route(self, method: str) -> None:
        self._drain()
        parsed = urllib.parse.urlsplit(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        # parse once; handlers get the single-value view, _handle_run the
        # multi-value one (repeated cmd= params are argv entries)
        self._multi_query = urllib.parse.parse_qs(parsed.query)
        query = {k: v[-1] for k, v in self._multi_query.items()}
        try:
            self._dispatch(method, parts, query)
        except BrokenPipeError:
            pass
        except Exception as e:
            try:
                self._send_text(500, f"Internal Error: {e}\n")
            except OSError:
                pass

    def _dispatch(self, method: str, parts, query) -> None:
        ks = self.ks
        head = parts[0] if parts else ""
        if head == "healthz":
            return self._send_text(200, "ok")
        if head == "pods":
            return self._handle_pods()
        if head == "podInfo":
            return self._handle_pod_info(query)
        if head == "spec":
            return self._send_json(200, ks.stats.machine_info().as_dict())
        if head == "stats":
            return self._handle_stats(parts[1:])
        if head == "logs":
            return self._handle_logs(parts[1:])
        if head == "containerLogs":
            return self._handle_container_logs(parts[1:], query)
        if head in ("run", "exec"):
            return self._handle_run(parts[1:], query)
        if head == "portForward":
            return self._handle_port_forward(parts[1:], query)
        if head == "metrics":
            body = ks.metrics.render_text()
            if ks.metrics is not metricspkg.default_registry():
                # default-registry merge (the apiserver's pattern):
                # process-wide families — the async event recorder's
                # posted/dropped counters above all — must ride the
                # kubelet's scrape too, or its event shedding would be
                # invisible exactly where events originate
                body += metricspkg.default_registry().render_text()
            return self._send(200, body.encode(),
                              "text/plain; version=0.0.4")
        if head == "debug" and len(parts) >= 2 and parts[1] == "pprof":
            # ref: every reference binary mounts pprof (master.go:431-435)
            from kubernetes_tpu.util import pprof
            body = pprof.handle(parts[2] if len(parts) > 2 else "",
                                query.get("seconds", ""))
            if body is not None:
                return self._send_text(200, body)
        self._send_text(404, f"unknown path /{'/'.join(parts)}\n")

    # -- endpoints ---------------------------------------------------------
    def _handle_pods(self) -> None:
        ks = self.ks
        pods = ks.kubelet_pods()
        wire = ks.scheme.encode_to_wire(api.PodList(items=pods))
        self._send(200, json.dumps(wire).encode())

    def _handle_pod_info(self, query) -> None:
        name = query.get("podID", "")
        ns = query.get("podNamespace", "")
        if not name or not ns:
            return self._send_text(400, "Missing 'podID' or 'podNamespace' "
                                        "query entry.\n")
        pod = self.ks.find_pod(ns, name)
        if pod is None:
            return self._send_text(404, f"pod {ns}/{name} not found\n")
        # PodStatus is not a top-level registered kind; serialize it raw
        wire = to_wire(pod.status)
        self._send(200, json.dumps(wire).encode())

    def _handle_stats(self, rest) -> None:
        ks = self.ks
        if not rest:
            return self._send_json(200, ks.stats.node_stats().as_dict())
        # /stats/<ns>/<pod>/<uid>/<container> or /stats/<ns>/<pod>/<container>
        if len(rest) == 4:
            ns, pod_name, uid, container = rest
        elif len(rest) == 3:
            ns, pod_name, container = rest
            pod = ks.find_pod(ns, pod_name)
            uid = pod.metadata.uid if pod else ""
        else:
            return self._send_text(400, "stats needs "
                                        "/stats/<ns>/<pod>/[<uid>/]<container>\n")
        st = ks.stats.container_stats(uid, container)
        if st is None:
            return self._send_text(404, "no stats for container\n")
        self._send_json(200, st.as_dict())

    def _handle_logs(self, rest) -> None:
        ks = self.ks
        if ks.log_dir is None:
            return self._send_text(404, "log serving disabled\n")
        root = os.path.realpath(ks.log_dir)
        target = os.path.realpath(os.path.join(ks.log_dir, *rest))
        # prefix check must be directory-aware: /var/log/kubelet-private
        # shares a raw string prefix with /var/log/kubelet
        if target != root and not target.startswith(root + os.sep):
            return self._send_text(403, "path escapes the log dir\n")
        if os.path.isdir(target):
            return self._send_text(
                200, "".join(f"{n}\n" for n in sorted(os.listdir(target))))
        if not os.path.exists(target):
            return self._send_text(404, "no such log\n")
        with open(target, "rb") as f:
            self._send(200, f.read(), "text/plain; charset=utf-8")

    def _resolve_container(self, rest):
        """(ns, pod, container) path -> (pod, container record) or None."""
        if len(rest) != 3:
            return None, None
        ns, pod_name, container = rest
        ks = self.ks
        pod = ks.find_pod(ns, pod_name)
        if pod is None:
            return None, None
        rec = ks.container_record(pod, container)
        return pod, rec

    def _handle_container_logs(self, rest, query) -> None:
        pod, rec = self._resolve_container(rest)
        if pod is None:
            return self._send_text(404, "pod not found\n")
        if rec is None:
            return self._send_text(404, "container not found\n")
        tail = int(query.get("tail") or 0)
        text = self.ks.runtime.container_logs(rec.id, tail=tail)
        self._send_text(200, text)

    def _handle_run(self, rest, query) -> None:
        pod, rec = self._resolve_container(rest)
        if pod is None or rec is None:
            return self._send_text(404, "container not found\n")
        # repeated cmd= params are argv entries (ref: server.go handleRun);
        # a single spaced value is whitespace-split as a convenience
        multi = self._multi_query
        cmd = multi.get("cmd") or multi.get("command") or []
        if len(cmd) == 1 and " " in cmd[0]:
            cmd = cmd[0].split()
        if not cmd:
            return self._send_text(400, "missing cmd\n")
        from kubernetes_tpu.util import websocket as ws
        if ws.wants_websocket(self.headers):
            # streaming exec (the stream-upgrade seam the reference fills
            # with SPDY, ref: pkg/util/httpstream/spdy/upgrade.go): output
            # chunks as binary frames, exit code in the final text frame
            self._ws_handshake(ws)
            exit_code = 0
            try:
                for item in self.ks.runtime.exec_stream_in_container(
                        rec.id, cmd):
                    if isinstance(item, int):
                        exit_code = item
                    elif item:
                        ws.send_binary(self.wfile, item)
                ws.send_text(self.wfile,
                             json.dumps({"exitCode": exit_code}).encode())
                ws.send_close(self.wfile)
            except Exception:
                # after the 101 upgrade an HTTP error response would be
                # garbage inside the websocket stream; just drop the
                # connection (ref: SPDY upgrade has the same property)
                pass
            self.close_connection = True
            return
        code, output = self.ks.runtime.exec_in_container(rec.id, cmd)
        self._send_text(200 if code == 0 else 500, output)

    def _ws_handshake(self, ws) -> None:
        self.send_response_only(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", ws.accept_key(
            self.headers.get("Sec-WebSocket-Key", "")))
        self.end_headers()
        self.wfile.flush()

    def _handle_port_forward(self, rest, query) -> None:
        """Raw byte tunnel: 101 upgrade, then relay the HTTP connection to
        the pod's port (the stream-upgrade seam the reference fills with
        SPDY, ref: server.go handlePortForward + httpstream/spdy)."""
        if len(rest) < 2:
            return self._send_text(400, "portForward needs /<ns>/<pod>\n")
        ns, pod_name = rest[0], rest[1]
        port = int(query.get("port") or 0)
        if not port:
            return self._send_text(400, "missing port\n")
        pod = self.ks.find_pod(ns, pod_name)
        if pod is None:
            return self._send_text(404, "pod not found\n")
        try:
            backend = self.ks.port_forward_dial(pod, port)
        except OSError as e:
            return self._send_text(502, f"dial failed: {e}\n")
        from kubernetes_tpu.util import websocket as ws
        if ws.wants_websocket(self.headers):
            # WebSocket port-forward: client binary frames -> backend,
            # backend bytes -> binary frames (the reference's SPDY stream
            # pair, per RFC 6455 instead)
            self._ws_handshake(ws)
            wlock = threading.Lock()

            def pump_client():
                try:
                    while True:
                        frame = ws.read_frame(self.rfile)
                        if frame is None or frame[0] == ws.OP_CLOSE:
                            # None = EOF or an over-MAX_FRAME length: the
                            # tunnel closes cleanly either way (fragment
                            # large messages; CONT frames relay fine)
                            break
                        if frame[0] == ws.OP_PING:
                            with wlock:
                                ws.send_pong(self.wfile, frame[1])
                        elif frame[0] in (ws.OP_BIN, ws.OP_TEXT,
                                          ws.OP_CONT) and frame[1]:
                            backend.sendall(frame[1])
                except OSError:
                    pass
                try:
                    backend.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

            t = threading.Thread(target=pump_client, daemon=True,
                                 name="ws-portforward")
            t.start()
            # same idle bound the raw-relay path enforces: a silently
            # vanished client must not pin this thread forever
            backend.settimeout(30.0)
            try:
                while True:
                    data = backend.recv(65536)
                    if not data:
                        break
                    with wlock:
                        ws.send_binary(self.wfile, data)
                with wlock:
                    ws.send_close(self.wfile)
            except (BrokenPipeError, ConnectionResetError, OSError,
                    socket.timeout):
                pass
            finally:
                backend.close()
                self.close_connection = True
            return
        self.send_response(101, "Switching Protocols")
        self.send_header("Upgrade", "tcp")
        self.send_header("Connection", "Upgrade")
        self.end_headers()
        self.wfile.flush()
        try:
            relay_bidirectional(self.connection, backend, idle_timeout=30.0)
        finally:
            backend.close()
            self.close_connection = True


class KubeletServer:
    """Wires the handler to a kubelet instance (ref: server.go ListenAndServe
    + the HostInterface seam it serves from)."""

    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0,
                 stats: Optional[StatsProvider] = None,
                 log_dir: Optional[str] = None,
                 scheme=None,
                 port_forward_dial: Optional[Callable] = None,
                 metrics: Optional[metricspkg.Registry] = None):
        self.kubelet = kubelet
        self.stats = stats or ProcStatsProvider()
        self.log_dir = log_dir
        self.scheme = scheme or default_scheme
        self.metrics = metrics or metricspkg.Registry()
        self._dial = port_forward_dial
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.kubelet_server = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- HostInterface (ref: server.go HostInterface) ----------------------
    @property
    def runtime(self):
        return self.kubelet.runtime

    def kubelet_pods(self):
        """Bound pods with their current generated status."""
        pods = []
        with self.kubelet._lock:
            desired = list(self.kubelet._desired.values())
        for pod in desired:
            p = self.scheme.deep_copy(pod)
            try:
                p.status = self.kubelet.generate_pod_status(pod)
            except Exception:
                pass
            pods.append(p)
        return pods

    def find_pod(self, namespace: str, name: str) -> Optional[api.Pod]:
        with self.kubelet._lock:
            match = next(
                (p for p in self.kubelet._desired.values()
                 if p.metadata.namespace == namespace
                 and p.metadata.name == name), None)
        if match is None:
            return None
        # copy + status only the one pod — kubelet_pods() would regenerate
        # every pod's status per request
        pod = self.scheme.deep_copy(match)
        try:
            pod.status = self.kubelet.generate_pod_status(match)
        except Exception:
            pass
        return pod

    def container_record(self, pod: api.Pod, container_name: str):
        uid = pod.metadata.uid or pod.metadata.name
        records = [r for r in self.runtime.list_containers(include_dead=True)
                   if r.parsed and r.parsed[3] == uid
                   and r.parsed[0] == container_name]
        running = [r for r in records if r.running]
        pick = running or records
        return pick[-1] if pick else None

    def port_forward_dial(self, pod: api.Pod, port: int) -> socket.socket:
        if self._dial is not None:
            return self._dial(pod, port)
        ip = pod.status.pod_ip or "127.0.0.1"
        return socket.create_connection((ip, port), timeout=5)

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "KubeletServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="kubelet-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
