"""Pod config sources + merger (ref: pkg/kubelet/config/).

Three sources in the reference — file (file.go:41), URL (http.go:41), and
apiserver watch (apiserver.go:29) — merged by ``PodConfig``/Mux with
per-source tracking (config.go:53-63). Here: ``FileSource`` (a directory of
JSON manifests, doubling as the URL source's decode path), and
``ApiserverSource`` (list+watch of pods bound to this node). Each source
reports its complete snapshot; the mux merges the per-source snapshots and
emits one SET update (the kubelet is level-triggered, so SET is the only op
it needs; the reference's ADD/UPDATE/REMOVE ops are a delta encoding of the
same stream).
"""

from __future__ import annotations

import json
import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.latest import scheme as default_scheme
from kubernetes_tpu.client.cache import Reflector, Store
from kubernetes_tpu.controllers.util import run_periodic

__all__ = ["PodUpdate", "PodConfig", "FileSource", "HTTPSource", "ApiserverSource",
           "ConfigSourceAnnotation"]

SET = "SET"
ConfigSourceAnnotation = "kubernetes.io/config.source"


@dataclass
class PodUpdate:
    """ref: config.PodUpdate (pkg/kubelet/types.go)."""

    op: str = SET
    pods: List[api.Pod] = field(default_factory=list)
    source: str = ""


class PodConfig:
    """Merges per-source snapshots into one update channel
    (ref: config.PodConfig + Mux, config.go:53-63)."""

    def __init__(self):
        # bounded + coalescing: every queued PodUpdate is a FULL merged
        # snapshot, so a slow kubelet sync loop drops superseded old
        # entries (latest wins) instead of buffering unbounded history
        self.updates: "queue.Queue[PodUpdate]" = queue.Queue(maxsize=64)
        self._lock = threading.Lock()
        self._per_source: Dict[str, List[api.Pod]] = {}

    def merge(self, source: str, pods: List[api.Pod]) -> None:
        with self._lock:
            stamped = []
            for p in pods:
                p.metadata.annotations.setdefault(ConfigSourceAnnotation, source)
                stamped.append(p)
            self._per_source[source] = stamped
            merged: Dict[str, api.Pod] = {}
            for src in sorted(self._per_source):
                for p in self._per_source[src]:
                    merged[p.metadata.uid or p.metadata.name] = p
            update = PodUpdate(op=SET, pods=list(merged.values()),
                               source=source)
            # never block here: a blocking put while holding _lock would
            # wedge every other source (and seen_sources) behind a
            # stalled consumer, and a source's stop() could not
            # interrupt it. Older snapshots are strictly superseded by
            # this one, so dropping the oldest is lossless.
            while True:
                try:
                    self.updates.put_nowait(update)
                    break
                except queue.Full:
                    try:
                        self.updates.get_nowait()
                    except queue.Empty:
                        pass

    def seen_sources(self) -> List[str]:
        with self._lock:
            return sorted(self._per_source)


def _apply_static_pod_defaults(pod: api.Pod, source: str,
                               hostname: str) -> api.Pod:
    """Static pod normalization shared by the file and URL sources: default
    namespace, ``-<hostname>`` name suffix, deterministic uid, pinned host,
    source annotation (ref: config/file.go + http.go applyDefaults)."""
    if not pod.metadata.namespace:
        pod.metadata.namespace = api.NamespaceDefault
    if not pod.metadata.name.endswith("-" + hostname):
        pod.metadata.name = f"{pod.metadata.name}-{hostname}"
    if not pod.metadata.uid:
        pod.metadata.uid = f"{source}-{pod.metadata.namespace}-{pod.metadata.name}"
    pod.spec.host = hostname
    pod.metadata.annotations[ConfigSourceAnnotation] = source
    return pod


class FileSource:
    """Static pods from a directory of JSON manifests (ref: config/file.go:41).

    Static pod names get a ``-<hostname>`` suffix and a deterministic uid so
    mirror pods are stable across kubelet restarts (ref: file.go applyDefaults).
    """

    def __init__(self, config: PodConfig, path: str, hostname: str,
                 period: float = 5.0, scheme=None):
        self.config = config
        self.path = path
        self.hostname = hostname
        self.period = period
        self.scheme = scheme or default_scheme
        self._stop = threading.Event()

    def read_once(self) -> List[api.Pod]:
        pods = []
        if not os.path.isdir(self.path):
            return pods
        for fname in sorted(os.listdir(self.path)):
            if not fname.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.path, fname)) as f:
                    obj = self.scheme.decode(f.read())
            except Exception:
                continue  # a bad manifest must not poison the others
            if not isinstance(obj, api.Pod):
                continue
            pods.append(_apply_static_pod_defaults(obj, "file", self.hostname))
        return pods

    def sync(self) -> None:
        self.config.merge("file", self.read_once())

    def run(self) -> "FileSource":
        run_periodic(self.sync, self.period, "file-source", self._stop)
        return self

    def stop(self) -> None:
        self._stop.set()


class HTTPSource:
    """Static pods from a manifest URL (ref: config/http.go:41): GET the
    URL each period; the body is one Pod or a PodList manifest."""

    def __init__(self, config: PodConfig, url: str, hostname: str,
                 period: float = 5.0, scheme=None):
        self.config = config
        self.url = url
        self.hostname = hostname
        self.period = period
        self.scheme = scheme or default_scheme
        self._stop = threading.Event()

    def read_once(self) -> Optional[List[api.Pod]]:
        """None on fetch/decode failure (keep last state); [] is a
        legitimately empty manifest (tear static pods down)."""
        import urllib.request
        try:
            with urllib.request.urlopen(self.url, timeout=10) as r:
                obj = self.scheme.decode(r.read())
        except Exception:
            return None
        if isinstance(obj, api.PodList):
            pods = list(obj.items)
        elif isinstance(obj, api.Pod):
            pods = [obj]
        else:
            # decoded but wrong kind (misconfigured URL serving some other
            # object): an error, not an empty manifest — keep last state
            # (ref: config/http.go rejects unknown types)
            return None
        return [_apply_static_pod_defaults(p, "http", self.hostname)
                for p in pods if isinstance(p, api.Pod)]

    def sync(self) -> None:
        pods = self.read_once()
        if pods is not None:
            self.config.merge("http", pods)

    def run(self) -> "HTTPSource":
        run_periodic(self.sync, self.period, "http-source", self._stop)
        return self

    def stop(self) -> None:
        self._stop.set()


class _NotifyStore(Store):
    """A cache.Store that re-merges into the PodConfig on every mutation —
    this is how the apiserver watch becomes a snapshot source."""

    def __init__(self, on_change):
        super().__init__()
        self._on_change = on_change

    def _notify(self):
        self._on_change(self.list())

    def add(self, obj):
        super().add(obj)
        self._notify()

    def update(self, obj):
        super().update(obj)
        self._notify()

    def delete(self, obj):
        super().delete(obj)
        self._notify()

    def replace(self, objs):
        super().replace(objs)
        self._notify()


class ApiserverSource:
    """Pods bound to this node, via list+watch (ref: config/apiserver.go:29 —
    NewSourceApiserver uses a Reflector on field selector spec.host=<node>)."""

    def __init__(self, config: PodConfig, client, hostname: str):
        self.config = config
        self.client = client
        self.hostname = hostname
        store = _NotifyStore(lambda pods: self.config.merge("api", pods))
        self._reflector = Reflector(
            client.pods(api.NamespaceAll).list_watch(
                field_selector=f"spec.host={hostname}"),
            store, name=f"apiserver-source-{hostname}")

    def run(self) -> "ApiserverSource":
        self._reflector.run()
        return self

    def stop(self) -> None:
        self._reflector.stop()
