"""Service discovery environment variables.

ref: pkg/kubelet/envvars/envvars.go FromServices — every container is
started with `{SVC}_SERVICE_HOST` / `{SVC}_SERVICE_PORT` for each
service visible to its pod, plus the docker-links-compatible
`{SVC}_PORT*` family, so applications written against either convention
find their backends without DNS. The kubelet composes the visible set
per namespace (kubelet.go:857-893 getServiceEnvVarMap): the pod's own
namespace wins; the master services ("kubernetes", "kubernetes-ro")
from the master namespace are added when not shadowed.
"""

from __future__ import annotations

from typing import Dict, List

from kubernetes_tpu.api import types as api

# ref: cmd/kubelet masterServiceNamespace default + kubelet.go:846
MASTER_SERVICES = ("kubernetes", "kubernetes-ro")


def _var_name(service_name: str) -> str:
    # ref: envvars.go makeEnvVariableName
    return service_name.upper().replace("-", "_")


def from_services(services: List[api.Service]) -> List[api.EnvVar]:
    """ref: envvars.go FromServices — skips services without a portal IP
    (they have nothing routable to advertise)."""
    out: List[api.EnvVar] = []
    for svc in services:
        portal_ip = svc.spec.portal_ip
        if not portal_ip or portal_ip == "None":
            continue
        prefix = _var_name(svc.metadata.name)
        port = svc.spec.port
        proto = (svc.spec.protocol or api.ProtocolTCP).lower()
        url = f"{proto}://{portal_ip}:{port}"
        port_prefix = f"{prefix}_PORT_{port}_{proto.upper()}"
        out.extend([
            api.EnvVar(name=f"{prefix}_SERVICE_HOST", value=portal_ip),
            api.EnvVar(name=f"{prefix}_SERVICE_PORT", value=str(port)),
            # docker-compatible link variables (envvars.go makeLinkVariables)
            api.EnvVar(name=f"{prefix}_PORT", value=url),
            api.EnvVar(name=port_prefix, value=url),
            api.EnvVar(name=f"{port_prefix}_PROTO", value=proto),
            api.EnvVar(name=f"{port_prefix}_PORT", value=str(port)),
            api.EnvVar(name=f"{port_prefix}_ADDR", value=portal_ip),
        ])
    return out


def visible_services(all_services: List[api.Service], namespace: str,
                     master_ns: str = "default") -> List[api.Service]:
    """The services a pod in `namespace` should see (ref:
    kubelet.go:857-893): every service in its own namespace, plus the
    master services from master_ns unless shadowed by a same-named
    local service."""
    by_name: Dict[str, api.Service] = {}
    for svc in all_services:
        ns = svc.metadata.namespace
        name = svc.metadata.name
        if ns == namespace:
            by_name[name] = svc
        elif ns == master_ns and name in MASTER_SERVICES:
            by_name.setdefault(name, svc)
    return list(by_name.values())
