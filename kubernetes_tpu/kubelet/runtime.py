"""Container runtime seam (ref: pkg/kubelet/dockertools/).

``ContainerRuntime`` is the interface the kubelet drives
(ref: dockertools.DockerInterface — ListContainers/CreateContainer/
StartContainer/StopContainer/InspectContainer/PullImage). ``FakeRuntime``
is the in-memory double (ref: FakeDockerClient,
pkg/kubelet/dockertools/fake_docker_client.go) that also serves as the
"machine" in the multi-node integration harness: it allocates pod IPs and
tracks container lifecycles, and its ``call_log`` records every operation
for assertions.

Containers are named by the reference's convention
``k8s_<container>_<podname>_<namespace>_<uid>_<rand>``
(ref: dockertools/docker.go BuildDockerName/ParseDockerName) so that pod
membership is recoverable from the runtime alone after a kubelet restart.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api

__all__ = ["ContainerRecord", "ContainerRuntime", "FakeRuntime",
           "INFRA_CONTAINER_NAME", "INFRA_IMAGE", "build_container_name",
           "parse_container_name", "pod_full_name"]

# ref: kubelet.go:1020-1030 — the infra ("pause") container that holds the
# pod sandbox. networkContainerName = "POD"; our native equivalent binary
# lives in native/pause.cc.
INFRA_CONTAINER_NAME = "POD"
INFRA_IMAGE = "kubernetes/pause:latest"

_PREFIX = "k8s"


def pod_full_name(pod: api.Pod) -> str:
    """<name>_<namespace> (ref: GetPodFullName, kubelet.go:214)."""
    return f"{pod.metadata.name}_{pod.metadata.namespace or api.NamespaceDefault}"


def build_container_name(pod: api.Pod, container_name: str, attempt: int) -> str:
    """ref: BuildDockerName — rand suffix doubles as the restart counter."""
    return "_".join([_PREFIX, container_name, pod.metadata.name,
                     pod.metadata.namespace or api.NamespaceDefault,
                     pod.metadata.uid, str(attempt)])


def parse_container_name(name: str) -> Optional[Tuple[str, str, str, str, int]]:
    """-> (container_name, pod_name, namespace, pod_uid, attempt) or None."""
    parts = name.split("_")
    if len(parts) != 6 or parts[0] != _PREFIX:
        return None
    try:
        attempt = int(parts[5])
    except ValueError:
        return None
    return parts[1], parts[2], parts[3], parts[4], attempt


@dataclass
class ContainerRecord:
    """What the runtime knows about one container (ref: docker.APIContainers
    + InspectContainer fields the kubelet reads)."""

    id: str = ""
    name: str = ""              # encoded k8s_... name
    image: str = ""
    running: bool = False
    exit_code: int = 0
    created_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    ip: str = ""                # infra containers carry the pod IP

    @property
    def parsed(self):
        return parse_container_name(self.name)


class ContainerRuntime:
    """The kubelet-facing interface; implementations must be thread-safe."""

    def list_containers(self, include_dead: bool = False) -> List[ContainerRecord]:
        raise NotImplementedError

    def create_container(self, pod: api.Pod, container: api.Container,
                         attempt: int) -> str:
        raise NotImplementedError

    def create_infra_container(self, pod: api.Pod) -> str:
        raise NotImplementedError

    def start_container(self, container_id: str) -> None:
        raise NotImplementedError

    def stop_container(self, container_id: str) -> None:
        raise NotImplementedError

    def remove_container(self, container_id: str) -> None:
        raise NotImplementedError

    def inspect_container(self, container_id: str) -> Optional[ContainerRecord]:
        raise NotImplementedError

    def pull_image(self, image: str) -> None:
        raise NotImplementedError

    def list_images(self) -> List[str]:
        raise NotImplementedError

    def remove_image(self, image: str) -> None:
        raise NotImplementedError

    def exec_in_container(self, container_id: str, cmd: List[str]) -> Tuple[int, str]:
        raise NotImplementedError

    def exec_stream_in_container(self, container_id: str, cmd: List[str]):
        """Yield output chunks (bytes) as the command produces them, then
        the final exit code (int) as the last item — the streaming seam the
        WebSocket exec upgrade serves. Default: wrap the blocking exec
        (one chunk); ProcessRuntime streams live."""
        code, output = self.exec_in_container(container_id, cmd)
        if output:
            yield output.encode("utf-8", "replace")
        yield code

    def container_logs(self, container_id: str, tail: int = 0) -> str:
        """ref: dockertools GetKubeletDockerContainerLogs."""
        raise NotImplementedError


class FakeRuntime(ContainerRuntime):
    """In-memory runtime double (ref: FakeDockerClient).

    - ``call_log`` records (op, detail) tuples, like FakeDockerClient.called.
    - ``errors[op]`` injects an exception for the next call of that op
      (ref: FakeDockerClient.Errors map).
    - ``exec_results[(container_name, tuple(cmd))]`` scripts exec probes.
    - pod IPs are allocated from ``ip_base`` per infra container.
    """

    def __init__(self, ip_base: str = "10.88.0."):
        self._lock = threading.RLock()
        self._containers: Dict[str, ContainerRecord] = {}
        self._images: set = set()
        self._id_counter = itertools.count(1)
        self._ip_counter = itertools.count(1)
        self.ip_base = ip_base
        self.call_log: List[tuple] = []
        self.errors: Dict[str, Exception] = {}
        self.exec_results: Dict[tuple, Tuple[int, str]] = {}
        self.logs: Dict[str, str] = {}  # container id -> accumulated output

    # -- helpers ------------------------------------------------------------
    def _called(self, op: str, detail: str = "") -> None:
        self.call_log.append((op, detail))
        err = self.errors.pop(op, None)
        if err is not None:
            raise err

    def containers_for_pod(self, pod_uid: str,
                           include_dead: bool = False) -> List[ContainerRecord]:
        with self._lock:
            out = []
            for c in self._containers.values():
                p = c.parsed
                if p and p[3] == pod_uid and (include_dead or c.running):
                    out.append(c)
            return out

    # -- ContainerRuntime ----------------------------------------------------
    def list_containers(self, include_dead: bool = False) -> List[ContainerRecord]:
        with self._lock:
            self._called("list")
            return [ContainerRecord(**vars(c)) for c in self._containers.values()
                    if include_dead or c.running]

    def create_container(self, pod: api.Pod, container: api.Container,
                         attempt: int) -> str:
        with self._lock:
            self._called("create", container.name)
            if container.image not in self._images:
                raise RuntimeError(f"image not present: {container.image}")
            cid = f"c{next(self._id_counter)}"
            self._containers[cid] = ContainerRecord(
                id=cid, name=build_container_name(pod, container.name, attempt),
                image=container.image, created_at=time.time())
            return cid

    def create_infra_container(self, pod: api.Pod) -> str:
        with self._lock:
            self._called("create_infra", pod_full_name(pod))
            cid = f"c{next(self._id_counter)}"
            self._containers[cid] = ContainerRecord(
                id=cid, name=build_container_name(pod, INFRA_CONTAINER_NAME, 0),
                image=INFRA_IMAGE, created_at=time.time(),
                ip=f"{self.ip_base}{next(self._ip_counter)}")
            return cid

    def start_container(self, container_id: str) -> None:
        with self._lock:
            self._called("start", container_id)
            c = self._containers[container_id]
            c.running = True
            c.started_at = time.time()

    def stop_container(self, container_id: str) -> None:
        with self._lock:
            self._called("stop", container_id)
            c = self._containers.get(container_id)
            if c is not None and c.running:
                c.running = False
                c.finished_at = time.time()

    def remove_container(self, container_id: str) -> None:
        with self._lock:
            self._called("remove", container_id)
            self._containers.pop(container_id, None)

    def inspect_container(self, container_id: str) -> Optional[ContainerRecord]:
        with self._lock:
            c = self._containers.get(container_id)
            return ContainerRecord(**vars(c)) if c else None

    def pull_image(self, image: str) -> None:
        with self._lock:
            self._called("pull", image)
            self._images.add(image)

    def list_images(self) -> List[str]:
        with self._lock:
            return sorted(self._images)

    def remove_image(self, image: str) -> None:
        with self._lock:
            self._called("remove_image", image)
            self._images.discard(image)

    def exec_in_container(self, container_id: str, cmd: List[str]) -> Tuple[int, str]:
        with self._lock:
            self._called("exec", container_id)
            c = self._containers.get(container_id)
            if c is None or not c.running:
                return 1, "container not running"
            p = c.parsed
            key = (p[0] if p else c.name, tuple(cmd))
            return self.exec_results.get(key, (0, ""))

    def container_logs(self, container_id: str, tail: int = 0) -> str:
        with self._lock:
            self._called("logs", container_id)
            text = self.logs.get(container_id, "")
            if tail > 0:
                lines = text.splitlines(keepends=True)
                text = "".join(lines[-tail:])
            return text

    def append_log(self, container_id: str, text: str) -> None:
        """Test convenience: accumulate synthetic container output."""
        with self._lock:
            self.logs[container_id] = self.logs.get(container_id, "") + text

    # -- test conveniences ---------------------------------------------------
    def kill_container_of(self, pod_uid: str, container_name: str,
                          exit_code: int = 137) -> bool:
        """Simulate a container dying out from under the kubelet."""
        with self._lock:
            for c in self._containers.values():
                p = c.parsed
                if p and p[3] == pod_uid and p[0] == container_name and c.running:
                    c.running = False
                    c.exit_code = exit_code
                    c.finished_at = time.time()
                    return True
            return False
