"""Container & image garbage collection
(ref: pkg/kubelet/container_gc.go + image_manager.go).

``ContainerGC`` evicts dead containers by the reference's realContainerGC
policy: keep at most ``max_per_pod_container`` dead instances per
(pod, container) pair, never remove containers younger than ``min_age``,
and cap total dead containers at ``max_containers`` (oldest evicted first).

``ImageManager`` deletes unused images when the disk-usage callable reports
utilization above ``high_threshold_percent``, oldest-unused first, until
below ``low_threshold_percent`` (ref: image_manager.go GarbageCollect).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.kubelet.runtime import ContainerRuntime

__all__ = ["GCPolicy", "ContainerGC", "ImageGCPolicy", "ImageManager"]


@dataclass
class GCPolicy:
    """ref: ContainerGCPolicy (container_gc.go:28-38)."""

    min_age: float = 0.0
    max_per_pod_container: int = 2
    max_containers: int = 100


class ContainerGC:
    def __init__(self, runtime: ContainerRuntime, policy: GCPolicy):
        self.runtime = runtime
        self.policy = policy

    def collect(self, live_uids: Optional[set] = None) -> int:
        """Returns the number of containers removed."""
        now = time.time()
        dead = [r for r in self.runtime.list_containers(include_dead=True)
                if not r.running and r.parsed is not None
                and now - (r.finished_at or r.created_at) >= self.policy.min_age]
        removed = 0
        # group dead containers by (pod uid, container name); newest kept
        groups: Dict[tuple, List] = {}
        for r in dead:
            p = r.parsed
            groups.setdefault((p[3], p[0]), []).append(r)
        survivors = []
        for (uid, cname), records in groups.items():
            records.sort(key=lambda r: r.finished_at or r.created_at, reverse=True)
            keep = self.policy.max_per_pod_container
            if live_uids is not None and uid not in live_uids:
                keep = 0  # pod is gone: its corpses hold no restart history
            for r in records[keep:]:
                self.runtime.remove_container(r.id)
                removed += 1
            survivors.extend(records[:keep])
        # global cap, oldest first (ref: enforceMaxContainers)
        if len(survivors) > self.policy.max_containers:
            survivors.sort(key=lambda r: r.finished_at or r.created_at)
            excess = len(survivors) - self.policy.max_containers
            for r in survivors[:excess]:
                self.runtime.remove_container(r.id)
                removed += 1
        return removed


@dataclass
class ImageGCPolicy:
    """ref: ImageGCPolicy (image_manager.go:28-40)."""

    high_threshold_percent: int = 90
    low_threshold_percent: int = 80


class ImageManager:
    """``disk_usage_percent`` is the cadvisor seam: a callable returning the
    image filesystem utilization (ref: image_manager.go uses cadvisor's
    DockerImagesFsInfo)."""

    def __init__(self, runtime: ContainerRuntime, policy: ImageGCPolicy,
                 disk_usage_percent: Callable[[], float],
                 image_size: Callable[[str], int] = lambda image: 1):
        self.runtime = runtime
        self.policy = policy
        self.disk_usage_percent = disk_usage_percent
        self.image_size = image_size

    def images_in_use(self) -> set:
        used = set()
        for r in self.runtime.list_containers(include_dead=True):
            used.add(r.image)
        return used

    def garbage_collect(self) -> List[str]:
        """Returns the images removed."""
        usage = self.disk_usage_percent()
        if usage < self.policy.high_threshold_percent:
            return []
        used = self.images_in_use()
        candidates = [i for i in self.runtime.list_images() if i not in used]
        removed = []
        for image in candidates:
            if self.disk_usage_percent() <= self.policy.low_threshold_percent:
                break
            self.runtime.remove_image(image)
            removed.append(image)
        return removed
