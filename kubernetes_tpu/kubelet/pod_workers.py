"""Per-pod sync workers (ref: pkg/kubelet/pod_workers.go).

One worker thread per pod UID; updates arriving while a sync is in flight
are coalesced to the latest (ref: podWorkers:34-58 — a buffered channel of
size 1 per pod; managePodLoop:83-112 drains to the freshest update).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from kubernetes_tpu.api import types as api

__all__ = ["PodWorkers"]


class _Worker:
    def __init__(self, sync_fn: Callable[[api.Pod], None], name: str):
        self.sync_fn = sync_fn
        self._cond = threading.Condition()
        self._pending: Optional[api.Pod] = None
        self._closed = False
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._loop, daemon=True, name=name)
        self._thread.start()

    def update(self, pod: api.Pod) -> None:
        with self._cond:
            self._pending = pod  # coalesce: latest wins
            self._idle.clear()   # busy from the caller's perspective now
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._idle.set()
                    self._cond.wait()
                if self._closed and self._pending is None:
                    self._idle.set()
                    return
                pod, self._pending = self._pending, None
                self._idle.clear()
            try:
                self.sync_fn(pod)
            except Exception:
                pass  # crash-only (ref: util.HandleCrash in managePodLoop)

    def wait_idle(self, timeout: float) -> bool:
        return self._idle.wait(timeout)


class PodWorkers:
    """ref: podWorkers — UpdatePod dispatches to the pod's worker,
    ForgetNonExistingPodWorkers reaps workers for deleted pods."""

    def __init__(self, sync_fn: Callable[[api.Pod], None]):
        self.sync_fn = sync_fn
        self._lock = threading.Lock()
        self._workers: Dict[str, _Worker] = {}

    def update_pod(self, pod: api.Pod) -> None:
        uid = pod.metadata.uid or pod.metadata.name
        with self._lock:
            w = self._workers.get(uid)
            if w is None:
                w = _Worker(self.sync_fn, name=f"pod-worker-{pod.metadata.name}")
                self._workers[uid] = w
        w.update(pod)

    def forget_non_existing(self, live_uids: set) -> None:
        with self._lock:
            dead = [uid for uid in self._workers if uid not in live_uids]
            for uid in dead:
                self._workers.pop(uid).close()

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Block until every worker has drained (test/integration helper)."""
        with self._lock:
            workers = list(self._workers.values())
        ok = True
        for w in workers:
            ok = w.wait_idle(timeout) and ok
        return ok

    def stop(self) -> None:
        with self._lock:
            for w in self._workers.values():
                w.close()
            self._workers.clear()
