"""ProcessRuntime — a real container runtime on the ContainerRuntime seam.

The reference's kubelet drives Docker (ref: pkg/kubelet/dockertools/
docker.go, ~2.5k LoC; infra container kubelet.go:1025). This image has no
container engine, so the real runtime runs pods as **local process groups**:

- the pod sandbox is the native ``pause`` binary (native/pause/pause.cc —
  our C++ rebuild of the reference's x86-64 asm pause, third_party/pause/
  pause.asm) started in its own process group as the pod's PID-1 stand-in;
- each container is ``command + args`` spawned in its own process group
  with the container's env/working dir, stdout+stderr streamed to a
  per-container log file (the json-log analog that containerLogs serves);
- stop is TERM-to-process-group, grace period, then KILL — the same
  escalation Docker's StopContainer performs;
- exec runs the command with the container's environment and returns
  (exit_code, combined output) — the /run//exec and exec-probe path.

"Images" are names only: pull records availability (create fails on an
unpulled image, preserving the kubelet's pull-then-create contract) but
nothing is fetched — the process IS the workload. Pods share the host
network namespace, so the pod IP is 127.0.0.1 and HostPort conflicts are
physical, which is exactly what the scheduler's PodFitsPorts models.
"""

from __future__ import annotations

import itertools
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.kubelet.runtime import (
    INFRA_CONTAINER_NAME,
    INFRA_IMAGE,
    ContainerRecord,
    ContainerRuntime,
    build_container_name,
)

__all__ = ["ProcessRuntime", "find_pause_binary", "pause_command"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def find_pause_binary(build_dir: Optional[str] = None) -> Optional[str]:
    """Locate (or build) the native pause binary, falling back to the
    pure-Python sandbox (native/pause/pause.py) when no binary exists
    and the toolchain is unavailable — the flagship runtime must work in
    toolchain-less environments. Returns the sandbox entry path (binary
    or .py script; see pause_command), or None only if even the Python
    fallback is missing."""
    candidates = [
        os.path.join(_REPO_ROOT, "native", "pause", "pause"),
        os.path.join(build_dir, "pause") if build_dir else None,
    ]
    for c in candidates:
        if c and os.path.isfile(c) and os.access(c, os.X_OK):
            return c
    src = os.path.join(_REPO_ROOT, "native", "pause", "pause.cc")
    if build_dir and os.path.isfile(src) and shutil.which("g++"):
        out = os.path.join(build_dir, "pause")
        try:
            os.makedirs(build_dir, exist_ok=True)
            subprocess.run(["g++", "-Os", "-o", out, src],
                           check=True, capture_output=True, timeout=120)
            return out
        except (subprocess.SubprocessError, OSError):
            pass
    fallback = os.path.join(_REPO_ROOT, "native", "pause", "pause.py")
    if os.path.isfile(fallback):
        return fallback
    return None


def pause_command(pause_path: Optional[str]) -> Optional[list]:
    """argv for the sandbox holder: the native binary directly, or the
    Python fallback through this interpreter."""
    if pause_path is None:
        return None
    if pause_path.endswith(".py"):
        return [sys.executable, pause_path]
    return [pause_path]


class _Proc:
    """Book-keeping for one spawned container."""

    def __init__(self, record: ContainerRecord, argv: List[str],
                 env: Dict[str, str], cwd: str, log_path: str):
        self.record = record
        self.argv = argv
        self.env = env
        self.cwd = cwd
        self.log_path = log_path
        self.popen: Optional[subprocess.Popen] = None
        self.stopping = False     # runtime-initiated stop in progress
        self.respawns = 0         # spawn-kill heals (see _refresh)


class ProcessRuntime(ContainerRuntime):
    """Real local-process runtime behind the kubelet's runtime seam."""

    def __init__(self, root_dir: str, pause_binary: Optional[str] = None,
                 stop_grace_s: float = 3.0):
        self.root_dir = root_dir
        self.log_dir = os.path.join(root_dir, "containers")
        os.makedirs(self.log_dir, exist_ok=True)
        self.pause_binary = pause_binary or find_pause_binary(
            build_dir=os.path.join(root_dir, "bin"))
        # argv the sandbox holder is spawned with (binary, or the Python
        # fallback through sys.executable); identity checks compare argv
        # against this list
        self.pause_cmd = pause_command(self.pause_binary)
        self.stop_grace_s = stop_grace_s
        self._lock = threading.RLock()
        self._procs: Dict[str, _Proc] = {}
        self._images: set = set()
        self._id_counter = itertools.count(1)

    # spawn-kill hardening: some sandboxed environments deliver a stray
    # SIGTERM/SIGKILL to freshly-spawned session leaders (observed in this
    # image: ~50% of new sessions TERM'd within ~1ms of exec, before even a
    # C signal handler can install). A container that died from an external
    # signal this quickly, produced no output, and was not stopped by us is
    # a spawn casualty, not a workload decision — respawn it transparently.
    SPAWN_GUARD_S = 0.2
    SPAWN_RETRIES = 3

    # -- helpers ------------------------------------------------------------
    def _refresh(self, p: _Proc) -> None:
        """Reap and update running state from the real process."""
        if p.popen is None or not p.record.running:
            return
        rc = p.popen.poll()
        if rc is None:
            return
        if (rc in (-signal.SIGTERM, -signal.SIGKILL)
                and not p.stopping
                and p.respawns < self.SPAWN_RETRIES
                and time.time() - p.record.started_at < self.SPAWN_GUARD_S
                and self._log_size(p) == 0):
            p.respawns += 1
            try:
                self._spawn(p)
                return  # still running from the caller's point of view
            except RuntimeError:
                pass
        p.record.running = False
        # children killed by signal surface negative returncodes;
        # docker-style exit codes are 128+signum
        p.record.exit_code = rc if rc >= 0 else 128 - rc
        p.record.finished_at = time.time()

    @staticmethod
    def _log_size(p: _Proc) -> int:
        try:
            return os.path.getsize(p.log_path)
        except OSError:
            return 0

    def _spawn(self, p: _Proc) -> None:
        logf = open(p.log_path, "ab")
        # pause understands the blocked-TERM handshake: it discards one
        # pending stray TERM after installing handlers (pause.cc), so the
        # sandbox holder survives environments that TERM fresh processes.
        # Arbitrary workloads can't be spawned with TERM blocked (most
        # never unblock, which would break graceful stop); they rely on
        # the _refresh spawn-kill heal instead.
        block_term = p.argv == self.pause_cmd
        # Own process group so stop() can killpg the whole container.
        # A fresh pgid within the SAME session — NOT setsid: sandboxed
        # environments may reap processes that escape the supervisor's
        # session. On py3.11+ Popen's process_group=0 does this post-fork
        # without preexec_fn (which CPython documents as unsafe with
        # threads, and the kubelet spawns from many); preexec only carries
        # the pause sandbox's TERM-block handshake there. py3.10 has no
        # process_group kwarg, so the pgid move rides preexec_fn too.
        kwargs = {}
        preexec = None
        if sys.version_info >= (3, 11):
            kwargs["process_group"] = 0
            if block_term:
                def preexec():
                    signal.pthread_sigmask(signal.SIG_BLOCK,
                                           {signal.SIGTERM, signal.SIGINT})
        else:
            def preexec():
                os.setpgid(0, 0)
                if block_term:
                    signal.pthread_sigmask(signal.SIG_BLOCK,
                                           {signal.SIGTERM, signal.SIGINT})
        try:
            p.popen = subprocess.Popen(
                p.argv, stdout=logf, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL, env=p.env, cwd=p.cwd,
                preexec_fn=preexec, **kwargs)
        except OSError as e:
            logf.write(f"start failed: {e}\n".encode())
            logf.close()
            raise RuntimeError(f"cannot start {p.argv[0]!r}: {e}")
        logf.close()  # child holds its own fd
        p.record.running = True
        p.record.started_at = time.time()

    def _snapshot(self, p: _Proc) -> ContainerRecord:
        self._refresh(p)
        return ContainerRecord(**vars(p.record))

    def containers_for_pod(self, pod_uid: str,
                           include_dead: bool = False) -> List[ContainerRecord]:
        with self._lock:
            out = []
            for p in self._procs.values():
                parsed = p.record.parsed
                self._refresh(p)
                if parsed and parsed[3] == pod_uid and \
                        (include_dead or p.record.running):
                    out.append(ContainerRecord(**vars(p.record)))
            return out

    # -- ContainerRuntime ----------------------------------------------------
    def list_containers(self, include_dead: bool = False) -> List[ContainerRecord]:
        with self._lock:
            out = []
            for p in self._procs.values():
                self._refresh(p)
                if include_dead or p.record.running:
                    out.append(ContainerRecord(**vars(p.record)))
            return out

    def create_container(self, pod: api.Pod, container: api.Container,
                         attempt: int) -> str:
        with self._lock:
            if container.image not in self._images:
                raise RuntimeError(f"image not present: {container.image}")
            argv = list(container.command) + list(container.args)
            if not argv:
                # no entrypoint metadata without a real image — hold the
                # slot with a pause process so lifecycle still works
                if self.pause_binary is None:
                    raise RuntimeError(
                        f"container {container.name!r} has no command and "
                        "no pause binary is available")
                argv = list(self.pause_cmd)
            cid = f"p{next(self._id_counter)}"
            env = dict(os.environ)
            for e in container.env:
                env[e.name] = e.value
            record = ContainerRecord(
                id=cid,
                name=build_container_name(pod, container.name, attempt),
                image=container.image, created_at=time.time())
            self._procs[cid] = _Proc(
                record, argv, env, container.working_dir or self.root_dir,
                os.path.join(self.log_dir, f"{cid}.log"))
            return cid

    def create_infra_container(self, pod: api.Pod) -> str:
        with self._lock:
            if self.pause_binary is None:
                raise RuntimeError(
                    "no pause binary: build native/pause (make -C native/pause) "
                    "or install g++")
            cid = f"p{next(self._id_counter)}"
            record = ContainerRecord(
                id=cid,
                name=build_container_name(pod, INFRA_CONTAINER_NAME, 0),
                image=INFRA_IMAGE, created_at=time.time(),
                # host-network model: every pod is reachable on loopback,
                # so HTTP/TCP probes and the service proxy hit real sockets
                ip="127.0.0.1")
            self._procs[cid] = _Proc(
                record, list(self.pause_cmd), dict(os.environ), self.root_dir,
                os.path.join(self.log_dir, f"{cid}.log"))
            return cid

    def start_container(self, container_id: str) -> None:
        with self._lock:
            p = self._procs[container_id]
            if p.record.running:
                return
            p.stopping = False
            self._spawn(p)

    def stop_container(self, container_id: str) -> None:
        with self._lock:
            p = self._procs.get(container_id)
            if p is None or p.popen is None:
                return
            p.stopping = True
            self._refresh(p)
            if not p.record.running:
                return
            pgid = p.popen.pid
            is_pause = p.argv == self.pause_cmd
        # TERM -> grace -> KILL outside the lock (the wait can take seconds).
        # For the pause sandbox only, TERM is re-sent every 0.5s through the
        # grace period: pause may classify one early TERM as a spawn-kill
        # stray and discard it (native/pause/pause.cc), so a single shot
        # could wedge a graceful stop into the KILL path. Ordinary workloads
        # get the Docker-style single TERM — some tools treat a second
        # signal as "force quit now", which would cut their grace short.
        deadline = time.monotonic() + self.stop_grace_s
        terminated = False
        while True:
            try:
                os.killpg(pgid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                p.popen.wait(timeout=min(0.5, remaining) if is_pause
                             else remaining)
                terminated = True
                break
            except subprocess.TimeoutExpired:
                if not is_pause:
                    break
                continue
        if not terminated and p.popen.poll() is None:
            try:
                os.killpg(pgid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            p.popen.wait(timeout=5)
        with self._lock:
            self._refresh(p)

    def remove_container(self, container_id: str) -> None:
        self.stop_container(container_id)
        with self._lock:
            p = self._procs.pop(container_id, None)
            if p is not None:
                try:
                    os.unlink(p.log_path)
                except OSError:
                    pass

    def inspect_container(self, container_id: str) -> Optional[ContainerRecord]:
        with self._lock:
            p = self._procs.get(container_id)
            return self._snapshot(p) if p else None

    def pull_image(self, image: str) -> None:
        with self._lock:
            self._images.add(image)

    def list_images(self) -> List[str]:
        with self._lock:
            return sorted(self._images)

    def remove_image(self, image: str) -> None:
        with self._lock:
            self._images.discard(image)

    def group_stats(self, container_id: str):
        """(cpu_seconds, rss_bytes) summed over the container's whole
        process group via /proc, or None when the group is gone — each
        container IS a process group (spawned with a fresh pgid, see
        _spawn), so
        pgrp matching gives the cgroup-equivalent accounting cAdvisor
        would report, including forked children."""
        with self._lock:
            p = self._procs.get(container_id)
            if p is None or p.popen is None:
                return None
            self._refresh(p)
            if not p.record.running:
                return None
            pgid = p.popen.pid
        try:
            hz = float(os.sysconf("SC_CLK_TCK"))
        except (ValueError, OSError):
            hz = 100.0
        cpu = 0.0
        rss = 0
        found = False
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    # fields after the parenthesised comm (which may hold
                    # spaces): state, ppid, pgrp, ... utime@11, stime@12
                    rest = f.read().rpartition(")")[2].split()
                if int(rest[2]) != pgid:
                    continue
                found = True
                cpu += (int(rest[11]) + int(rest[12])) / hz
                with open(f"/proc/{entry}/status") as f:
                    for line in f:
                        if line.startswith("VmRSS:"):
                            rss += int(line.split()[1]) * 1024
                            break
            except (OSError, ValueError, IndexError):
                continue  # raced with an exiting group member
        return (cpu, rss) if found else None

    def _exec_target(self, container_id: str):
        """(env, cwd) for a live container, or an error string — shared
        preamble of both exec paths; callers never hold the lock across
        their IO."""
        with self._lock:
            p = self._procs.get(container_id)
            if p is None:
                return None, "no such container"
            self._refresh(p)
            if not p.record.running:
                return None, "container not running"
            return (dict(p.env), p.cwd), ""

    def exec_in_container(self, container_id: str, cmd: List[str]) -> Tuple[int, str]:
        target, err = self._exec_target(container_id)
        if target is None:
            return 1, err
        env, cwd = target
        try:
            r = subprocess.run(cmd, env=env, cwd=cwd, timeout=15,
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT,
                               stdin=subprocess.DEVNULL)
            return r.returncode, r.stdout.decode("utf-8", "replace")
        except subprocess.TimeoutExpired:
            return 124, "exec timed out"
        except OSError as e:
            return 126, f"exec failed: {e}"

    def exec_stream_in_container(self, container_id: str, cmd: List[str]):
        """Live-stream the command's combined output, then the exit code —
        the WebSocket exec path. The process runs with the container's
        environment exactly like exec_in_container. Never yields while
        holding the runtime lock (the consumer's socket write can stall
        arbitrarily), and an abandoned stream kills + reaps the child."""
        target, err = self._exec_target(container_id)
        if target is None:
            yield err.encode()
            yield 1
            return
        env, cwd = target
        try:
            proc = subprocess.Popen(cmd, env=env, cwd=cwd,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT,
                                    stdin=subprocess.DEVNULL)
        except (OSError, ValueError) as e:  # ValueError: NUL in argv etc.
            yield f"exec failed: {e}".encode()
            yield 126
            return
        try:
            assert proc.stdout is not None
            while True:
                chunk = proc.stdout.read1(65536)
                if not chunk:
                    break
                yield chunk
            yield proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            yield 124
        finally:
            # normal exit, timeout, or the consumer abandoning the
            # generator (GeneratorExit): no orphans, no zombies
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
            proc.stdout.close()

    def container_logs(self, container_id: str, tail: int = 0) -> str:
        with self._lock:
            p = self._procs.get(container_id)
            if p is None:
                return ""
            log_path = p.log_path
        try:
            with open(log_path, "r", errors="replace") as f:
                text = f.read()
        except OSError:
            return ""
        if tail > 0:
            lines = text.splitlines(keepends=True)
            text = "".join(lines[-tail:])
        return text

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        """Stop every process (harness teardown)."""
        for cid in list(self._procs):
            try:
                self.stop_container(cid)
            except Exception:
                pass
