"""Embedded web UI (ref: pkg/ui/datafile.go — go-bindata-embedded static
assets served at /static/; source under www/).

``asset(path)`` returns (bytes, content_type) for an embedded file; the
apiserver mounts the set at /ui/. The dashboard is a single self-contained
page polling the JSON API — the spiritual successor of www/app's cluster
view, small enough to embed as the reference embeds its build output.
"""

from kubernetes_tpu.ui.datafile import ASSETS, asset  # noqa: F401
