"""Generic declarative per-resource storage.

Rebuild of the reference's ``etcdgeneric.Etcd`` + ``rest.Storage`` pattern
(ref: pkg/registry/generic/etcd/etcd.go:52-92 and pkg/api/rest/rest.go:34-151):
one generic registry parameterized by object type, key layout, create/update
strategies, and an attribute function for label/field selection. Every
resource (pods, services, nodes, ...) is an instance of this class plus a
small strategy — exactly the declarative shape of the reference.
"""

from __future__ import annotations

import itertools
import random
import string
import threading
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import validation
from kubernetes_tpu.api.fields import FieldSelector
from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.api.meta import accessor
from kubernetes_tpu.storage.helper import StoreHelper
from kubernetes_tpu.util import tracing

__all__ = ["Context", "Strategy", "GenericRegistry", "default_attr_func"]

# UID generation: one urandom-backed prefix per process + a counter.
# uuid.uuid4() pays a 16-byte urandom syscall per object (~0.1ms of the
# per-pod churn budget); uniqueness needs randomness once per process,
# not per object. uid is an opaque string (ref: docs/identifiers.md —
# "unique in space and time"), so the shape need not be RFC 4122.
_UID_NODE = uuid.uuid4().hex[:20]
_UID_SEQ = itertools.count(1)


def _next_uid() -> str:
    return f"{_UID_NODE}-{next(_UID_SEQ):012x}"


@dataclass
class Context:
    """Request context (ref: pkg/api/context.go): namespace + caller identity."""

    namespace: str = ""
    user: Optional[Any] = None

    def with_namespace(self, ns: str) -> "Context":
        return Context(namespace=ns, user=self.user)


class Strategy:
    """Create/update strategy (ref: pkg/api/rest/{create,update}.go
    RESTCreateStrategy / RESTUpdateStrategy)."""

    kind = "Object"
    namespaced = True
    allow_create_on_update = False

    def prepare_for_create(self, ctx: Context, obj: Any) -> None:
        """Mutate obj before validation/storage (clear status, defaults)."""

    def validate(self, ctx: Context, obj: Any) -> List[Exception]:
        return []

    def prepare_for_update(self, ctx: Context, new: Any, old: Any) -> None:
        pass

    def validate_update(self, ctx: Context, new: Any, old: Any) -> List[Exception]:
        return []


def default_attr_func(obj: Any) -> Tuple[Dict[str, str], Dict[str, str]]:
    """Default label/field attributes for selection: labels + metadata.name."""
    return accessor.labels(obj), {"metadata.name": accessor.name(obj)}


class GenericRegistry:
    """One resource's storage logic (ref: etcdgeneric.Etcd).

    Declarative knobs mirror the reference's struct fields: obj_type/list_type
    (NewFunc/NewListFunc), prefix (KeyRootFunc/KeyFunc), strategy
    (Create/UpdateStrategy), ttl_func (TTLFunc), attr_func (PredicateFunc
    attributes).
    """

    def __init__(self, helper: StoreHelper, prefix: str, obj_type: Type,
                 list_type: Type, strategy: Strategy,
                 attr_func: Callable = default_attr_func,
                 ttl_func: Optional[Callable[[Any], Optional[float]]] = None):
        self.helper = helper
        self.prefix = prefix.rstrip("/")
        self.obj_type = obj_type
        self.list_type = list_type
        self.strategy = strategy
        self.attr_func = attr_func
        self.ttl_func = ttl_func
        self.kind = strategy.kind
        # (namespace, name, resourceVersion) -> attr_func result. A stored
        # revision's selectable attributes are immutable, and watch fan-out
        # evaluates every watcher's selector against the same revision —
        # N watchers pay one attr build instead of N. Bounded FIFO.
        self._attr_cache: "OrderedDict" = OrderedDict()
        self._attr_lock = threading.Lock()

    # -- keys ---------------------------------------------------------------
    def key_root(self, ctx: Context) -> str:
        if self.strategy.namespaced and ctx.namespace:
            return f"{self.prefix}/{ctx.namespace}"
        return self.prefix

    def key(self, ctx: Context, name: str) -> str:
        if not name:
            raise errors.new_bad_request("name is required")
        if self.strategy.namespaced:
            if not ctx.namespace:
                raise errors.new_bad_request(
                    f"namespace is required for {self.kind}")
            return f"{self.prefix}/{ctx.namespace}/{name}"
        return f"{self.prefix}/{name}"

    # -- verbs (ref: rest.Storage verb interfaces) --------------------------
    def new(self) -> Any:
        return self.obj_type()

    def new_list(self) -> Any:
        return self.list_type()

    def create(self, ctx: Context, obj: Any) -> Any:
        """ref: etcd.go Create + rest.BeforeCreate (pkg/api/rest/create.go)."""
        m = accessor.metadata(obj)
        if self.strategy.namespaced:
            if m.namespace and ctx.namespace and m.namespace != ctx.namespace:
                raise errors.new_bad_request(
                    f"namespace {m.namespace!r} does not match context {ctx.namespace!r}")
            m.namespace = m.namespace or ctx.namespace or api.NamespaceDefault
        if m.generate_name and not m.name:
            suffix = "".join(random.choices(string.ascii_lowercase + string.digits, k=5))
            m.name = m.generate_name + suffix
        if not m.uid:
            m.uid = _next_uid()
        if m.creation_timestamp is None:
            import datetime
            m.creation_timestamp = datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)
        m.resource_version = ""
        self.strategy.prepare_for_create(ctx, obj)
        errs = self.strategy.validate(ctx, obj)
        if errs:
            raise errors.new_invalid(self.kind, m.name, errs)
        ttl = self.ttl_func(obj) if self.ttl_func else None
        # store-write leg of the request's trace; child_span records only
        # when this thread is inside a traced request (untraced churn
        # creates stay out of the span ring)
        with tracing.child_span("store.create", kind=self.kind):
            return self.helper.create_obj(
                self.key(ctx.with_namespace(m.namespace), m.name),
                obj, ttl=ttl)

    def get(self, ctx: Context, name: str) -> Any:
        return self.helper.extract_obj(self.key(ctx, name), self.kind, name)

    def list(self, ctx: Context, label_selector: Optional[Selector] = None,
             field_selector: Optional[FieldSelector] = None) -> Any:
        lst = self.helper.extract_to_list(self.key_root(ctx), self.list_type)
        if label_selector or field_selector:
            lst.items = [o for o in lst.items
                         if self._matches(o, label_selector, field_selector)]
        return lst

    def update(self, ctx: Context, obj: Any) -> Any:
        """ref: etcd.go Update + rest.BeforeUpdate."""
        m = accessor.metadata(obj)
        if (self.strategy.namespaced and m.namespace and ctx.namespace
                and m.namespace != ctx.namespace):
            raise errors.new_bad_request(
                f"namespace {m.namespace!r} does not match context {ctx.namespace!r}")
        key = self.key(ctx, m.name)
        try:
            old = self.helper.extract_obj(key, self.kind, m.name)
        except errors.StatusError as e:
            if errors.is_not_found(e) and self.strategy.allow_create_on_update:
                return self.create(ctx, obj)
            raise
        m.uid = accessor.metadata(old).uid
        m.creation_timestamp = accessor.metadata(old).creation_timestamp
        self.strategy.prepare_for_update(ctx, obj, old)
        errs = self.strategy.validate_update(ctx, obj, old)
        if errs:
            raise errors.new_invalid(self.kind, m.name, errs)
        if not m.resource_version:
            # unconditional update: CAS against what we just read, retrying is
            # the caller's job on conflict (matches reference SetObj semantics)
            m.resource_version = accessor.resource_version(old)
        ttl = self.ttl_func(obj) if self.ttl_func else None
        with tracing.child_span("store.update", kind=self.kind):
            return self.helper.set_obj(key, obj, ttl=ttl)

    def delete(self, ctx: Context, name: str) -> api.Status:
        self.helper.delete_obj(self.key(ctx, name), self.kind, name)
        return api.Status(status=api.StatusSuccess)

    def watch(self, ctx: Context, label_selector: Optional[Selector] = None,
              field_selector: Optional[FieldSelector] = None,
              resource_version: str = "") -> watchpkg.Watcher:
        return self.helper.watch(
            self.key_root(ctx), resource_version=resource_version,
            filter_fn=lambda o: self._matches(o, label_selector, field_selector))

    def watch_raw(self, ctx: Context,
                  label_selector: Optional[Selector] = None,
                  field_selector: Optional[FieldSelector] = None,
                  resource_version: str = "",
                  lag_limit: Optional[int] = None):
        """Raw watch + translate for the HTTP fan-out path: returns
        ``(watcher, translate)`` where ``watcher`` streams StoreEvents on a
        bounded queue and ``translate(ev)`` maps one to the API-level watch
        Event (None = filtered out) via the shared decode/attr caches. The
        caller's own thread drives translation — no per-watcher pump."""
        if label_selector is not None and label_selector.empty():
            label_selector = None
        if field_selector is not None and not field_selector.requirements:
            field_selector = None
        raw = self.helper.watch_raw(self.key_root(ctx), resource_version,
                                    lag_limit=lag_limit)
        if label_selector is None and field_selector is None:
            # unfiltered watchers (the wide-fan-out population) take the
            # decode-free fast path: (type, rv, obj_thunk) tuples
            return raw, self.helper.translate_event_fast
        filt = lambda o: self._matches(o, label_selector, field_selector)
        return raw, (lambda ev: self.helper.translate_event(ev, filt))

    # -- selection ----------------------------------------------------------
    _ATTR_CACHE_MAX = 8192

    def _attrs(self, obj: Any) -> Tuple[Dict[str, str], Dict[str, str]]:
        m = getattr(obj, "metadata", None)
        rv = getattr(m, "resource_version", "") if m is not None else ""
        name = getattr(m, "name", "") if m is not None else ""
        if not rv or not name:
            return self.attr_func(obj)
        key = (getattr(m, "namespace", ""), name, rv)
        with self._attr_lock:
            got = self._attr_cache.get(key)
        if got is None:
            got = self.attr_func(obj)
            with self._attr_lock:
                self._attr_cache[key] = got
                while len(self._attr_cache) > self._ATTR_CACHE_MAX:
                    self._attr_cache.popitem(last=False)
        return got

    def _matches(self, obj: Any, label_selector: Optional[Selector],
                 field_selector: Optional[FieldSelector]) -> bool:
        lbls, flds = self._attrs(obj)
        if label_selector is not None and not label_selector.matches(lbls):
            return False
        if field_selector is not None and not field_selector.matches(flds):
            return False
        return True
